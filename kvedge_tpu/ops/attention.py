"""Causal flash attention as a Pallas TPU kernel.

Why: naive attention materializes the [T, T] score matrix per (batch, head)
— at T=512 that dominated the flagship's HBM footprint (an observed OOM at
batch 64 on one v5e chip before remat), and at T=8192 the naive forward was
measured 26x slower than this kernel on v5e (HBM thrash). The kernel
streams K/V blocks with an online softmax (running max + denominator), so
peak VMEM is O(block²) regardless of context length.

Structure (canonical TPU flash layout): grid = (batch*heads, q_blocks,
k_blocks) with the k dimension innermost. TPU grids execute sequentially,
so VMEM scratch (running max / denominator / accumulator) carries state
across the k iterations of one q block; the output block is written on the
last k step. Causal blocks above the diagonal are skipped with ``pl.when``
(no wasted MXU work). Matmuls request ``preferred_element_type=float32`` so
the MXU accumulates in fp32.

Backward: custom VJP from the saved log-sum-exp. The backward recomputes
scores with dense per-layer matmuls (acceptable under the model's per-layer
remat, where only one layer's [T, T] is live at a time); a blockwise Pallas
backward is the next refinement.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK = 128


def pick_block(seq: int) -> int:
    """Largest hardware-aligned block that divides ``seq``.

    Raises (at trace time, with an actionable message) when no aligned
    block divides the sequence, rather than silently running a different
    attention path than the one configured.
    """
    for block in (DEFAULT_BLOCK, 64, 32, 16, 8):
        if seq % block == 0:
            return block
    raise ValueError(
        f"flash attention needs the sequence length to be divisible by 8, "
        f"got {seq} (training slices [B, S+1] batches to S tokens — choose "
        "S divisible by 8)"
    )


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scratch, l_scratch, acc_scratch, *, block: int,
                scale: float):
    """One (bh, qi, ki) step: fold k block ki into q block qi's running state.

    q_ref: [1, block, dh]; k_ref/v_ref: [1, block, dh];
    o_ref: [1, block, dh]; lse_ref: [1, block, 1] (trailing singleton keeps
    the block's last two dims on the (8, 128) tiling rule);
    scratches: m/l [block, 1], acc [block, dh] — persist across the
    sequential k grid dimension.
    """
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _():
        m_scratch[:] = jnp.full_like(m_scratch, -jnp.inf)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    # Causal: q block qi sees k blocks 0..qi only (block_q == block_k).
    @pl.when(ki <= qi)
    def _():
        q = q_ref[0].astype(jnp.float32) * scale  # [bq, dh]
        kj = k_ref[0].astype(jnp.float32)
        vj = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, kj,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bq, bk]
        row_ids = qi * block + jax.lax.broadcasted_iota(
            jnp.int32, (block, block), 0
        )
        col_ids = ki * block + jax.lax.broadcasted_iota(
            jnp.int32, (block, block), 1
        )
        s = jnp.where(col_ids <= row_ids, s, -jnp.inf)

        m_prev = m_scratch[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        correction = jnp.exp(m_prev - m_new)
        m_scratch[:] = m_new
        l_scratch[:] = l_scratch[:] * correction + jnp.sum(
            p, axis=-1, keepdims=True
        )
        acc_scratch[:] = acc_scratch[:] * correction + jax.lax.dot_general(
            p, vj,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ki == nk - 1)
    def _():
        o_ref[0] = (acc_scratch[:] / l_scratch[:]).astype(o_ref.dtype)
        lse_ref[0] = m_scratch[:] + jnp.log(l_scratch[:])


def _flash_fwd_raw(q, k, v, *, block: int, interpret: bool):
    """q, k, v: [BH, T, dh] -> (out [BH, T, dh], lse [BH, T])."""
    bh, seq, dh = q.shape
    if seq % block:
        raise ValueError(f"seq {seq} must be a multiple of block {block}")
    scale = dh ** -0.5
    nblk = seq // block
    grid = (bh, nblk, nblk)
    kernel = functools.partial(_fwd_kernel, block=block, scale=scale)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block, dh), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block, dh), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block, dh), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block, dh), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, seq, dh), q.dtype),
            jax.ShapeDtypeStruct((bh, seq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block, 1), jnp.float32),
            pltpu.VMEM((block, 1), jnp.float32),
            pltpu.VMEM((block, dh), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse[..., 0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention(q, k, v, block: int = DEFAULT_BLOCK,
                    interpret: bool = False):
    """Causal flash attention. q, k, v: [BH, T, dh] -> [BH, T, dh].

    ``interpret=True`` runs the kernel in the Pallas interpreter (for CPU
    tests); pass post-rotary, unscaled q (scaling happens inside).
    """
    out, _ = _flash_fwd_raw(q, k, v, block=block, interpret=interpret)
    return out


def _flash_fwd_vjp(q, k, v, block, interpret):
    out, lse = _flash_fwd_raw(q, k, v, block=block, interpret=interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd_vjp(block, interpret, residuals, g):
    """Dense recompute backward from the saved LSE (per-layer under remat)."""
    del block, interpret
    q, k, v, out, lse = residuals
    dh = q.shape[-1]
    scale = dh ** -0.5
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    do = g.astype(jnp.float32)
    seq = q.shape[1]

    s = jnp.einsum("bqd,bkd->bqk", qf * scale, kf)
    causal = jnp.tril(jnp.ones((seq, seq), jnp.bool_))
    s = jnp.where(causal[None], s, -jnp.inf)
    p = jnp.exp(s - lse[:, :, None])  # softmax probabilities, exactly

    dv = jnp.einsum("bqk,bqd->bkd", p, do)
    dp = jnp.einsum("bqd,bkd->bqk", do, vf)
    delta = jnp.sum(do * out.astype(jnp.float32), axis=-1, keepdims=True)
    ds = p * (dp - delta)
    dq = jnp.einsum("bqk,bkd->bqd", ds, kf) * scale
    dk = jnp.einsum("bqk,bqd->bkd", ds, qf) * scale
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


flash_attention.defvjp(_flash_fwd_vjp, _flash_bwd_vjp)
