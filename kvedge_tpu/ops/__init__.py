"""TPU kernels (Pallas) for the payload's hot ops.

The reference has no compute kernels of any kind (SURVEY.md §2); these exist
to make the *payload* slot genuinely TPU-native: where XLA's automatic
fusion isn't enough (attention's [T, T] score materialization), a Pallas
kernel takes over.
"""

from kvedge_tpu.ops.attention import flash_attention
from kvedge_tpu.ops.xent import fused_xent

__all__ = ["flash_attention", "fused_xent"]
