"""Fused softmax-cross-entropy readout as Pallas TPU kernels.

Why: the flagship's training loss is dominated in HBM terms by the logits.
``tied_readout`` materializes ``[B*T, V]`` fp32 (at the bench shape,
32768 x 32000 x 4B = 4.2 GB), and the loss + its backward then stream that
tensor several times (logsumexp reads, the softmax-minus-onehot cotangent,
and both readout matmul transposes). Measured on v5e this kept the train
step ~35% MFU while the sweep showed throughput flat in batch — a
bandwidth ceiling, not a compute one.

This module applies the flash-attention trick to the vocab axis instead:
logits are computed blockwise (``[bn, bv]`` tiles live only in VMEM), an
online max/sum accumulates the logsumexp, and the target logit is
extracted with a masked reduce as its block streams past. The backward
recomputes each block's probabilities from the saved LSE (numerically
identical to the forward's final state) and accumulates ``dx`` and
``d_embedding`` in VMEM scratch — so neither pass ever materializes a
``[*, V]`` tensor in HBM. Matmul operands stay bf16 (MXU rate) with fp32
accumulation, matching ``tied_readout``'s
``preferred_element_type=float32`` contract.

No reference counterpart: levi106/kvedge has no compute path at all
(SURVEY.md §0); this is TPU-first optimization of the payload this repo
adds.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Vocab-block preference: bigger tiles amortize grid overhead; 2048 x fp32
# rows start pressuring the ~16 MB VMEM scope once the embedding block and
# double-buffering are counted (same budget reasoning as ops/attention.py).
_VOCAB_BLOCKS = (1280, 1024, 512, 256, 128)
_ROW_BLOCKS = (1024, 512, 256, 128, 64, 32, 16, 8)

# Row-block ceilings, from the [bn, bv] fp32 intermediates each kernel
# holds live at once (s / p / ds are ~bn*bv*4B each): the forward keeps
# two, the backward kernels keep three plus a [*, D] accumulator —
# bn=1024 in backward was measured to exceed the 16 MB scoped-vmem limit
# by 668 KB on v5e at bv=1280, D=512.
FWD_MAX_ROWS = 512
BWD_MAX_ROWS = 256


def pick_vocab_block(vocab: int) -> int:
    """Largest lane-aligned vocab block that divides ``vocab``."""
    for block in _VOCAB_BLOCKS:
        if vocab % block == 0:
            return block
    raise ValueError(
        f"fused cross-entropy needs vocab divisible by 128, got {vocab} "
        "(pad the vocabulary or disable fused_xent)"
    )


def pick_row_block(rows: int, max_block: int = 1024) -> int:
    """Largest sublane-aligned row block <= max_block dividing ``rows``."""
    for block in _ROW_BLOCKS:
        if block <= max_block and rows % block == 0:
            return block
    raise ValueError(
        f"fused cross-entropy needs batch*seq divisible by 8, got {rows}"
    )


def _fwd_kernel(x_ref, e_ref, tgt_ref, lse_ref, tlogit_ref,
                m_scr, l_scr, t_scr, *, bv: int):
    """One (ni, vi) step: fold vocab block vi into row block ni's state.

    x_ref: [bn, D] bf16; e_ref: [bv, D] bf16; tgt_ref: [bn, 1] int32;
    lse_ref/tlogit_ref: [bn, 1] f32; scratches m/l/t: [bn, 1] f32,
    persisting across the sequential vocab grid dimension.
    """
    vi = pl.program_id(1)
    nv = pl.num_programs(1)

    @pl.when(vi == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, -jnp.inf)
        l_scr[:] = jnp.zeros_like(l_scr)
        t_scr[:] = jnp.zeros_like(t_scr)

    s = jax.lax.dot_general(
        x_ref[...], e_ref[...],
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [bn, bv]

    m_prev = m_scr[:]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    l_scr[:] = l_scr[:] * jnp.exp(m_prev - m_new) + jnp.sum(
        jnp.exp(s - m_new), axis=-1, keepdims=True
    )
    m_scr[:] = m_new

    # Each row's target id falls in exactly one vocab block, so summing the
    # masked scores across blocks yields precisely that one logit.
    cols = vi * bv + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    t_scr[:] += jnp.sum(
        jnp.where(cols == tgt_ref[...], s, 0.0), axis=-1, keepdims=True
    )

    @pl.when(vi == nv - 1)
    def _():
        lse_ref[...] = m_scr[:] + jnp.log(l_scr[:])
        tlogit_ref[...] = t_scr[:]


def _dx_kernel(x_ref, e_ref, tgt_ref, lse_ref, g_ref, dx_ref, acc_scr,
               *, bv: int):
    """One (ni, vi) step: fold vocab block vi into row block ni's dx.

    dx_i = g_i * (softmax_i @ E - E[target_i]); both terms stream through
    the same ``ds = g * (p - onehot)`` cotangent tile.
    """
    vi = pl.program_id(1)
    nv = pl.num_programs(1)

    @pl.when(vi == 0)
    def _():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    s = jax.lax.dot_general(
        x_ref[...], e_ref[...],
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    p = jnp.exp(s - lse_ref[...])  # exact recompute from the saved LSE
    cols = vi * bv + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    ds = (p - jnp.where(cols == tgt_ref[...], 1.0, 0.0)) * g_ref[...]
    acc_scr[:] += jax.lax.dot_general(
        ds.astype(e_ref.dtype), e_ref[...],
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(vi == nv - 1)
    def _():
        dx_ref[...] = acc_scr[:].astype(dx_ref.dtype)


def _de_kernel(x_ref, e_ref, tgt_ref, lse_ref, g_ref, de_ref, acc_scr,
               *, bv: int):
    """One (vi, ni) step: fold row block ni into vocab block vi's dE.

    Grid is vocab-major (rows innermost) so the [bv, D] accumulator can
    carry across all row blocks and write once at the end.
    """
    ni = pl.program_id(1)
    nn = pl.num_programs(1)

    @pl.when(ni == 0)
    def _():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    vi = pl.program_id(0)
    s = jax.lax.dot_general(
        x_ref[...], e_ref[...],
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [bn, bv]
    p = jnp.exp(s - lse_ref[...])
    cols = vi * bv + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    ds = (p - jnp.where(cols == tgt_ref[...], 1.0, 0.0)) * g_ref[...]
    acc_scr[:] += jax.lax.dot_general(
        ds.astype(x_ref.dtype), x_ref[...],
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [bv, D]

    @pl.when(ni == nn - 1)
    def _():
        de_ref[...] = acc_scr[:]


def _xent_fwd_raw(x, embedding, targets, *, bn: int, bv: int,
                  interpret: bool):
    """x [N, D] bf16, embedding [V, D] bf16, targets [N] int32 ->
    (lse [N] f32, target_logit [N] f32)."""
    n, d = x.shape
    v = embedding.shape[0]
    tgt = targets.reshape(n, 1).astype(jnp.int32)
    grid = (n // bn, v // bv)
    row_spec = pl.BlockSpec((bn, d), lambda i, j: (i, 0))
    out_row = pl.BlockSpec((bn, 1), lambda i, j: (i, 0))
    lse, tlogit = pl.pallas_call(
        functools.partial(_fwd_kernel, bv=bv),
        grid=grid,
        in_specs=[
            row_spec,
            pl.BlockSpec((bv, d), lambda i, j: (j, 0)),
            out_row,
        ],
        out_specs=[out_row, out_row],
        out_shape=[
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((bn, 1), jnp.float32)] * 3,
        interpret=interpret,
    )(x, embedding, tgt)
    return lse[:, 0], tlogit[:, 0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_xent(x, embedding, targets, interpret: bool = False):
    """Per-row softmax cross-entropy of the tied readout, fused.

    x: [N, D] (compute dtype), embedding: [V, D] (fp32 master — cast to
    the compute dtype once, in here, so its cotangent stays fp32 for the
    optimizer), targets: [N] int32 -> [N] f32 losses
    ``logsumexp(x @ E^T) - logit[t]``. Semantically identical to the
    naive path built on
    :func:`~kvedge_tpu.models.transformer.tied_readout`, but no [N, V]
    tensor ever reaches HBM in either pass. Requires N % 8 == 0 and
    V % 128 == 0 (checked with actionable errors at trace time).
    """
    # One forward recipe: the primal delegates to the VJP-forward so the
    # two paths can never drift apart.
    return _fused_xent_fwd(x, embedding, targets, interpret)[0]


def _fused_xent_fwd(x, embedding, targets, interpret):
    e16 = embedding.astype(x.dtype)
    v = embedding.shape[0]
    # Match the naive path's jnp.take_along_axis semantics on garbage ids
    # exactly: negative ids wrap (-1 -> V-1), ids outside [-V, V) gather
    # a NaN fill — so a corrupt corpus NaNs the loss LOUDLY in both paths
    # instead of silently training on a wrong extraction here. (Backward
    # NaN poisoning is not bit-matched; forward loss is, which is what a
    # diverging-loss check sees.) The wrapped ids ride the residuals so
    # the backward's onehot matches the forward's extraction.
    wrapped = jnp.where(targets < 0, targets + v, targets)
    valid = (targets >= -v) & (targets < v)
    lse, tlogit = _xent_fwd_raw(
        x, e16, jnp.clip(wrapped, 0, v - 1),
        bn=pick_row_block(x.shape[0], FWD_MAX_ROWS),
        bv=pick_vocab_block(v),
        interpret=interpret,
    )
    tlogit = jnp.where(valid, tlogit, jnp.nan)
    return lse - tlogit, (x, e16, jnp.clip(wrapped, 0, v - 1), lse)


def _fused_xent_bwd(interpret, residuals, g):
    x, embedding, targets, lse = residuals
    n, d = x.shape
    v = embedding.shape[0]
    bn = pick_row_block(n, BWD_MAX_ROWS)
    bv = pick_vocab_block(v)
    tgt = targets.reshape(n, 1).astype(jnp.int32)
    lse2 = lse.reshape(n, 1)
    g2 = g.reshape(n, 1).astype(jnp.float32)

    row_spec = pl.BlockSpec((bn, d), lambda i, j: (i, 0))
    row_col = pl.BlockSpec((bn, 1), lambda i, j: (i, 0))
    dx = pl.pallas_call(
        functools.partial(_dx_kernel, bv=bv),
        grid=(n // bn, v // bv),
        in_specs=[
            row_spec,
            pl.BlockSpec((bv, d), lambda i, j: (j, 0)),
            row_col, row_col, row_col,
        ],
        out_specs=row_spec,
        out_shape=jax.ShapeDtypeStruct((n, d), x.dtype),
        scratch_shapes=[pltpu.VMEM((bn, d), jnp.float32)],
        interpret=interpret,
    )(x, embedding, tgt, lse2, g2)

    # Vocab-major grid for dE: row blocks are grid dim 1 (innermost).
    vrow_spec = pl.BlockSpec((bn, d), lambda i, j: (j, 0))
    vrow_col = pl.BlockSpec((bn, 1), lambda i, j: (j, 0))
    de = pl.pallas_call(
        functools.partial(_de_kernel, bv=bv),
        grid=(v // bv, n // bn),
        in_specs=[
            vrow_spec,
            pl.BlockSpec((bv, d), lambda i, j: (i, 0)),
            vrow_col, vrow_col, vrow_col,
        ],
        out_specs=pl.BlockSpec((bv, d), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((v, d), jnp.float32),
        scratch_shapes=[pltpu.VMEM((bv, d), jnp.float32)],
        interpret=interpret,
    )(x, embedding, tgt, lse2, g2)

    d_targets = jax.numpy.zeros(targets.shape, jax.dtypes.float0)
    # de is fp32 from the kernel accumulator and the embedding primal is
    # the fp32 master, so the optimizer sees full-precision grads.
    return dx, de, d_targets


fused_xent.defvjp(_fused_xent_fwd, _fused_xent_bwd)
