"""Token-batch feeder: the training input pipeline.

The training driver (models/training.py) consumes an iterator of
``[batch, seq+1]`` int32 arrays. This module provides that iterator from
a binary corpus file on the state volume, backed by the **native
prefetching feeder** (``native/kvedge-feed.cc``: mmap + worker thread +
bounded ring buffer, so host IO and slicing overlap the device step
instead of serializing with it), with a pure-Python fallback of
identical semantics for environments without a C++ toolchain.

The reference has no data path at all (its payload is the external IoT
Edge daemon, SURVEY.md §0); this is payload-side runtime IO, native
where it matters, like the rest of the runtime around the JAX compute
path.

Corpus format (``write_corpus``): magic ``KVFEED01``, uint64 little-
endian token count, int32 tokens. Batch order is deterministic — batch
``b`` row ``r`` covers tokens ``[(b*batch + r) * seq, ... + seq + 1)``
wrapping modulo the corpus — so a training run resumed at step ``k``
(``start_batch=k``) sees exactly the batches it would have seen without
the restart: the feeder's half of the checkpoint/resume contract.

Multi-host sharding: a logical batch may span ``global_batch`` rows of
which this feeder produces ``batch`` rows starting at global row
``shard_offset`` (host p of P passes ``batch=global//P,
shard_offset=p*global//P``). ``start_batch`` stays a GLOBAL batch index,
so every host resumes with the same arithmetic, and concatenating the P
hosts' outputs row-wise reconstructs the single-host batch exactly —
pinned by tests/test_feeder.py.
"""

from __future__ import annotations

import ctypes
import os
import pathlib
import struct
import subprocess
import threading
import warnings

import numpy as np

MAGIC = b"KVFEED01"
_HEADER = struct.Struct("<8sQ")

_NATIVE_DIR = pathlib.Path(__file__).resolve().parent.parent.parent / "native"
_LIB_PATH = _NATIVE_DIR / "build" / "libkvedge-feed.so"

_lib = None
_lib_lock = threading.Lock()


def write_corpus(path: str | os.PathLike, tokens) -> None:
    """Write an int32 token corpus in the feeder's format."""
    arr = np.asarray(tokens, dtype=np.int32)
    if arr.ndim != 1:
        raise ValueError("corpus tokens must be a 1-D sequence")
    with open(path, "wb") as fh:
        fh.write(_HEADER.pack(MAGIC, arr.size))
        fh.write(arr.tobytes())


def read_corpus_header(path: str | os.PathLike) -> int:
    """Validate the header; return the token count."""
    with open(path, "rb") as fh:
        raw = fh.read(_HEADER.size)
    if len(raw) < _HEADER.size:
        raise ValueError("corpus file too small for header")
    magic, n_tokens = _HEADER.unpack(raw)
    if magic != MAGIC:
        raise ValueError(f"bad corpus magic {magic!r} (expected {MAGIC!r})")
    return n_tokens


def _load_native():
    """Build (if needed) and load the native library; None if unavailable."""
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib or None
        try:
            # Run `make` even when the .so already exists: the build is
            # dependency-checked (a no-op when current), and skipping it
            # would load a STALE library after an in-place source update —
            # dlopen caches by path, so a missing symbol discovered at
            # bind time is too late to rebuild. Environments without a
            # toolchain but with a prebuilt, current .so (the runtime
            # image) still load it: a failed make only raises when no
            # library exists at all.
            try:
                # locklint: allow[io-under-lock] one-time lazy init — the module lock exists precisely to serialize the native build+dlopen; waiters need the finished library anyway, and no request-path lock is held here
                subprocess.run(
                    ["make", "-C", str(_NATIVE_DIR)],
                    check=True, capture_output=True,
                )
            except (OSError, subprocess.SubprocessError):
                if not _LIB_PATH.exists():
                    raise
            lib = ctypes.CDLL(str(_LIB_PATH))
            # Symbol binding stays inside the try: a prebuilt library from
            # an older source revision lacks newer symbols, and that must
            # surface as the loud Python fallback (AttributeError), not an
            # uncaught crash in open_feeder.
            lib.kvf_open_sharded.restype = ctypes.c_void_p
            lib.kvf_open_sharded.argtypes = [
                ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                ctypes.c_ulonglong, ctypes.c_int, ctypes.c_int,
            ]
            lib.kvf_next.restype = ctypes.c_int
            lib.kvf_next.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
            lib.kvf_tokens.restype = ctypes.c_ulonglong
            lib.kvf_tokens.argtypes = [ctypes.c_void_p]
            lib.kvf_close.argtypes = [ctypes.c_void_p]
            lib.kvf_last_error.restype = ctypes.c_char_p
        except (OSError, subprocess.SubprocessError, AttributeError) as e:
            # Loud fallback: a silently-degraded input pipeline is the
            # exact stall the native feeder exists to prevent, so say
            # why (a missing toolchain reads very differently from a
            # broken build).
            detail = ""
            if isinstance(e, subprocess.CalledProcessError) and e.stderr:
                detail = ": " + e.stderr.decode(errors="replace").strip()
            warnings.warn(
                "native feeder unavailable, using the pure-Python "
                f"fallback ({type(e).__name__}{detail})",
                RuntimeWarning, stacklevel=3,
            )
            _lib = False  # cached negative: no toolchain / no / stale lib
            return None
        _lib = lib
        return lib


class TokenFeeder:
    """Iterator of [batch, seq+1] int32 batches via the native feeder."""

    def __init__(self, path: str | os.PathLike, batch: int, seq: int,
                 depth: int = 4, start_batch: int = 0,
                 global_batch: int = 0, shard_offset: int = 0):
        lib = _load_native()
        if lib is None:
            raise RuntimeError(
                "native feeder library unavailable (no C++ toolchain?); "
                "use PyTokenFeeder or open_feeder() for the fallback"
            )
        self._lib = lib
        self._batch, self._seq = batch, seq
        self._handle = lib.kvf_open_sharded(
            str(path).encode(), batch, seq, depth, start_batch,
            global_batch or batch, shard_offset,
        )
        if not self._handle:
            raise ValueError(lib.kvf_last_error().decode())
        self.n_tokens = int(lib.kvf_tokens(self._handle))

    def __iter__(self):
        return self

    def __next__(self) -> np.ndarray:
        if self._handle is None:
            raise StopIteration
        out = np.empty((self._batch, self._seq + 1), np.int32)
        rc = self._lib.kvf_next(
            self._handle, out.ctypes.data_as(ctypes.c_void_p)
        )
        if rc != 0:
            raise StopIteration
        return out

    def close(self) -> None:
        if self._handle is not None:
            self._lib.kvf_close(self._handle)
            self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class PyTokenFeeder:
    """Pure-Python feeder with byte-identical output order.

    The no-toolchain fallback AND the parity oracle for the native
    implementation's tests.
    """

    def __init__(self, path: str | os.PathLike, batch: int, seq: int,
                 depth: int = 4, start_batch: int = 0,
                 global_batch: int = 0, shard_offset: int = 0):
        del depth  # no prefetching; signature parity with TokenFeeder
        global_batch = global_batch or batch
        if not (0 <= shard_offset and shard_offset + batch <= global_batch):
            # Same open-time rejection as the native feeder.
            raise ValueError(
                "shard must satisfy 0 <= shard_offset and "
                "shard_offset + batch <= global_batch"
            )
        self.n_tokens = read_corpus_header(path)
        if self.n_tokens < seq + 1:
            raise ValueError("corpus smaller than one sequence")
        self._tokens = np.fromfile(
            path, dtype=np.int32, offset=_HEADER.size
        )[: self.n_tokens]
        if self._tokens.size < self.n_tokens:
            # Same open-time rejection as the native feeder — a truncated
            # body must not surface as an IndexError mid-training.
            raise ValueError(
                "corpus header claims more tokens than the file holds"
            )
        self._batch, self._seq = batch, seq
        self._global_batch, self._shard_offset = global_batch, shard_offset
        self._index = start_batch

    def __iter__(self):
        return self

    def __next__(self) -> np.ndarray:
        out = np.empty((self._batch, self._seq + 1), np.int32)
        for r in range(self._batch):
            start = (
                (self._index * self._global_batch + self._shard_offset + r)
                * self._seq % self.n_tokens
            )
            idx = (start + np.arange(self._seq + 1)) % self.n_tokens
            out[r] = self._tokens[idx]
        self._index += 1
        return out

    def close(self) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def open_feeder(path: str | os.PathLike, batch: int, seq: int,
                depth: int = 4, start_batch: int = 0,
                global_batch: int = 0, shard_offset: int = 0):
    """The native feeder when buildable, the Python fallback otherwise."""
    cls = TokenFeeder if _load_native() is not None else PyTokenFeeder
    return cls(path, batch, seq, depth, start_batch,
               global_batch=global_batch, shard_offset=shard_offset)
