"""Training-input pipeline: corpus files and the prefetching feeder."""

from kvedge_tpu.data.feeder import (
    PyTokenFeeder,
    TokenFeeder,
    open_feeder,
    read_corpus_header,
    write_corpus,
)

__all__ = [
    "PyTokenFeeder",
    "TokenFeeder",
    "open_feeder",
    "read_corpus_header",
    "write_corpus",
]
