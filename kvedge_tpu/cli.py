"""kvedge-tpu CLI — the `helm install`-shaped front door.

The reference's only entry point is the operator's install command
(``README.md:60``):

    helm install aziotedgeinstance ./deployment/helm \\
        --set publicSshKey=... --set-file azIotEdgeConfig=config.toml

kvedge-tpu mirrors that interface natively (no helm binary required):

    python -m kvedge_tpu render --set publicSshKey=... \\
        --set-file jaxRuntimeConfig=config.toml --output-dir ./out

which writes the manifest set for ``kubectl apply -f ./out`` and prints the
post-install NOTES. The equivalent Helm chart lives at ``deployment/helm``
for operators who prefer helm itself.
"""

from __future__ import annotations

import argparse
import pathlib
import sys

from kvedge_tpu.config.values import (
    DEFAULT_VALUES,
    parse_set_flag,
    parse_set_file_flag,
)
from kvedge_tpu.render import render_all, to_yaml, to_multidoc_yaml
from kvedge_tpu.render.manifests import render_notes
from kvedge_tpu.version import CHART_NAME, CHART_VERSION


def _add_value_flags(parser: argparse.ArgumentParser) -> None:
    parser.add_argument(
        "--set",
        dest="sets",
        action="append",
        default=[],
        metavar="KEY=VALUE",
        help="override a chart value (helm --set analogue)",
    )
    parser.add_argument(
        "--set-file",
        dest="set_files",
        action="append",
        default=[],
        metavar="KEY=PATH",
        help="set a chart value from a file (helm --set-file analogue)",
    )


def _resolve_values(args: argparse.Namespace):
    values = DEFAULT_VALUES
    for assignment in args.sets:
        values = parse_set_flag(values, assignment)
    for assignment in args.set_files:
        values = parse_set_file_flag(values, assignment)
    return values


def cmd_render(args: argparse.Namespace) -> int:
    values = _resolve_values(args)
    chart = render_all(values)
    if args.golden or args.output_dir:
        out = pathlib.Path(args.golden or args.output_dir)
        out.mkdir(parents=True, exist_ok=True)
        for filename, doc in chart.ordered():
            (out / filename).write_text(to_yaml(doc))
        if args.golden:
            (out / "NOTES.txt").write_text(chart.notes)
            print(f"wrote golden render to {out}", file=sys.stderr)
        else:
            print(f"wrote {len(chart.manifests)} manifests to {out}", file=sys.stderr)
            print(chart.notes, file=sys.stderr)
    else:
        print(to_multidoc_yaml([doc for _, doc in chart.ordered()]), end="")
        print(chart.notes, file=sys.stderr)
    return 0


def cmd_notes(args: argparse.Namespace) -> int:
    print(render_notes(_resolve_values(args)), end="")
    return 0


def cmd_version(args: argparse.Namespace) -> int:
    print(f"{CHART_NAME} {CHART_VERSION}")
    return 0


def cmd_package(args: argparse.Namespace) -> int:
    """Package a Helm chart as ``<name>-<version>.tgz`` (helm package
    analogue).

    Packaging needs NO template parsing — only ``Chart.yaml`` metadata
    and the ``.helmignore`` exclusions (shared with the renderer via
    ``helmlite.load_helmignore``). The ignore file is load-bearing: it
    is what keeps the dead prepopulated-volume template out of the
    installable package (reference ``.helmignore:23-24``). The whole
    chart tree is walked (crds/, charts/, README, ...), matching what
    real helm includes, and the archive is byte-reproducible.
    """
    import gzip
    import io
    import tarfile

    import yaml

    from kvedge_tpu.render.helmlite import (
        helmignore_matches,
        load_helmignore,
    )

    chart_dir = pathlib.Path(args.chart_dir)
    chart_yaml = chart_dir / "Chart.yaml"
    if not chart_yaml.is_file():
        raise ValueError(f"{chart_dir} has no Chart.yaml")
    meta = yaml.safe_load(chart_yaml.read_text())
    try:
        name, version = meta["name"], str(meta["version"])
    except (TypeError, KeyError):
        raise ValueError(f"{chart_yaml} must declare name and version")
    patterns = load_helmignore(chart_dir)
    out_dir = pathlib.Path(args.out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    out_path = out_dir / f"{name}-{version}.tgz"

    members = []
    for path in sorted(chart_dir.rglob("*")):
        if not path.is_file():
            continue
        rel = path.relative_to(chart_dir).as_posix()
        if rel != ".helmignore" and helmignore_matches(rel, patterns):
            continue
        members.append((rel, path.read_bytes()))

    with open(out_path, "wb") as raw:
        # mtime=0 in the gzip header too, or two identical packagings
        # differ by wall clock — the archive must be reproducible.
        with gzip.GzipFile(fileobj=raw, mode="wb", mtime=0) as gz:
            with tarfile.open(fileobj=gz, mode="w") as tar:
                for rel, data in members:
                    info = tarfile.TarInfo(f"{name}/{rel}")
                    info.size = len(data)
                    info.mtime = 0
                    info.mode = 0o644
                    tar.addfile(info, io.BytesIO(data))
    print(f"wrote {out_path}", file=sys.stderr)
    return 0


def cmd_corpus(args: argparse.Namespace) -> int:
    """Write a KVFEED01 token corpus for the ``train`` payload.

    Sources, exactly one of: ``--from-tokens`` (a text file of integer
    token ids, whitespace/newline separated — the format any external
    tokenizer can emit) or ``--random N`` (a seeded synthetic corpus for
    smoke tests and demos).

    ``--holdout F`` (0 < F < 1) splits the stream's TAIL fraction into a
    second file ``<out>.eval`` — the held-out split for the ``eval``
    payload (``[payload] eval_corpus``). A sequential tail split, not a
    shuffle: the corpus is a token stream, and shuffling would leak
    training n-grams across the boundary.
    """
    import numpy as np

    from kvedge_tpu.data import read_corpus_header, write_corpus

    if (args.from_tokens is None) == (args.random is None):
        raise ValueError(
            "exactly one of --from-tokens or --random is required"
        )
    if args.from_tokens is not None:
        # Whitespace/newline separated, no rectangularity requirement
        # (np.loadtxt would reject ragged lines).
        try:
            words = pathlib.Path(args.from_tokens).read_text().split()
            # Validate in Python ints first: a huge id must become the
            # friendly error below, not an OverflowError from numpy.
            ids = [int(w) for w in words]
        except ValueError as e:
            raise ValueError(f"--from-tokens must contain integers: {e}")
        if not ids:
            raise ValueError(
                f"--from-tokens file {args.from_tokens} contains no "
                "tokens; an empty corpus would only fail later at pod "
                "boot"
            )
        if min(ids) < 0 or max(ids) > 2**31 - 1:
            raise ValueError("token ids must fit in int32 and be >= 0")
        tokens = np.array(ids, dtype=np.int32)
    else:
        if args.random <= 0:
            raise ValueError("--random needs a positive token count")
        rng = np.random.default_rng(args.seed)
        tokens = rng.integers(0, args.vocab, size=args.random,
                              dtype=np.int32)
    if args.holdout is not None:
        if not 0.0 < args.holdout < 1.0:
            raise ValueError("--holdout must be a fraction in (0, 1)")
        n_eval = int(len(tokens) * args.holdout)
        # Same discipline as the empty --from-tokens guard above: a split
        # too small to feed even one seq=128 eval batch row would only
        # fail later at pod boot (the feeder needs seq+1 tokens).
        if n_eval < 129 or len(tokens) - n_eval < 129:
            raise ValueError(
                f"--holdout {args.holdout} of {len(tokens)} tokens "
                f"leaves a split of {min(n_eval, len(tokens) - n_eval)} "
                "tokens — too small to feed one default-seq (128) batch "
                "row at pod boot; use more tokens or a different fraction"
            )
        eval_path = f"{args.out}.eval"
        write_corpus(args.out, tokens[:-n_eval])
        write_corpus(eval_path, tokens[-n_eval:])
        print(
            f"wrote {read_corpus_header(args.out)} tokens to {args.out} "
            f"and {read_corpus_header(eval_path)} held-out tokens to "
            f"{eval_path} (set [payload] eval_corpus to it)",
            file=sys.stderr,
        )
        return 0
    write_corpus(args.out, tokens)
    print(
        f"wrote {read_corpus_header(args.out)} tokens to {args.out}",
        file=sys.stderr,
    )
    return 0


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="kvedge-tpu",
        description="TPU-native deployment accelerator for JAX runtimes on K8s.",
    )
    sub = parser.add_subparsers(dest="command", required=True)

    p_render = sub.add_parser(
        "render", help="render the manifest set (helm template/install analogue)"
    )
    _add_value_flags(p_render)
    p_render.add_argument(
        "--output-dir", help="write manifests here instead of stdout"
    )
    p_render.add_argument(
        "--golden", help=argparse.SUPPRESS  # regenerate golden test fixtures
    )
    p_render.set_defaults(func=cmd_render)

    p_notes = sub.add_parser("notes", help="print post-install usage notes")
    _add_value_flags(p_notes)
    p_notes.set_defaults(func=cmd_notes)

    p_version = sub.add_parser("version", help="print chart/app version")
    p_version.set_defaults(func=cmd_version)

    p_corpus = sub.add_parser(
        "corpus",
        help="write a KVFEED01 token corpus for the `train` payload",
    )
    p_corpus.add_argument("--out", required=True, help="output corpus path")
    p_corpus.add_argument(
        "--from-tokens",
        help="text file of integer token ids (whitespace separated)",
    )
    p_corpus.add_argument(
        "--random", type=int,
        help="generate N random tokens instead (seeded; smoke tests/demos)",
    )
    p_corpus.add_argument("--vocab", type=int, default=512,
                          help="vocab for --random (default 512, the "
                               "train payload's model vocab)")
    p_corpus.add_argument("--seed", type=int, default=0)
    p_corpus.add_argument(
        "--holdout", type=float,
        help="split this tail fraction (0 < F < 1) into <out>.eval — "
             "the held-out corpus for the `eval` payload",
    )
    p_corpus.set_defaults(func=cmd_corpus)

    p_package = sub.add_parser(
        "package",
        help="package the Helm chart as <name>-<version>.tgz "
             "(helm package analogue, honors .helmignore)",
    )
    p_package.add_argument(
        "--chart-dir", default=str(
            pathlib.Path(__file__).resolve().parent.parent
            / "deployment" / "helm"
        ),
        help="chart directory (default: the bundled chart)",
    )
    p_package.add_argument("--out-dir", default=".",
                           help="where to write the .tgz (default: cwd)")
    p_package.set_defaults(func=cmd_package)

    return parser


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    try:
        return args.func(args)
    except (ValueError, OSError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    raise SystemExit(main())
