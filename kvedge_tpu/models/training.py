"""Resumable training driver: the checkpoint/resume consumer.

Proves the accelerator's persistence capability end-to-end for a real JAX
workload: training state (params, optimizer state, step) is checkpointed
through the PVC-backed state dir, and a new pod generation resumes from the
latest step instead of restarting — the payload-level analogue of EdgeHub's
PVC-backed message state in the reference (``README.md:88``).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Iterable

import jax

from kvedge_tpu.models.transformer import (
    TransformerConfig,
    init_params,
    make_train_step,
)
from kvedge_tpu.runtime.checkpoint import StateCheckpointer


@dataclasses.dataclass
class TrainResult:
    step: int
    params: dict
    losses: list[float]
    resumed_from: int | None


def run_training(
    cfg: TransformerConfig,
    state_dir: str,
    num_steps: int,
    batches: Iterable,
    optimizer=None,
    checkpoint_every: int = 10,
    seed: int = 0,
    prepare: Callable = lambda tree: tree,
    mesh=None,
    on_step: Callable | None = None,
    checkpoint_dir: str = "",
) -> TrainResult:
    """Train for ``num_steps`` total, resuming from the latest checkpoint.

    ``num_steps`` counts from step 0 across ALL runs against this state
    dir: a rerun after a crash picks up where the checkpoint left off and
    returns immediately if the target was already reached. ``prepare``
    lets callers shard the (restored or fresh) state onto a mesh;
    ``mesh`` is required for the sequence-parallel attention modes
    (``'ring'``/``'ulysses'``; see :func:`make_train_step`).
    ``on_step(step, loss)`` is called after every completed step — the
    hook the runtime uses to stream live progress into its heartbeat.
    ``checkpoint_dir`` redirects checkpoints to shared storage (multi-host
    slices; see runtime/checkpoint.py) while ``state_dir`` keeps holding
    the per-host runtime state.
    """
    init_opt, train_step = make_train_step(cfg, optimizer=optimizer, mesh=mesh)
    step = 0
    resumed_from = None

    def fresh_state():
        params = init_params(jax.random.PRNGKey(seed), cfg)
        return {"params": params, "opt_state": init_opt(params)}

    with StateCheckpointer(state_dir, checkpoint_dir=checkpoint_dir) as ckpt:
        # Abstract template first (zero allocation): materialize a fresh
        # state only when there is nothing to restore, so a resuming pod
        # never holds two full copies of params + optimizer state.
        restored = ckpt.restore_latest(jax.eval_shape(fresh_state))
        if restored is not None:
            step, tree = restored
            resumed_from = step
        else:
            tree = fresh_state()
        params, opt_state = tree["params"], tree["opt_state"]
        params = prepare(params)
        opt_state = prepare(opt_state)

        losses: list[float] = []
        batch_iter = iter(batches)
        while step < num_steps:
            batch = next(batch_iter)
            params, opt_state, loss = train_step(params, opt_state, batch)
            step += 1
            losses.append(float(loss))
            if on_step is not None:
                on_step(step, losses[-1])
            if step % checkpoint_every == 0 or step == num_steps:
                ckpt.save(step, {"params": params, "opt_state": opt_state})
        return TrainResult(
            step=step, params=params, losses=losses, resumed_from=resumed_from
        )
