"""Continuous-batching generation server over the paged KV cache.

The request-level serving loop the paged cache (models/kvcache.py) exists
for: many concurrent requests with different prompt lengths and budgets
share one page pool and ONE batched decode step. A request joins
mid-stream (admit + per-sequence prefill into a free slot), rides the
batched ``step`` with whatever else is in flight, and leaves when its
budget is done (pages released back to the pool) — no request ever waits
for another to finish, which is the whole point of continuous batching
over static batches.

TPU-first split, same as the cache it wraps: the decode loop is one
batched jitted step over all ``slots`` regardless of occupancy (static
shapes, no retracing as requests come and go); admission, slot
assignment, and page-budget reservation are host-side Python under one
lock. Greedy decode here agrees token-for-token with the contiguous
:func:`~kvedge_tpu.models.decode.generate` — the paged attention math
matches decode.py exactly, and tests/test_serving.py pins the
equivalence under concurrency.

The reference has no serving of any kind (SURVEY.md §0); this is the
capability the repo's own README listed as future work.
"""

from __future__ import annotations

import dataclasses
import hashlib
import heapq
import json
import queue
import threading
import time

import numpy as np

from kvedge_tpu.runtime.failures import (
    PageAccountingError,
    PoolPoisoned,
    ServingFailure,
    classify_failure,
)
from kvedge_tpu.runtime.journal import JournalEntry, RequestJournal
from kvedge_tpu.models.scheduler import AdmissionScheduler, _Hist

# Stream sentinel objects (token queue carries ints, then one of these).
_STREAM_DONE = object()


def _raw_key_data(key) -> np.ndarray:
    """Raw uint32 key data from a PRNG key, typed or legacy — the form
    that crosses host/process boundaries (the sampled-window dispatch
    and the slice op-stream); kvcache wraps it back on device with the
    DEFAULT impl, so a typed key built with any other PRNG impl is
    rejected here, per-request at submit — not deep in the decode loop
    where the failure would poison every co-tenant."""
    import jax
    import jax.numpy as jnp

    arr = jnp.asarray(key)
    if jnp.issubdtype(arr.dtype, jax.dtypes.prng_key):
        default = str(jax.random.key_impl(jax.random.key(0)))
        got = str(jax.random.key_impl(arr))
        if got != default:
            raise ValueError(
                f"sampling seed key uses PRNG impl {got}; the serving "
                f"key schedule is defined on the default impl "
                f"({default}) — pass a jax.random.PRNGKey/key() seed"
            )
        return np.asarray(jax.random.key_data(arr))
    return np.asarray(arr, np.uint32)


class ServerBusy(RuntimeError):
    """No slot/page capacity became available within the timeout."""


class ServerOverloaded(ServerBusy):
    """Shed at admission by the scheduler's overload watermarks —
    raised BEFORE parking, so the caller pays one RTT instead of its
    full timeout. ``retry_after_s`` (when measurable) is the measured
    per-class queue wait; the HTTP layer forwards it as a hint."""

    def __init__(self, msg: str, retry_after_s: float | None = None):
        super().__init__(msg)
        if retry_after_s is not None:
            self.retry_after_s = retry_after_s


class ServerClosed(RuntimeError):
    """The server was shut down."""


class RequestCancelled(RuntimeError):
    """The request was cancelled (consumer disconnect / explicit)."""


# eq=False: a request is its identity (hashable — the journal keys on
# the live object), never field-equality over mutable token lists.
@dataclasses.dataclass(eq=False)
class _Request:
    prompt: list[int]
    n_new: int
    # (seed_key, temperature, top_p) or None for greedy. The key schedule
    # is decode.py's: token t samples with fold_in(seed_key, t) — a pure
    # function of the request, so batch composition changes nothing.
    sampling: tuple | None = None
    next_token: int = -1
    # Early-termination token (rung 23): generation finishes the moment
    # this token is PRODUCED — it is emitted as the final token, then
    # the request completes with its remaining budget unused. -1 (no
    # stop token) can never match: every produced token id is >= 0, so
    # stop-free traffic takes bit-identical paths with zero compares on
    # device (the capped window kernels carry the per-row stop id and
    # report the first hit in the packed finish rows).
    stop_token: int = -1
    # Device/host-detected stop whose finish had to be DEFERRED: the
    # truncated stream (stop token last) is already emitted, but an
    # in-flight window still touches this slot, so the slot and pages
    # must survive until that window retires. The forced boundary's
    # finish sweep completes it.
    stopped: bool = False
    # Pages reserved at admission — stored on the request so release is
    # symmetric even if the server's spec mode changes mid-flight (the
    # auto guard rail can zero _spec; recomputing at release would then
    # under-release a greedy request's slack). With a prefix-cache hit
    # this is the PRIVATE part only (pages_needed − full shared pages);
    # the shared pages are covered by leases (serving._lease).
    pages_reserved: int = 0
    # Prefix sharing (rung 24): the FULL shared pages this request's
    # table starts on (leased, registry-refcounted, read-only) and the
    # trie node at that depth — the journal shadow's key. A partially
    # shared page is COWed at admission and is private, never listed
    # here. Both reset when a preempt/requeue round-trip materializes
    # the request as self-contained bytes.
    shared_pages: tuple = ()
    prefix_node: "int | None" = None
    # Raw uint32 data of the sampling seed key, fetched ONCE at
    # admission (the sampled-window dispatch needs it host-side every
    # window; re-fetching from the device key per window would add a
    # transfer per request per window).
    key_data: "np.ndarray | None" = None
    generated: list[int] = dataclasses.field(default_factory=list)
    done: threading.Event = dataclasses.field(
        default_factory=threading.Event
    )
    error: Exception | None = None
    # Set for streaming requests: every generated token is put here as it
    # lands, then _STREAM_DONE (or the failing exception).
    stream: "queue.SimpleQueue | None" = None
    # Cancellation request (consumer gone / explicit): honored at the
    # next loop iteration — the step/window in flight completes first.
    cancelled: bool = False
    # Scheduler (models/scheduler.py): the request's priority class,
    # its admission ticket number (kept across preemption so a resumed
    # request re-queues ahead of later arrivals), and the admission
    # sequence victim selection orders by (preempt the LATEST admitted
    # request of the lowest class — least progress lost).
    pclass: str = "interactive"
    ticket_no: int = -1
    admit_seq: int = -1
    # Overlap pipeline bookkeeping: tokens this request will receive
    # from windows that are DISPATCHED but not yet harvested.
    # len(generated) + inflight is the request's committed position —
    # the number the next window's budget cap is computed from, so a
    # speculative dispatch can never outrun the budget even though the
    # host hasn't seen its tokens yet.
    inflight: int = 0
    # Tracing (runtime/tracing.py): the request ID minted/accepted at
    # HTTP ingress (echoed as X-Request-Id) and the sampling decision,
    # made ONCE at submit so all of this request's spans share fate.
    # The stage stamps (tracer clock) feed the serve_ttft_ms and
    # queue-vs-decode histograms; they are recorded even with tracing
    # off (perf_counter is cheap, histograms are always-on metrics).
    rid: str = ""
    trace: bool = False
    t_submit: float = 0.0
    t_admit: float = 0.0
    # First-token stamp (the TTFT observation instant): with the final
    # finish stamp it yields the request's mean inter-token gap — the
    # per-request ITL the rung-25 SLO engine computes its p99 over.
    t_first: float = 0.0
    # Exactly-once delivery watermark (rung 22): tokens at indices
    # below this were already streamed to the consumer before a
    # journal restore rewound ``generated`` to the checkpoint —
    # replayed decode regenerates them bit-identically (greedy argmax
    # / the positional fold_in key schedule) and ``_emit`` records
    # them WITHOUT re-streaming. 0 (the normal path) streams every
    # token.
    stream_resume_at: int = 0

    def pick(self, logits_row, step: int) -> int:
        """Next token from a [V] logits row, greedy or sampled. Used at
        prefill (one row); the decode loop batches every slot's pick
        into one device call instead (see ``_next_tokens``)."""
        import jax.numpy as jnp

        if self.sampling is None:
            return int(jnp.argmax(logits_row))
        from kvedge_tpu.models.decode import row_sample_keys, sample_token

        seed_key, temperature, top_p = self.sampling
        keys = row_sample_keys(seed_key[None], step)
        return int(sample_token(
            logits_row[None], keys, temperature, top_p
        )[0])


class StreamHandle:
    """Iterator over a streaming request's tokens + cancellation.

    Iteration semantics match the old generator exactly (tests and the
    HTTP layer consume it with ``next``/``for``); ``cancel()`` is the
    new client-disconnect hook — it frees the request's slot and pages
    at the next step/window boundary instead of decoding out the
    reserved budget.
    """

    def __init__(self, server: "PagedGenerationServer", req: _Request):
        self._server = server
        self._req = req
        self._produced = 0

    def __iter__(self) -> "StreamHandle":
        return self

    def __next__(self) -> int:
        if self._produced >= self._req.n_new:
            raise StopIteration
        item = self._req.stream.get()
        if item is _STREAM_DONE:
            raise StopIteration
        if isinstance(item, Exception):
            raise item
        self._produced += 1
        return item

    def cancel(self) -> None:
        self._server.cancel(self._req)


class PagedGenerationServer:
    """Continuous-batching decode over a :class:`PagedKVCache` — greedy
    by default, per-request nucleus sampling via ``submit(sampling=...)``
    (same key schedule and filter as the contiguous backend).

    ``submit`` blocks the calling thread until its tokens are ready (the
    HTTP handler model); the single background decode thread advances
    every in-flight request one token per batched step. Admission
    reserves each request's WORST-CASE page budget
    (``ceil((prompt + n_new) / page_size)``) up front, so ``grow`` can
    never exhaust the pool mid-decode — a request either gets capacity
    at admission or waits/queues, it never dies halfway.
    """

    def __init__(self, params: dict, cfg, *, slots: int = 4,
                 pages: int = 64, page_size: int = 16,
                 prefill_chunk: int = 0, prefix_cache: bool = True,
                 speculative: int = 0, spec_window: int = 0,
                 spec_sampled_window: bool = True,
                 window: int | str = 64,
                 window_min: int = 1, window_max: int = 256,
                 kv_dtype: str = "", cache=None,
                 retry_after_s: float | None = None,
                 overlap: str = "auto", sched_policy: str = "strict",
                 sched_weights: dict | None = None,
                 sched_max_queue_depth: int = 0,
                 sched_max_queue_wait_s: float = 0.0,
                 sched_swap_budget_mb: int = 0,
                 min_bucket: int = 0,
                 page_low_watermark: float = 0.0,
                 page_high_watermark: float = 0.0,
                 tracer=None, debug_locks: bool = False,
                 checkpoint_every: int = 0,
                 journal_budget_mb: int = 0,
                 prefix_host_mb: int = 0,
                 debug_pages: bool = False,
                 slo=None, slo_shed: bool = False,
                 occupancy_ring: int = 0):
        from kvedge_tpu.models.kvcache import PagedKVCache

        self._params = params
        self._cfg = cfg
        # Request-scoped tracing (runtime/tracing.py, SERVING.md rung
        # 18): a shared flight recorder, or None (off — every emission
        # site guards on it). Held as a plain attribute with no device
        # or thread state, so it survives revive() and slice
        # reformation unchanged.
        self.tracer = tracer
        # Device-window cap (steps per dispatched greedy decode scan).
        # The per-dispatch host round trip is the paged path's tax, and
        # the relay RTT has been measured anywhere from ~1.5 ms to
        # ~108 ms across sessions — a window amortizes it ~window x.
        # Round 4 hardwired the cap to page_size (16), which chained
        # throughput to the session's RTT (VERDICT r4 weak #2); the cap
        # is now an operator knob ([payload] serving_window, default
        # 64). The compiled program set stays the powers of two
        # {2..window} (see _window_steps); the tradeoff is admission
        # latency — a submitter joins at the next window boundary, so
        # worst-case wait grows with the window (SERVING.md).
        # "auto" hands the choice to the online controller (SERVING.md
        # rung 26): _window starts at the bounds cap and is re-picked
        # at every harvested window from EWMAs of the measured host
        # turnaround R and per-step device time t — the smallest power
        # of two with W*t >= R, the saturation point of the rung-16
        # law. The controller is plain data owned by this server and
        # mutated only under the work lock; revive() and slice
        # reformation never recreate it, so its learned state rides
        # through recovery (tests/test_autotune.py).
        self._autotune = None
        if window == "auto":
            from kvedge_tpu.runtime.autotune import WindowController
            self._autotune = WindowController(lo=window_min,
                                              hi=window_max)
            window = self._autotune.window()
        elif isinstance(window, str):
            raise ValueError("window must be an int >= 1 or 'auto'")
        if window < 1:
            raise ValueError("window must be >= 1")
        self._window = window
        # Overlapped (double-buffered) window dispatch ([payload]
        # serving_overlap): the decode loop enqueues window N+1 before
        # harvesting window N, so the host's round trip and bookkeeping
        # for N hide under the device's execution of N+1 — steps/s
        # moves from 1/(R + W*t) toward 1/max(R, W*t) (SERVING.md
        # rung 16). "auto" and "on" both pipeline (the loop itself
        # falls back to non-overlapped boundaries whenever exactness
        # needs one: admissions, cancellations, speculative passes);
        # "off" keeps the serial loop verbatim.
        if overlap not in ("auto", "on", "off"):
            raise ValueError("overlap must be 'auto', 'on' or 'off'")
        self._overlap = overlap
        self._overlap_on = overlap != "off"
        # The one in-flight (dispatched, unharvested) window record:
        # {"window": steps, "parts": [(slot, req, adv)], "handle":
        # unforced device tokens, "t0": dispatch stamp}. Depth is at
        # most 1 — double buffering, not an unbounded queue — so the
        # admission-latency price is bounded at one extra window.
        self._inflight: dict | None = None
        self._overlap_windows = 0
        # Per-window latency histograms (ms; exported via /metrics):
        # dispatch->harvest wall time (the device+RTT leg), host
        # processing time (the work the overlap hides), and the
        # pipeline depth observed at each dispatch.
        self._hist_rtt = _Hist((1.0, 2.0, 5.0, 10.0, 20.0, 50.0,
                                100.0, 200.0, 500.0, 1000.0, 2000.0))
        self._hist_host = _Hist((0.1, 0.2, 0.5, 1.0, 2.0, 5.0, 10.0,
                                 20.0, 50.0, 100.0))
        self._hist_depth = _Hist((0.0, 1.0))
        # Per-request stage histograms (ms; always on — cheap
        # perf_counter stamps, independent of the tracer): time to
        # first token (submit -> prefill logits picked), the
        # queue-vs-decode split (submit -> admit, admit -> done).
        # The log-spaced tail past 30 s keeps overload-regime p99s
        # measurable (openloop wait p99s used to clamp at the 30 000
        # cap); the pre-existing edges are unchanged so cumulative
        # bucket deltas stay comparable across bench snapshots.
        _stage_edges = (1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0,
                        200.0, 500.0, 1000.0, 2000.0, 5000.0,
                        10000.0, 30000.0, 60000.0, 120000.0,
                        240000.0, 480000.0, 960000.0)
        self._hist_ttft = _Hist(_stage_edges)
        self._hist_queue = _Hist(_stage_edges)
        self._hist_decode = _Hist(_stage_edges)
        # Device-time attribution (SERVING.md rung 25): the forced
        # device sync inside each window/harvest call, timed on its
        # own. Subtracted from the dispatch->harvest RTT it proves
        # where a regression lives — device kernel vs host bookkeeping
        # vs transport. Same always-on contract as the stage hists:
        # two perf_counter stamps per WINDOW, not per token.
        self._hist_device = _Hist((1.0, 2.0, 5.0, 10.0, 20.0, 50.0,
                                   100.0, 200.0, 500.0, 1000.0,
                                   2000.0))
        # Per-request mean inter-token gap, observed once at finish
        # ((t_done - t_first) / (tokens - 1)) — the SLO engine's
        # inter-token SLI input. Cheaper and tail-honest vs stamping
        # every token: a stall inflates the request's mean.
        self._hist_itl = _Hist((0.5, 1.0, 2.0, 5.0, 10.0, 20.0,
                                50.0, 100.0, 200.0, 500.0))
        # Completion counters (goodput / shed-rate SLIs): requests
        # that finished NORMALLY and the generated tokens they
        # realized. Cancels/failures don't count — goodput is good.
        self._done_total = 0
        self._tokens_done_total = 0
        # Speculative mode (draft length K, 0 = off): greedy slots
        # advance by batched verify passes — K prompt-lookup drafts per
        # slot, one (1+K)-query forward for the whole batch, up to K+1
        # tokens emitted per slot per pass (exact: drafts accept only
        # where they equal the model's own argmax). Sampled slots ride
        # the same pass advancing one token. A GREEDY request's page
        # budget carries K slack positions (a verify pass writes K/V at
        # length..length+K even when nothing accepts); sampled requests
        # reserve none — they can never accept a draft and the verify
        # kernel drops their draft-position scatters (_pages_needed).
        self._spec = int(speculative)
        self._spec_passes = 0
        self._spec_emitted = 0      # tokens emitted by greedy slots
        self._spec_slot_passes = 0  # greedy-slot participations
        # Device-resident spec windows ([payload] serving_spec_window,
        # SERVING.md rung 20): W > 0 batches W draft+verify passes into
        # ONE dispatched device program — drafting, accept/reject, KV
        # commits, budget freezing, and the pending-token chain all run
        # in the scan, so the host RTT amortizes over up to W*(1+K)
        # tokens instead of taxing every pass. Requires spec mode
        # (speculative > 0); an all-greedy active set rides windows,
        # any sampled co-tenant falls back to the legacy per-pass path
        # (identical tokens either way — windows are a scheduling
        # change, not a semantic one).
        if spec_window < 0:
            raise ValueError("spec_window must be >= 0")
        if spec_window > 0 and self._spec <= 0:
            raise ValueError(
                "spec_window needs speculative mode (speculative > 0)"
            )
        self._spec_window = int(spec_window)
        # Operator ceiling for the controller's spec-depth channel
        # (rung 26): with serving_window=auto the effective
        # _spec_window floats in [1, cap] at true boundaries; a static
        # window pins it to the configured value forever.
        self._spec_window_cap = int(spec_window)
        self._spec_windows = 0
        # On-device sampled verify ([payload] serving_spec_sampled_window,
        # SERVING.md rung 23): with the knob ON (default), a mixed
        # greedy+sampled batch STAYS on the windowed spec path — sampled
        # rows ride the verify scan advancing one token per pass with
        # their positional fold_in keys split inside the scan, emitting
        # the SAME tokens as the legacy per-pass path (pinned by tests).
        # OFF restores the rung-20 behaviour (one sampled co-tenant
        # collapses the batch to _spec_pass) and counts the collapse.
        self._spec_sampled_window = bool(spec_sampled_window)
        # Windowed-path collapses, labelled by cause (exported as
        # spec_window_fallbacks_total{cause=...}): a spec window was
        # configured but a boundary ran the legacy per-pass path
        # anyway. "sampled" = mixed batch with the sampled-window knob
        # off; "spec_off" = speculation disabled with a spec carry in
        # flight; "overlap_off" = spec windows need the overlap
        # pipeline but the serial loop is running.
        self._spec_window_fallbacks = {
            "sampled": 0, "spec_off": 0, "overlap_off": 0,
        }
        # Device-resident finish bookkeeping (rung 23): slots whose
        # NEXT boundary sweep should examine them for completion —
        # registered by every site that sets a pending token that
        # completes a budget or matches a stop token, so the sweep does
        # O(registered) work instead of scanning every active slot at
        # bucket 256. The sweep re-validates each entry; dispatch loops
        # re-register idle zero-budget rows as a self-healing backstop
        # (a missed registration costs one extra window, never a hang).
        self._finish_ready: set[int] = set()
        # Stop-terminated rows whose finish is deferred until the
        # window still touching their slot retires (_Request.stopped):
        # a positive count forces the pipeline to a boundary, where the
        # finish sweep completes them and zeroes this.
        self._stops_pending = 0
        self._stop_finishes = 0
        # Drafting-context capacity for the device-resident proposer:
        # prompt + generated + pending never exceeds max_seq + 1, and
        # the device appends at most K past the budget before freezing.
        self._spec_ctx_cap = int(cfg.max_seq) + int(speculative) + 2
        # Per-window emitted-tokens histogram (tokens a single request
        # realized from one dispatched spec window, post-truncation) —
        # the in-window acceptance E the rung-20 perf model needs, and
        # the Perfetto counterpart showing logical passes per dispatch.
        self._hist_spec_tokens = _Hist(
            (0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0)
        )
        # Chunked prefill granule (0 = whole-prompt): long prompts land
        # in fixed-size chunks with the lock RELEASED between chunks, so
        # in-flight requests keep decoding during an admission and XLA
        # compiles per chunk length instead of per prompt length.
        self._prefill_chunk = prefill_chunk
        # An injected cache overrides the pool knobs: the multi-host
        # serve path hands in a SlicePagedKVCache whose device calls
        # span the slice (runtime/sliceserve.py); the server neither
        # knows nor cares — every cache call below already serializes
        # on the one lock, which is exactly the total-order guarantee
        # the slice protocol needs.
        if cache is not None:
            slots, pages = cache.slots, cache.num_pages
            page_size = cache.page_size
        # Spec mode widens the per-sequence table cap by the draft
        # slack so a full-length (prompt + n_new == max_seq) request
        # still admits; an injected cache was built with the same
        # formula (workload._serving_pool_dims).
        self._cache = cache or PagedKVCache(
            cfg, slots=slots, pages=pages, page_size=page_size,
            max_pages_per_seq=-(-(cfg.max_seq + self._spec)
                                // page_size),
            kv_dtype=kv_dtype, min_bucket=min_bucket,
        )
        # Bucketed compile cache (SERVING.md rung 21): the device batch
        # dim is the cache's current BUCKET, not ``slots`` — every
        # dispatch-array site below sizes on ``self._cache.bucket``.
        # An injected cache governs its own bucketing (the slice cache
        # pins bucket == slots: the broadcast op stream fixes payload
        # shapes). A pending step-up requested by an admission that
        # found no row inside the current bucket; the decode loop
        # applies it at the next pipeline boundary.
        self._bucket_step_wanted = False
        # Free-page watermarks (fractions of the pool, 0 = off): below
        # ``low`` free-page headroom, non-top-priority admissions shed
        # with page-capacity terms instead of parking; swapped requests
        # resume only at ``high`` or better — the hysteresis that stops
        # preempt/resume thrash when the pool hovers at the edge.
        for name, v in (("page_low_watermark", page_low_watermark),
                        ("page_high_watermark", page_high_watermark)):
            if not 0.0 <= v < 1.0:
                raise ValueError(f"{name} must be in [0, 1), got {v}")
        if page_low_watermark and page_high_watermark \
                and page_low_watermark > page_high_watermark:
            raise ValueError(
                "page_low_watermark must be <= page_high_watermark"
            )
        self._page_low_wm = float(page_low_watermark)
        self._page_high_wm = float(page_high_watermark)
        # Prefix sharing: completed prompts register their page-aligned
        # prefixes here (key: token tuple -> pinned pages + LRU stamp);
        # a later prompt with the same prefix starts its table on those
        # READ-ONLY pages and prefills only the suffix. K/V depend only
        # on the prompt tokens and positions, so reuse is exact — for
        # sampled requests too. Capacity stays sound with zero
        # accounting changes: admission still reserves the WORST-CASE
        # page budget (sharing saves compute and physical pages, not
        # reservation), and registry pins are evicted LRU on demand —
        # excluding the entry being shared from — which is always
        # sufficient because every other allocation is within its own
        # reservation.
        self._prefix_enabled = prefix_cache
        # Radix trie over page-sized token blocks (NOT a dict of
        # full-prefix tuples: that costs O(len^2/page) hashing under
        # the lock per admission/registration). Node 0 is the root;
        # each node owns its out-edges {block_tuple: child_id} plus an
        # optional HBM entry {"pages": pinned page list, "last_used":
        # LRU stamp} and an optional host-tier record (rung 24b).
        # Lookup and registration walk the prompt once — O(len(prompt))
        # total hashing — and eviction prunes edge-less, entry-less,
        # host-less nodes upward so the trie never outlives its
        # residents. Node ids are monotonic and NEVER reused: the
        # journal's shadow store keys on them across evictions.
        self._prefix_nodes: dict[int, dict] = {
            0: {"parent": None, "edges": {}, "entry": None,
                "host": None},
        }
        self._prefix_entry_nodes: dict[int, dict] = {}  # id -> entry
        self._prefix_next_id = 1
        self._prefix_hits = 0
        self._prefix_lookups = 0
        self._prefix_tokens_saved = 0
        self._prefix_cow_copies = 0
        self._prefix_registrations = 0  # persistence dirty counter
        # Tiered residency (rung 24b): cold entries demote to host RAM
        # as the verbatim swapout bytes instead of being dropped, up to
        # ``prefix_host_mb`` (0 = off — evictions drop, exactly the
        # pre-rung behavior). A hit on a host-resident entry promotes
        # it back into fresh pinned pages at admission.
        if prefix_host_mb < 0:
            raise ValueError("prefix_host_mb must be >= 0")
        self._prefix_host_budget = int(prefix_host_mb) << 20
        self._prefix_host_nodes: dict[int, dict] = {}  # id -> record
        self._prefix_host_bytes = 0
        self._prefix_demotions = 0
        self._prefix_promotions = 0
        self._prefix_evictions = {
            "admission": 0, "pressure": 0, "revive": 0,
            "host_lru": 0, "host_over": 0,
        }
        # Live-sharer leases (rung 24 pricing): _reserved counts each
        # request's PRIVATE worst case plus ONE unit per distinct
        # shared prefix page any live request's table starts on —
        # shared pages are billed once, which is what lets page-gated
        # admission price an arrival at pages_needed − shared. The
        # unit belongs to the LEASE, not a request: it frees when the
        # last sharer releases, so an inheritor never loses coverage
        # because the creator finished first.
        self._lease: dict[int, int] = {}
        # Journal shadow store (rung 24c): trie node id -> the shared
        # prefix pages' verbatim swapout bytes, refcounted by the
        # journal entries that REFERENCE them instead of duplicating
        # them. Priced once against the journal budget (adjust_extra).
        self._prefix_shadow: dict[int, dict] = {}
        self._persist_stop: threading.Event | None = None
        self._persist_thread: threading.Thread | None = None
        self._spec_decision: dict | None = None
        # Registry pins live OUTSIDE any request's reservation, so the
        # cache needs a way to reclaim them when a mid-decode grow finds
        # the free list empty — otherwise one tenant's growth would
        # poison the whole server (see _relieve_pool_pressure_locked).
        self._cache.pressure_relief = self._relieve_pool_pressure_locked
        if tracer is not None:
            # Share the recorder with the cache: a slice-aware cache
            # (runtime/sliceserve.py) stamps per-op broadcast spans so
            # a slow follower is attributable; single-host caches
            # simply ignore the attribute.
            self._cache.tracer = tracer
        self._pages_total = pages
        self._reserved = 0  # worst-case pages of every in-flight request
        # Lock discipline ([payload] serving_debug_locks, SERVING.md
        # rung 19): the ownership-asserting DebugLock makes every
        # *_locked call and every Condition wait/notify verify the
        # calling thread actually holds the lock — the runtime twin of
        # the locklint static analyzer. Plain Lock in production.
        if debug_locks:
            from kvedge_tpu.runtime.debuglock import DebugLock
            self._lock = DebugLock()
        else:
            self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        # Admission scheduler (models/scheduler.py, SERVING.md rung 17):
        # per-class ticketed queue + preemption/shed policy. It SHARES
        # the server lock — queue order, slot state, and page
        # accounting mutate atomically together (invariant 5). With the
        # defaults (strict policy, single implicit class, no
        # watermarks, no swap budget) it degenerates to a fair FIFO:
        # every pre-scheduler exactness test runs unchanged on top of
        # it.
        self._sched = AdmissionScheduler(
            self._lock, policy=sched_policy, weights=sched_weights,
            max_queue_depth=sched_max_queue_depth,
            max_queue_wait_s=sched_max_queue_wait_s,
            swap_budget_mb=sched_swap_budget_mb,
            tracer=tracer,
        )
        # Host bytes one swapped-out page costs (k + v + int8 scale
        # slabs) — victim-sized budget checks BEFORE paying the device
        # gather. Filled lazily: the pool arrays exist after the cache
        # does.
        self._swap_page_bytes: int | None = None
        self._active: dict[int, _Request] = {}
        # Min-heap: allocation always takes the LOWEST free slot, so the
        # occupied set stays dense at the bottom of the batch dim — the
        # property that lets the bucket step back down when load drops.
        self._free_slots = list(range(slots))
        self._closed = False
        self._draining = False
        # Degraded mode (runtime/failures.py): a decode-loop failure
        # poisons the pool — in-flight waiters get the typed failure,
        # new submits are refused with a retry-after hint, and the
        # reason is exposed lock-free so /healthz can flip to 503
        # without touching the server lock.
        self._poison: ServingFailure | None = None
        self._degraded_reason: str | None = None
        # Optional observer (set by the workload layer): called once,
        # outside the lock, when the pool poisons — e.g. to persist a
        # post-mortem failure record in the state dir.
        self.on_degraded = None
        # Retry-after hint for poisoned-pool refusals: a static default
        # ([payload] serving_retry_after_s; None = taxonomy default),
        # overridden live by ``retry_after_hint`` — a () -> float|None
        # callable the recovery supervisor installs so refusals carry
        # the MEASURED recovery time while a heal is in flight.
        self._retry_after_s = retry_after_s
        self.retry_after_hint = None
        # Boundary checkpointing (runtime/journal.py, SERVING.md rung
        # 22): every ``checkpoint_every`` pipeline boundaries the loop
        # journals each live request's resumable state — KV pages as
        # the verbatim swapout bytes, token log, pending token,
        # original ticket — so _poison_locked can DIVERT journaled
        # requests (waiters stay parked) and revive()/reform re-admits
        # them bit-identically instead of failing them. 0 = off:
        # today's fail-everything poison semantics, zero cost.
        if checkpoint_every < 0:
            raise ValueError("checkpoint_every must be >= 0")
        if journal_budget_mb < 0:
            raise ValueError("journal_budget_mb must be >= 0")
        self._checkpoint_every = int(checkpoint_every)
        self._journal = RequestJournal(
            max_bytes=journal_budget_mb * (1 << 20)
        )
        # Boundaries-or-harvests since the last checkpoint: a saturated
        # overlap pipeline rarely visits a boundary on its own, so the
        # clock also advances per harvested window and an overdue clock
        # collapses the pipeline (_boundary_wanted_locked) — cadence N
        # means "at most ~N windows of decode progress ever at risk".
        self._ckpt_clock = 0
        self._checkpoints_total = 0
        self._checkpoint_skipped = 0
        # Delta-skipped checkpoints (rung 26): live requests whose
        # standing journal entry already matches (gen_len, next_token)
        # — re-serializing would be byte-identical, so the boundary
        # skips their device gather entirely.
        self._checkpoints_unchanged = 0
        self._journal_restores = 0
        # Page-conservation audit ([payload] serving_debug_pages): the
        # chaos soak's invariant 1, checked at every quiescent boundary
        # and raised as a typed PageAccountingError on violation.
        self._debug_pages = bool(debug_pages)
        # The capacity bucket rung at poison time: revive restores it
        # (instead of resetting to the bottom rung) so a loaded server
        # doesn't pay a retrace storm the moment traffic returns.
        self._prebucket = 0
        # Recorded by start_prefix_persistence so a poisoned-but-
        # readable pool can emergency-dump its warm prefixes on the
        # way down.
        self._persist_path: str | None = None
        self._persist_fp: str | None = None
        # Admissions whose chunked prefill is in flight (slot granted,
        # not yet in _active): the decode loop must not exit — and a
        # drain must not report done — while any exist, or their
        # waiters would hang on a request no loop will ever serve.
        self._prefilling = 0
        # SLO engine (runtime/slo.py, SERVING.md rung 25): rolling
        # multi-window SLIs from deltas of the cumulative histograms
        # above, fed one snapshot per quiescent boundary. None = off
        # (the default) — the boundary feed guards on it, so off costs
        # one attribute read per boundary and tokens are bit-identical.
        self._slo = None
        if slo is not None:
            from kvedge_tpu.runtime.slo import SloEngine
            self._slo = SloEngine(slo)
            if slo_shed:
                # Knob-gated burn-rate input to the rung-17 shed
                # decision: while the multi-window alert fires,
                # non-top classes shed at the door. Off by default —
                # the scheduler's burn_input stays None and every
                # shed path is byte-for-byte the rung-17 one.
                self._sched.burn_input = self._slo.alert
        elif slo_shed:
            raise ValueError("slo_shed needs SLO objectives (slo=...)")
        # Occupancy timeline ring (rung 25): HBM/page/bucket/prefix
        # residency gauges sampled at quiescent boundaries. 0 = off.
        # With tracing on, the ring doubles as the Chrome counter
        # track source so Perfetto draws occupancy under the spans.
        self._occ_ring = None
        if occupancy_ring:
            from kvedge_tpu.runtime.slo import OccupancyRing
            self._occ_ring = OccupancyRing(occupancy_ring)
            if tracer is not None:
                tracer.counter_source = self._occ_ring.chrome_counters
        if debug_locks:
            # Wrap every bound *_locked method (server AND the
            # scheduler sharing its lock) to assert ownership at call
            # time — executed L1, before the decode thread exists so
            # the loop only ever sees the checked bindings.
            from kvedge_tpu.runtime.debuglock import (
                instrument_locked_methods,
            )
            instrument_locked_methods(self, self._lock)
            instrument_locked_methods(self._sched, self._lock)
        # Installed AFTER lock instrumentation so the journal's drop
        # observer is the (possibly ownership-checked) bound method.
        # Every journal call site holds the work lock, so the observer
        # runs under it too.
        self._journal.on_drop = self._journal_drop_locked
        self._thread = threading.Thread(
            target=self._loop, name="kvedge-paged-serve", daemon=True
        )
        self._thread.start()

    # ---- public API ------------------------------------------------------

    def submit(self, prompt: list[int], n_new: int,
               timeout: float = 120.0, sampling: tuple | None = None,
               priority: str = "interactive",
               deadline_ms: int | None = None,
               request_id: str = "",
               stop_token: int | None = None) -> list[int]:
        """Blocking generate: returns the prompt plus UP TO ``n_new``
        generated tokens.

        Greedy unless ``sampling = (seed_key, temperature, top_p)`` —
        then token ``t`` samples with ``fold_in(seed_key, t)`` through
        the same nucleus filter as the contiguous backend, so the two
        produce identical tokens for identical requests.

        ``stop_token`` ends generation early: the first produced
        occurrence is emitted as the final token and the rest of the
        budget goes unused (admission still reserves the worst case —
        early stops return pages sooner, they never change capacity
        semantics). Detection runs ON DEVICE inside the capped window
        scans and comes back in the window's packed finish rows, so a
        stop costs no extra host work per token.

        ``priority`` names the request's scheduling class
        (``interactive``/``batch``); ``deadline_ms`` optionally bounds
        the ADMISSION wait tighter than ``timeout`` and lets the
        scheduler shed the request up front when the measured queue
        wait makes the deadline unmeetable. Raises :class:`ServerBusy`
        when capacity doesn't free up in time (a subclass,
        :class:`ServerOverloaded`, when shed early by the overload
        watermarks), ValueError for requests that can never fit.
        """
        req = self._start(prompt, n_new, timeout, sampling,
                          stream=False, priority=priority,
                          deadline_ms=deadline_ms,
                          request_id=request_id,
                          stop_token=stop_token)
        req.done.wait()
        if req.error is not None:
            raise req.error
        return req.prompt + req.generated

    def submit_stream(self, prompt: list[int], n_new: int,
                      timeout: float = 120.0,
                      sampling: tuple | None = None,
                      priority: str = "interactive",
                      deadline_ms: int | None = None,
                      request_id: str = "",
                      stop_token: int | None = None) -> "StreamHandle":
        """Streaming generate: an iterator yielding each generated token
        as it lands, with a ``cancel()`` method.

        Same admission/sampling/priority semantics as :meth:`submit`. A
        consumer that merely stops iterating leaves the request decoding
        out its reserved budget (co-tenants are never perturbed); a
        consumer that KNOWS the client is gone calls ``cancel()`` and
        the request releases its slot and pages at the next step/window
        boundary — or immediately if it is still parked in the
        admission queue or swapped out. A mid-stream failure raises
        from the iterator after the tokens already produced.
        """
        req = self._start(prompt, n_new, timeout, sampling,
                          stream=True, priority=priority,
                          deadline_ms=deadline_ms,
                          request_id=request_id,
                          stop_token=stop_token)
        return StreamHandle(self, req)

    def cancel(self, req: _Request) -> None:
        """Ask the decode loop to drop a request at the next boundary.

        Idempotent, and a no-op for a request that already finished. The
        waiter (blocked ``submit`` / stream consumer) gets
        :class:`RequestCancelled`.
        """
        with self._work:
            req.cancelled = True
            # Cancel-while-swapped-out (or parked in the journal of a
            # poisoned pool awaiting revive): the request holds no slot
            # and no reservation — only a host snapshot. Free it here
            # (no decode-loop boundary will ever see this request
            # again) and fail the waiter.
            dropped = self._sched.drop_swapped_locked(req) is not None
            if not dropped and req not in self._active.values():
                dropped = self._journal.pop(req) is not None
            if dropped:
                req.error = RequestCancelled(
                    "request cancelled while swapped out"
                )
                if req.stream is not None:
                    req.stream.put(req.error)
                req.done.set()
            # Cancel-while-parked: the waiter owns its ticket — wake
            # every parked thread so the cancelled one can dequeue
            # itself without consuming a slot or reservation.
            self._sched.wake_all_locked()
            self._work.notify_all()

    def _refusal(self) -> Exception:
        """The typed refusal a new/interrupted request gets (lock
        held): a poisoned pool beats plain ServerClosed — the client
        learns it may retry (against the rescheduled pod) and how long
        to wait, instead of a terminal-looking shutdown error."""
        if self._poison is not None:
            hint = None
            if self.retry_after_hint is not None:
                try:
                    hint = self.retry_after_hint()
                except Exception:
                    hint = None
            if hint is None:
                hint = self._retry_after_s
            e = PoolPoisoned(
                f"serving pool is poisoned ({self._degraded_reason}); "
                f"queue depth [{self._sched.depth_text_locked()}]; "
                f"retry against the recovered or rescheduled pod",
                **({} if hint is None else {"retry_after_s": hint}),
            )
            e.__cause__ = self._poison
            return e
        return ServerClosed(
            "server is draining" if self._draining
            else "server is shut down"
        )

    def _retry_hint(self) -> float | None:
        """The live retry-after hint (lock held): the recovery
        supervisor's measured estimate when installed, else the static
        config default."""
        if self.retry_after_hint is not None:
            try:
                hint = self.retry_after_hint()
            except Exception:
                hint = None
            if hint is not None:
                return hint
        return self._retry_after_s

    def _start(self, prompt: list[int], n_new: int, timeout: float,
               sampling: tuple | None, stream: bool,
               priority: str = "interactive",
               deadline_ms: int | None = None,
               request_id: str = "",
               stop_token: int | None = None) -> _Request:
        if not prompt or n_new < 1:
            raise ValueError("need a non-empty prompt and n_new >= 1")
        if stop_token is not None and stop_token < 0:
            raise ValueError("stop_token must be >= 0 (or None)")
        self._sched.rank(priority)  # unknown classes fail fast
        if deadline_ms is not None and deadline_ms < 1:
            raise ValueError("deadline_ms must be >= 1")
        total = len(prompt) + n_new
        if total > self._cfg.max_seq:
            raise ValueError(
                f"prompt ({len(prompt)}) + n_new ({n_new}) exceeds the "
                f"model's max_seq ({self._cfg.max_seq})"
            )
        pages_needed = self._pages_needed(
            total, self._spec > 0 and sampling is None
        )
        if pages_needed > self._cache.max_pages_per_seq:
            raise ValueError(
                f"request needs {pages_needed} pages > max_pages_per_seq "
                f"= {self._cache.max_pages_per_seq}"
            )
        if pages_needed > self._pages_total:
            raise ValueError(
                f"request needs {pages_needed} pages > pool size "
                f"{self._pages_total}"
            )

        import jax.numpy as jnp

        tr = self.tracer
        req = _Request(
            prompt=list(prompt), n_new=n_new, sampling=sampling,
            stop_token=-1 if stop_token is None else int(stop_token),
            pages_reserved=pages_needed,
            key_data=_raw_key_data(sampling[0]) if sampling else None,
            stream=queue.SimpleQueue() if stream else None,
            pclass=priority,
            rid=request_id,
            # The per-request sampling decision, made ONCE here: all of
            # this request's spans share fate, and a caller-replayed
            # X-Request-Id traces (or not) identically everywhere.
            trace=tr is not None and tr.sampled(request_id),
            t_submit=time.perf_counter(),
        )
        deadline = time.monotonic() + timeout
        if deadline_ms is not None:
            deadline = min(deadline,
                           time.monotonic() + deadline_ms / 1000.0)
        with self._work:
            if self._closed or self._draining:
                raise self._refusal()
            # Overload shedding: reject BEFORE parking when the queue
            # watermarks say the wait is hopeless, with the measured
            # per-class wait as the retry hint (falling back to the
            # recovery machinery's hint).
            shed = self._sched.shed_check_locked(priority, deadline_ms,
                                                 rid=request_id)
            if shed is None:
                # Page-watermark shed (capacity semantics, SERVING.md
                # rung 21): when granting this request's worst-case
                # reservation would push free-page headroom below the
                # low watermark, non-top-priority arrivals shed with
                # page terms instead of parking behind a pool that
                # cannot absorb them. The top class always parks — it
                # is what the preemption path frees pages FOR. The
                # price is the arrival's MARGINAL cost (rung 24): its
                # private budget plus the lease units its shared
                # prefix pages would newly pin — a mostly-cached
                # prompt no longer sheds at full pages_needed.
                self._prefix_lookups += 1
                _, shared0, st0, _ = self._prefix_lookup(req.prompt)
                shed = self._page_shed_locked(
                    priority,
                    self._admission_price_locked(
                        pages_needed, shared0, st0),
                )
            if shed is not None:
                hint = shed["retry_after_s"]
                if hint is None:
                    hint = self._retry_hint()
                raise ServerOverloaded(
                    f"request shed: {shed['reason']}; "
                    f"{self._capacity_text_locked()}; queue depth "
                    f"[{self._sched.depth_text_locked()}]"
                    + (f"; retry after ~{hint:.1f}s" if hint is not None
                       else ""),
                    retry_after_s=hint,
                )
            # Ticketed admission (SERVING.md rung 17): park on a
            # per-class FIFO ticket and wait on the TICKET's condition.
            # Only the policy head is ever woken, and only the head
            # takes capacity — admission order is the queue's order,
            # not the lock's (the notify_all fairness fix). The decode
            # loop preempts a lower-class slot at a window boundary
            # when this ticket is head and cannot fit.
            ticket = self._sched.enqueue_locked(req, priority,
                                                pages_needed)
            req.ticket_no = ticket.no
            if (not self._free_slots
                    or self._reserved + pages_needed
                    > self._pages_total):
                # Actually parking: kick the decode loop so the next
                # boundary can consider preempting for this ticket.
                # (The uncontended admit must NOT wake the loop — it
                # adds nothing and perturbs the seed path's timing.)
                self._work.notify_all()
            try:
                while True:
                    if self._closed or self._draining:
                        raise self._refusal()
                    if req.cancelled:
                        raise RequestCancelled(
                            "request cancelled while queued for "
                            "admission"
                        )
                    # Re-priced each wake: the trie changes while this
                    # ticket parks, so the marginal cost (private
                    # budget + unleased shared pages) and the HBM-hot
                    # flag both refresh here. A hot non-head ticket may
                    # be admitted past a head STARVED for capacity
                    # (prefix affinity, rung 24d) — bounded by the
                    # scheduler's bypass cap so the head cannot starve
                    # behind an endless hot stream.
                    _, shared_w, st_w, _ = self._prefix_lookup(
                        req.prompt)
                    price = self._admission_price_locked(
                        pages_needed, shared_w, st_w)
                    ticket.hot = st_w > 0
                    head = self._sched.head_locked()
                    at_head = head is ticket
                    if not at_head and ticket.hot:
                        at_head = (
                            head is not None
                            and (not self._free_slots
                                 or self._reserved + head.pages_needed
                                 > self._pages_total)
                            and self._sched.bypass_ok_locked(ticket)
                        )
                    if (at_head and self._free_slots
                            and self._reserved + price
                            <= self._pages_total
                            and self._ensure_bucket_locked()):
                        break
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        hint = self._retry_hint()
                        raise ServerBusy(
                            "no page capacity within the timeout "
                            f"({len(self._active)} requests in "
                            f"flight; {self._capacity_text_locked()}; "
                            f"queue depth "
                            f"[{self._sched.depth_text_locked()}]"
                            + (f"; retry after ~{hint:.1f}s"
                               if hint is not None else "") + ")"
                        )
                    ticket.cond.wait(timeout=remaining)
                self._sched.admit_locked(ticket)
                ticket = None  # admitted: the finally must not remove
            finally:
                if ticket is not None:
                    self._sched.remove_locked(ticket)
            req.admit_seq = self._sched.next_admit_seq_locked()
            req.t_admit = time.perf_counter()
            self._hist_queue.observe(
                (req.t_admit - req.t_submit) * 1e3
            )
            slot = heapq.heappop(self._free_slots)
            # Prefix sharing: start the table on the cached prefix's
            # read-only pages and evict LRU registry pins (never the
            # donor entry) until the free list covers this request's
            # full PRIVATE budget — so later grows can never starve on
            # registry-held pages. A host-tier match deeper than the
            # HBM one promotes first (best-effort: promotion can never
            # fail the admission — it falls back to the HBM match).
            donor, shared, shared_tokens, host_node = \
                self._prefix_lookup(req.prompt)
            if host_node is not None:
                got = self._promote_host_locked(host_node, {donor})
                if got is not None:
                    donor, shared, shared_tokens = got
            page = self._cache.page_size
            partial = shared_tokens % page != 0
            shared_full = tuple(shared[:-1] if partial else shared)
            private = pages_needed - len(shared_full)
            self._reserved += private
            self._lease_take_locked(shared_full)
            req.pages_reserved = private
            req.shared_pages = shared_full
            if shared_full:
                # The trie node at the full-shared depth: the journal's
                # shadow key. For a partial (COW) match the donor is
                # one level deeper — its parent is the shared path.
                req.prefix_node = (
                    self._prefix_nodes[donor]["parent"][0]
                    if partial else donor
                )
            try:
                self._evict_prefixes_for(private, {donor})
                self._cache.admit(slot, len(req.prompt), shared)
                if partial:
                    # COW divergence (rung 24a): the donor's partial
                    # last page is shared too — copy it device-side
                    # BEFORE the suffix prefill writes into it, so the
                    # registry's original stays immutable. The copy is
                    # within the private budget (it was priced as
                    # owned, never leased).
                    if self._cache.cow_page(slot, len(shared) - 1) \
                            is not None:
                        self._prefix_cow_copies += 1
            except Exception:
                self._release_locked(slot, private, req.shared_pages)
                req.shared_pages = ()
                req.prefix_node = None
                raise
            self._prefilling += 1
            if shared_tokens:
                self._prefix_hits += 1
                self._prefix_tokens_saved += shared_tokens
        # Prefill in chunks, the lock held only PER CHUNK: the decode
        # loop interleaves batched steps for in-flight requests between
        # chunks (they never touch this slot — the loop's active mask
        # excludes anything not yet in self._active), so one admission's
        # long prompt no longer stalls every co-tenant; and XLA compiles
        # one program per CHUNK length instead of per prompt length —
        # a bounded compile surface under arbitrary operator traffic.
        # Each cache call still happens under the lock: cache state
        # mutations must serialize against the step loop.
        chunk = self._prefill_chunk or len(req.prompt)
        activated = False
        t_prefill = time.perf_counter()
        try:
            logits = None
            off = shared_tokens  # cached prefix K/V are already in place
            while off < len(req.prompt):
                piece = req.prompt[off:off + chunk]
                with self._work:
                    if self._closed:
                        raise self._refusal()
                    if req.cancelled:
                        raise RequestCancelled(
                            "request cancelled during prefill"
                        )
                    logits = self._cache.prefill_chunk(
                        self._params, slot,
                        jnp.asarray(piece, jnp.int32), off,
                    )
                off += len(piece)
            with self._work:
                # Re-check under the activation lock: a hard close can
                # land between the last chunk and here, after which no
                # loop is alive to serve (or poison) this request.
                if self._closed:
                    raise self._refusal()
                req.next_token = req.pick(logits, 0)
                t_first = time.perf_counter()
                # Time to first token: submit -> the prefill logits'
                # pick. This is the serving-visible TTFT (the first
                # emission rides the next loop iteration, but the
                # token is decided here). The stamp is kept on the
                # request: finish pairs it with the final token for
                # the per-request inter-token gap (rung 25).
                req.t_first = t_first
                self._hist_ttft.observe((t_first - req.t_submit) * 1e3)
                if req.trace:
                    self.tracer.span(
                        "prefill", "serve", t_prefill, t_first,
                        rid=req.rid,
                        args={"prompt": len(req.prompt),
                              "shared": shared_tokens,
                              "class": req.pclass},
                    )
                self._active[slot] = req
                self._note_finish_candidate_locked(slot, req)
                self._prefilling -= 1
                activated = True
                # The fully-prefilled prompt's page-aligned prefixes
                # are now reusable K/V: pin and register them.
                self._register_prefixes(
                    req.prompt, self._cache.slot_pages(slot)
                )
                self._work.notify_all()  # wake the decode loop
        except Exception as e:
            with self._work:
                if not activated:
                    self._prefilling -= 1
                    self._release_locked(slot, req.pages_reserved,
                                         req.shared_pages)
                    req.shared_pages = ()
                    req.prefix_node = None
                if (isinstance(e, ServingFailure)
                        and not e.retryable):
                    # A terminal failure on the SUBMIT path (the op
                    # watchdog can fire during this request's prefill,
                    # not just in the decode loop) kills the pool for
                    # everyone: poison co-tenants now with the typed
                    # error rather than letting them ride a dead cache
                    # into the same failure one window later.
                    self._poison_locked(e)
            raise
        return req

    # ---- capacity semantics (SERVING.md rung 21) ------------------------

    def _capacity_text_locked(self) -> str:
        """Page-capacity terms for refusal payloads: pages, not slots,
        gate admission now, so a refused caller learns the pool state
        it is actually queued behind."""
        free = self._pages_total - self._reserved
        return (f"{free}/{self._pages_total} pages unreserved, "
                f"bucket {self._cache.bucket}/{self._cache.slots} rows")

    def _page_shed_locked(self, priority: str,
                          pages_needed: int) -> dict | None:
        """Low-watermark page shed: None (park) or a shed record in the
        scheduler's shape. Top-priority arrivals never page-shed —
        preemption exists to free pages for exactly them."""
        if not self._page_low_wm or self._sched.rank(priority) == 0:
            return None
        free_after = self._pages_total - self._reserved - pages_needed
        if free_after >= self._page_low_wm * self._pages_total:
            return None
        self._sched.shed += 1
        return {
            "reason": (
                f"free-page headroom below the low watermark "
                f"({free_after} of {self._pages_total} pages would "
                f"stay unreserved, watermark {self._page_low_wm:.0%})"
            ),
            "retry_after_s": None,
        }

    def _resume_pages_ok_locked(self, pages_needed: int) -> bool:
        """High-watermark resume gate: a preempted request swaps back
        in only when doing so leaves free-page headroom at or above
        the HIGH watermark — the hysteresis that stops a pool hovering
        at the low watermark from thrashing preempt/resume cycles."""
        if not self._page_high_wm:
            return True
        free_after = self._pages_total - self._reserved - pages_needed
        return free_after >= self._page_high_wm * self._pages_total

    def _ensure_bucket_locked(self) -> bool:
        """Admission's bucket clause: True iff a free slot INSIDE the
        current device bucket exists. When every free slot lies above
        the bucket, resize directly if the cache is quiescent (serial
        loop, or an idle pipeline); otherwise flag the step-up for the
        decode loop's next boundary and keep the caller parked — it is
        woken when the resize lands."""
        if self._free_slots and self._free_slots[0] < self._cache.bucket:
            return True
        if not self._free_slots:
            return False
        # With nothing dispatched-unharvested the resize is safe here:
        # the loop's next dispatch at a boundary is always first=True
        # (host tokens), so the carry set_bucket drops was dead anyway.
        if self._inflight is None and not self._cache.spec_pending():
            self._cache.set_bucket(
                self._cache.bucket_for(self._free_slots[0] + 1)
            )
            return True
        self._bucket_step_wanted = True
        self._work.notify_all()
        return False

    def _maybe_step_bucket_locked(self) -> None:
        """Resize the device batch dim at a pipeline boundary: step UP
        when an admission parked on a row above the bucket
        (``_bucket_step_wanted``), step DOWN when the occupied set has
        drained out of the bucket's top half and nothing is queued.
        Quiescent points only; no-op with bucketing disabled."""
        if not self._cache.min_bucket or self._inflight is not None:
            return
        if self._cache.spec_pending():
            return
        bucket = self._cache.bucket
        want = self._cache.rows_in_use()
        if self._bucket_step_wanted and self._free_slots:
            want = max(want, self._free_slots[0] + 1)
        self._bucket_step_wanted = False
        target = self._cache.bucket_for(want)
        if target > bucket or (target < bucket
                               and self._sched.head_locked() is None):
            self._cache.set_bucket(target)
            self._sched.wake_head_locked()
            self._work.notify_all()

    # ---- boundary checkpoints + page audit (SERVING.md rung 22) ----------

    def _maybe_checkpoint_locked(self) -> None:
        """Quiescent-boundary durability hook (lock held, nothing in
        flight): audit page conservation when asked, then — every
        ``checkpoint_every`` clock ticks — journal each live request's
        resumable state. The KV snapshot is the SAME verbatim-bytes
        gather preemption swaps out (``swapout_pages``, int8 scale
        slabs included), taken on the live slot without releasing it;
        ``saved_len`` covers exactly the committed positions, with the
        pending token stored host-side — the preempt/resume contract,
        which is why restore is bit-identical for free."""
        if self._debug_pages:
            self._audit_pages_locked()
        if not self._checkpoint_every:
            return
        self._ckpt_clock += 1
        if self._ckpt_clock < self._checkpoint_every:
            return
        self._ckpt_clock = 0
        if not self._active:
            return
        t0 = time.perf_counter()
        # Host-path elimination (rung 26): the old loop issued one
        # device gather + one forced transfer PER live request, every
        # checkpoint tick, even when nothing had changed. Two fixes:
        #
        # * Delta-skip — a request whose (gen_len, next_token) match
        #   its standing entry would re-serialize byte-identical state
        #   (decode only appends; KV below saved_len never mutates, and
        #   spec slack past saved_len is outside the restore contract),
        #   so it keeps the old entry at zero device work. A quiescent
        #   boundary now costs O(changes), not O(live).
        # * Coalesced gather — every page the boundary DOES need
        #   (own suffixes + any new prefix shadows, deduped per node)
        #   rides ONE ``swapout_pages`` call: one device program, one
        #   forced transfer, sliced per entry on host. The slices are
        #   compacted copies so the journal's byte accounting stays
        #   honest (a view would pin the whole batch buffer).
        jobs = []       # (req, saved_len, n_pages, own_span, sh_spans)
        all_ids = []
        new_shadow_spans: dict = {}   # node -> (start, sh_n)
        for slot, req in self._active.items():
            if req.cancelled:
                continue
            saved_len = len(req.prompt) + len(req.generated)
            prev = self._journal.get(req)
            if (prev is not None
                    and prev.gen_len == len(req.generated)
                    and prev.next_token == req.next_token):
                self._checkpoints_unchanged += 1
                continue
            n_pages = -(-saved_len // self._cache.page_size)
            ids = self._cache.slot_pages(slot)[:n_pages]
            sh_n = len(req.shared_pages)
            shared = req.prefix_node is not None and sh_n
            own_ids = ids[sh_n:] if shared else ids
            own_span = (len(all_ids), len(own_ids))
            all_ids.extend(own_ids)
            node = None
            if shared:
                node = req.prefix_node
                if (node not in self._prefix_shadow
                        and node not in new_shadow_spans):
                    new_shadow_spans[node] = (len(all_ids), sh_n)
                    all_ids.extend(ids[:sh_n])
            jobs.append((req, saved_len, sh_n if shared else 0, node,
                         own_span))
        if not jobs:
            return
        batch = (self._cache.swapout_pages(all_ids)
                 if all_ids else None)

        def _slice(span):
            # Gathered slabs are [L, n_pages, ...] (_gather_pages_impl)
            # — the page dimension is axis 1, layers axis 0.
            start, n = span
            return tuple(np.ascontiguousarray(a[:, start:start + n])
                         for a in batch)

        new_shadows = {node: _slice(span)
                       for node, span in new_shadow_spans.items()}
        for req, saved_len, sh_n, node, own_span in jobs:
            own = _slice(own_span)
            if node is not None:
                ok = self._checkpoint_shared_locked(
                    req, saved_len, sh_n, own,
                    new_shadows.get(node))
            else:
                entry = JournalEntry(
                    req=req, pclass=req.pclass,
                    ticket_no=req.ticket_no,
                    admit_seq=req.admit_seq,
                    pages_reserved=req.pages_reserved,
                    saved_len=saved_len, gen_len=len(req.generated),
                    next_token=req.next_token,
                    emitted=len(req.generated),
                    arrays=own,
                    nbytes=sum(a.nbytes for a in own),
                )
                ok = self._journal.put(req, entry)
            if ok:
                self._checkpoints_total += 1
            else:
                # Budget-refused: the request keeps its previous
                # (older but internally consistent) entry, or stays
                # unjournaled — counted so operators see the bound
                # biting.
                self._checkpoint_skipped += 1
        if self.tracer is not None:
            self.tracer.span(
                "checkpoint", "serve", t0,
                args={"live": len(self._active),
                      "entries": len(self._journal),
                      "bytes": self._journal.nbytes},
            )

    def _checkpoint_shared_locked(self, req: _Request,
                                  saved_len: int, sh_n: int,
                                  own: tuple,
                                  sh_arrays: tuple | None) -> bool:
        """Checkpoint a request whose table starts on cached-prefix
        pages (lock held): the entry carries only the request's OWN
        page bytes plus a REFERENCE (trie node id + page/token depth)
        into a per-node shadow snapshot of the shared bytes, taken
        once and refcounted across every entry that cites it — N
        requests on one system prompt bill the journal budget 1 shadow
        + N suffixes, not N full copies (rung 24c). Refs bump BEFORE
        ``put`` so the on_drop of a replaced older entry (which fires
        inside ``put``) nets correctly when both cite the same node.
        ``own``/``sh_arrays`` arrive pre-gathered from the boundary's
        single coalesced ``swapout_pages`` batch; ``sh_arrays`` is
        only consulted when the node's shadow does not exist yet."""
        node = req.prefix_node
        shadow = self._prefix_shadow.get(node)
        extra = 0
        if shadow is None:
            if sh_arrays is None:
                # Should be unreachable — the batching loop gathers
                # shadow bytes for every node it cannot find — but a
                # refused-then-retried node races only against itself,
                # so refuse rather than journal a dangling reference.
                return False
            extra = sum(a.nbytes for a in sh_arrays)
            shadow = {"arrays": sh_arrays, "nbytes": extra,
                      "refs": 0, "npages": sh_n}
            self._prefix_shadow[node] = shadow
        shadow["refs"] += 1
        entry = JournalEntry(
            req=req, pclass=req.pclass, ticket_no=req.ticket_no,
            admit_seq=req.admit_seq,
            pages_reserved=req.pages_reserved,
            saved_len=saved_len, gen_len=len(req.generated),
            next_token=req.next_token, emitted=len(req.generated),
            arrays=own, nbytes=sum(a.nbytes for a in own),
            prefix_node=node, prefix_pages_n=sh_n,
            prefix_tokens=sh_n * self._cache.page_size,
        )
        if self._journal.put(req, entry, extra=extra):
            if extra:
                self._journal.adjust_extra(extra)
            return True
        shadow["refs"] -= 1
        if shadow["refs"] <= 0:
            # Freshly created for this refused entry — unwind it
            # without billing (extra was never adjusted in).
            del self._prefix_shadow[node]
        return False

    def _journal_drop_locked(self, entry) -> None:
        """Journal entry-drop observer (lock held, wired to
        ``RequestJournal.on_drop``): settle a dropped entry's prefix
        reference — the last citation of a shadow snapshot releases
        its bytes from the budget. Fires on put-replacement and pop;
        restore settles drained entries itself after re-admission."""
        node = entry.prefix_node
        if node is None:
            return
        shadow = self._prefix_shadow.get(node)
        if shadow is None:
            return
        shadow["refs"] -= 1
        if shadow["refs"] <= 0:
            del self._prefix_shadow[node]
            self._journal.adjust_extra(-shadow["nbytes"])

    def _audit_pages_locked(self) -> None:
        """Assert page conservation at a quiescent boundary (lock
        held): free + live == pages_total with clean books. Raises the
        typed :class:`PageAccountingError` — the decode loop's normal
        failure path poisons the pool with it, so a leak is loud and
        attributable to the boundary that found it."""
        acct_fn = getattr(self._cache, "page_accounting", None)
        if acct_fn is None:  # injected cache without the census
            return
        acct = acct_fn()
        if (acct["free"] + acct["live"] == acct["pages_total"]
                and not acct["free_dup"] and not acct["neg_refs"]
                and not acct["free_live"]):
            return
        raise PageAccountingError(
            f"page conservation violated at a quiescent boundary: "
            f"free={acct['free']} live={acct['live']} "
            f"total={acct['pages_total']} dup_free={acct['free_dup']} "
            f"neg_refs={acct['neg_refs']} "
            f"free_but_live={acct['free_live']}"
        )

    def _divert_to_journal_locked(self, req: _Request) -> bool:
        """Poison-path diversion (lock held): True when ``req`` has a
        checkpoint to resume from — its waiter stays parked across the
        outage and revive() re-admits it. Records the exactly-once
        watermark: every token in ``generated`` RIGHT NOW (including
        post-checkpoint decode) was already delivered, so the replay
        must not re-stream below this count."""
        if req.cancelled:
            return False
        entry = self._journal.get(req)
        if entry is None:
            return False
        entry.emitted = max(entry.emitted, len(req.generated))
        return True

    def _journal_swapped_locked(self, entry) -> bool:
        """Move a swapped-out request's snapshot into the journal at
        poison time (lock held): the scheduler entry already holds the
        verbatim host bytes, saved length, and original ticket — a
        ready-made checkpoint. False (caller fails the request and
        frees the snapshot) when checkpointing is off, the request was
        cancelled, or the journal budget refuses the bytes."""
        if not self._checkpoint_every or entry.req.cancelled:
            entry.arrays = ()
            return False
        req = entry.req
        je = JournalEntry(
            req=req, pclass=entry.pclass, ticket_no=entry.no,
            admit_seq=req.admit_seq,
            pages_reserved=entry.pages_needed,
            saved_len=entry.saved_len, gen_len=len(req.generated),
            next_token=req.next_token, emitted=len(req.generated),
            arrays=entry.arrays, nbytes=entry.nbytes,
        )
        if not self._journal.put(req, je):
            self._checkpoint_skipped += 1
            entry.arrays = ()
            return False
        self._checkpoints_total += 1
        return True

    def _fail_journal_locked(self, err: Exception) -> None:
        """Fail every journaled waiter (lock held) — the close() path
        of a pool that will never be revived. Without this, diverted
        requests would park forever behind a teardown."""
        for entry in self._journal.take_all():
            self._journal_drop_locked(entry)
            req = entry.req
            if req.done.is_set():
                continue
            req.error = err
            if req.stream is not None:
                req.stream.put(err)
            req.done.set()

    def capacity_probe(self) -> dict:
        """Lock-free capacity snapshot for /healthz: like
        :attr:`degraded`, bare attribute reads only — a health probe
        must answer even when a thread is misbehaving around the
        server lock — so values may be one boundary stale.
        ``pages_free`` is UNRESERVED pages (the admission resource a
        load balancer drains on), not the device free list."""
        return {
            "pages_free": max(self._pages_total - self._reserved, 0),
            "pages_total": self._pages_total,
            "bucket": self._cache.bucket,
        }

    def _poison_locked(self, failure: ServingFailure) -> None:
        """Poison the pool (lock held): every in-flight waiter gets the
        typed failure, the degraded flag flips for stats/healthz, and
        admission waiters wake to fail fast with _refusal()'s
        retry-after hint. The exiting decode loop runs _degrade() for
        the outside-the-lock cleanup (emergency dump, observer)."""
        if self._poison is None:
            self._poison = failure
            self._degraded_reason = f"{type(failure).__name__}: {failure}"
            # Satellite of rung 22: remember the capacity rung so
            # revive restores it instead of resetting to the bottom.
            self._prebucket = self._cache.bucket
        # Rung 22 diversion: a request with a journal checkpoint is
        # NOT failed — its waiter stays parked (done unset, stream
        # quiet) and revive() re-admits it from the checkpoint,
        # replaying the post-checkpoint gap bit-identically. Requests
        # the journal never caught (cadence, budget skip, checkpointing
        # off) fail exactly as before.
        survivors = 0
        failed = 0
        for req in self._active.values():
            if self._divert_to_journal_locked(req):
                survivors += 1
                continue
            failed += 1
            req.error = failure
            if req.stream is not None:
                req.stream.put(failure)
            req.done.set()
        self._active.clear()
        # Degraded mode reaches the swap set too (rung 14 x rung 17):
        # a swapped-out request's device pages are gone and no healthy
        # loop will ever resume it. Its host snapshot is ALREADY a
        # verbatim checkpoint under the original ticket — with
        # checkpointing on it moves into the journal; otherwise fail
        # it like an active one and free the snapshot.
        for entry in self._sched.take_swapped_locked():
            if self._journal_swapped_locked(entry):
                survivors += 1
                continue
            failed += 1
            entry.req.error = failure
            if entry.req.stream is not None:
                entry.req.stream.put(failure)
            entry.req.done.set()
        if self.tracer is not None:
            # The poison instant anchors the flight-recorder tail the
            # post-mortem (last-failure.json) embeds.
            self.tracer.event(
                "poison", "failure",
                args={"type": type(failure).__name__,
                      "failed": failed,
                      "journaled": survivors},
            )
        self._closed = True
        self._sched.wake_all_locked()
        self._work.notify_all()

    # ---- prefix sharing (lock held for every method here) ----------------

    def _prefix_lookup(self, prompt: list[int]):
        """(donor_node, pages, shared_tokens, host_node) of the best
        cached prefix — capped at len(prompt)-1 so at least one token
        prefills and produces the first-emission logits.

        The walk matches whole page-sized blocks down the radix trie;
        from the deepest walked node it then tries a PARTIAL last
        block against the children's HBM entries (COW divergence,
        rung 24a): an entry whose next block shares >= 1 leading token
        with the remaining prompt lends its partial page too — the
        admission path copies that page device-side before the suffix
        prefill writes into it. ``donor_node`` is the entry whose
        pages are borrowed (the admission's eviction keep-set).
        ``host_node`` is the deepest host-resident entry STRICTLY
        deeper than the HBM match (rung 24b) — admission promotes it
        when it can. One walk: O(len(prompt)) hashing."""
        if not self._prefix_enabled:
            return None, (), 0, None
        page = self._cache.page_size
        node, depth = 0, 0
        best = (None, (), 0)
        host = None
        for k in range(1, (len(prompt) - 1) // page + 1):
            block = tuple(prompt[(k - 1) * page:k * page])
            child = self._prefix_nodes[node]["edges"].get(block)
            if child is None:
                break
            node, depth = child, k
            rec = self._prefix_nodes[node]
            if rec["entry"] is not None:
                rec["entry"]["last_used"] = time.monotonic()
                best = (node, tuple(rec["entry"]["pages"]), k * page)
            if rec["host"] is not None:
                host = (node, k)
        cap = len(prompt) - 1 - depth * page
        if cap > 0:
            tail = prompt[depth * page:(depth + 1) * page]
            best_ov = 0
            for block, child in (
                    self._prefix_nodes[node]["edges"].items()):
                entry = self._prefix_nodes[child]["entry"]
                if entry is None:
                    continue
                ov = 0
                for a, b in zip(tail, block):
                    if a != b:
                        break
                    ov += 1
                ov = min(ov, cap)
                if ov > best_ov:
                    best_ov = ov
                    entry["last_used"] = time.monotonic()
                    best = (child,
                            tuple(entry["pages"][:depth + 1]),
                            depth * page + ov)
        host_node = None
        if host is not None and host[1] * page > best[2]:
            host_node = host[0]
        return best[0], best[1], best[2], host_node

    def _admission_price_locked(self, pages_needed: int, shared,
                                shared_tokens: int) -> int:
        """The MARGINAL page cost of admitting an arrival whose prefix
        lookup matched ``shared`` (lock held): its private budget (a
        partially-shared page's COW copy counts as private) plus one
        lease unit per full shared page no live request leases yet.
        This is what the low-watermark shed and the park-loop capacity
        clause gate on — shared pages already resident and leased are
        free to admit against (rung 24)."""
        page = self._cache.page_size
        full = (shared[:-1] if shared and shared_tokens % page
                else shared)
        new_leases = sum(1 for p in full if p not in self._lease)
        return pages_needed - len(full) + new_leases

    def _trie_child(self, node: int, block: tuple) -> int:
        """The trie child for ``block`` under ``node``, created if
        absent (lock held) — the ONE node-allocation walk step, shared
        by live registration and the persistence loader."""
        child = self._prefix_nodes[node]["edges"].get(block)
        if child is None:
            child = self._prefix_next_id
            self._prefix_next_id += 1
            self._prefix_nodes[node]["edges"][block] = child
            self._prefix_nodes[child] = {
                "parent": (node, block), "edges": {}, "entry": None,
                "host": None,
            }
        return child

    def _register_prefixes(self, prompt: list[int],
                           pages: list[int]) -> None:
        """Pin every page-aligned prefix of committed token state.
        Only full pages covered entirely by the given tokens register
        — later writes land past them (the first grow opens a fresh
        page even at an aligned boundary, and a shared partial page
        COWs before its first write), so registered pages are
        immutable. One walk down the trie: O(len(prompt))."""
        if not self._prefix_enabled:
            return
        page = self._cache.page_size
        node = 0
        for k in range(1, len(prompt) // page + 1):
            block = tuple(prompt[(k - 1) * page:k * page])
            node = self._trie_child(node, block)
            if self._prefix_nodes[node]["entry"] is None:
                held = list(pages[:k])
                self._cache.retain_pages(held)
                entry = {"pages": held, "last_used": time.monotonic()}
                self._prefix_nodes[node]["entry"] = entry
                self._prefix_entry_nodes[node] = entry
                self._prefix_registrations += 1
                if self._prefix_nodes[node]["host"] is not None:
                    # A live registration supersedes a host-tier copy
                    # of the same prefix (K/V are deterministic — the
                    # bytes are identical); keeping both would double-
                    # bill the host budget.
                    self._drop_host_record_locked(node)

    def _insert_prefix_entry(self, tokens: list[int],
                             pages) -> int:
        """Attach ONE registry entry holding ``pages`` at the trie
        node for ``tokens`` (a whole number of blocks), creating path
        nodes as needed (lock held). Ownership transfers: the caller's
        page refs (``allocate_pinned_page``) BECOME the registry pin —
        no extra retain — exactly the host-promotion idiom. Used by
        the journal restore to resurrect a shadow snapshot's shared
        pages as a live cache entry. Returns the node id."""
        page = self._cache.page_size
        node = 0
        for k in range(1, len(tokens) // page + 1):
            node = self._trie_child(
                node, tuple(tokens[(k - 1) * page:k * page]))
        if self._prefix_nodes[node]["entry"] is not None:
            # Already live (another path resurrected it first): the
            # existing pin wins, the caller's refs return to the pool.
            self._cache.release_pages(pages)
            return node
        entry = {"pages": list(pages), "last_used": time.monotonic()}
        self._prefix_nodes[node]["entry"] = entry
        self._prefix_entry_nodes[node] = entry
        self._prefix_registrations += 1
        if self._prefix_nodes[node]["host"] is not None:
            self._drop_host_record_locked(node)
        return node

    def _prune_prefix_upward(self, node: int) -> None:
        """Prune edge-less, entry-less, host-less nodes upward (lock
        held) — the trie never outlives its residents."""
        cur = node
        while cur != 0:
            rec = self._prefix_nodes[cur]
            if (rec["entry"] is not None or rec["host"] is not None
                    or rec["edges"]):
                break
            pid, block = rec["parent"]
            del self._prefix_nodes[cur]
            del self._prefix_nodes[pid]["edges"][block]
            cur = pid

    def _evict_prefix_node(self, node: int, cause: str) -> None:
        """Unpin one HBM entry — demoting its bytes to the host tier
        when the budget allows (rung 24b) — and prune upward. The
        low-watermark/pressure story this implements: cold SHARED
        pages leave HBM (to host, not to nowhere) before any unique
        live victim is preempted, because registry pins are always
        relieved ahead of the preemption path seeing starvation.
        ``cause`` feeds the eviction-by-cause counters; "revive" never
        demotes — the device is suspect after a poison, so only the
        emergency dump's host bytes are trusted."""
        entry = self._prefix_entry_nodes.pop(node)
        self._prefix_evictions[cause] += 1
        rec = self._prefix_nodes[node]
        rec["entry"] = None
        if (self._prefix_host_budget and cause != "revive"
                and rec["host"] is None):
            self._demote_prefix_locked(node, entry)
        self._cache.release_pages(entry["pages"])
        self._prune_prefix_upward(node)

    def _demote_prefix_locked(self, node: int, entry: dict) -> None:
        """Swap an evicted entry's pages to the host tier (lock held):
        the same verbatim as-stored bytes preemption uses (int8 scale
        slabs ride along). Oversize records drop; host-LRU eviction
        makes room otherwise. Best-effort — a failing device gather
        (poisoned pool mid-relief) drops the entry instead of failing
        the caller."""
        try:
            arrays = self._cache.swapout_pages(entry["pages"])
        except Exception:
            return
        nbytes = sum(a.nbytes for a in arrays)
        if nbytes > self._prefix_host_budget:
            self._prefix_evictions["host_over"] += 1
            return
        while (self._prefix_host_bytes + nbytes
               > self._prefix_host_budget):
            lru = min(
                self._prefix_host_nodes,
                key=lambda n: self._prefix_host_nodes[n]["last_used"],
            )
            self._prefix_evictions["host_lru"] += 1
            self._drop_host_record_locked(lru)
        rec = {"arrays": arrays, "nbytes": nbytes,
               "npages": len(entry["pages"]),
               "last_used": entry["last_used"]}
        self._prefix_nodes[node]["host"] = rec
        self._prefix_host_nodes[node] = rec
        self._prefix_host_bytes += nbytes
        self._prefix_demotions += 1

    def _drop_host_record_locked(self, node: int) -> None:
        """Forget a host-tier record and un-bill its bytes (lock
        held), pruning the trie path if nothing else holds it."""
        rec = self._prefix_host_nodes.pop(node)
        self._prefix_host_bytes -= rec["nbytes"]
        self._prefix_nodes[node]["host"] = None
        self._prune_prefix_upward(node)

    def _promote_host_locked(self, node: int, keep) -> tuple | None:
        """Swap a host-resident prefix entry back into HBM at an
        admission hit (rung 24b). Returns the promoted
        (node, pages, shared_tokens), or None — promotion is
        best-effort and must NEVER fail the admission, which falls
        back to the shallower HBM match. Fresh pages come from the
        pinned allocator after an LRU sweep of colder HBM entries
        (never ``keep``); if the free list still cannot cover the
        record, the promotion simply doesn't happen."""
        rec = self._prefix_host_nodes.get(node)
        if rec is None:
            return None
        n = rec["npages"]
        self._evict_prefixes_for(n, keep)
        if self._cache.free_pages() < n:
            return None
        pages = [self._cache.allocate_pinned_page() for _ in range(n)]
        try:
            self._cache.swapin_pages(pages, rec["arrays"])
        except Exception:
            self._cache.release_pages(pages)
            raise
        entry = {"pages": pages, "last_used": time.monotonic()}
        self._prefix_nodes[node]["entry"] = entry
        self._prefix_entry_nodes[node] = entry
        self._prefix_nodes[node]["host"] = None
        self._prefix_host_nodes.pop(node)
        self._prefix_host_bytes -= rec["nbytes"]
        self._prefix_promotions += 1
        self._prefix_registrations += 1
        return node, tuple(pages), n * self._cache.page_size

    def _evict_prefixes_for(self, needed_free: int, keep=()) -> None:
        """Evict LRU registry entries (never one in ``keep``) until
        the free list can cover ``needed_free`` pages. Always
        sufficient for an admission within its reservation: every
        non-registry allocation sits inside some request's reserved
        budget (or a lease), and reservations never exceed the pool."""
        while (self._cache.free_pages() < needed_free
               and any(n not in keep
                       for n in self._prefix_entry_nodes)):
            victim = min(
                (n for n in self._prefix_entry_nodes if n not in keep),
                key=lambda n: self._prefix_entry_nodes[n]["last_used"],
            )
            self._evict_prefix_node(victim, "admission")

    def _relieve_pool_pressure_locked(self, needed: int = 1) -> bool:
        """Cache callback when an allocation finds the free list short
        (kvcache.grow/admit/cow): registry pins sit outside every
        request's reservation, so a mid-decode grow — which IS within
        its request's reservation — must be able to reclaim them;
        after all pins are dropped, free >= every in-reservation need.
        Eviction demotes to the host tier when configured, so relief
        moves cold shared pages out of HBM instead of destroying them.
        Runs under the server lock (every cache call holds it).
        Returns True iff ``needed`` pages are now free."""
        while (self._prefix_entry_nodes
               and self._cache.free_pages() < needed):
            victim = min(
                self._prefix_entry_nodes,
                key=lambda n: self._prefix_entry_nodes[n]["last_used"],
            )
            self._evict_prefix_node(victim, "pressure")
        return self._cache.free_pages() >= needed

    # ---- prefix persistence ---------------------------------------------
    #
    # The registry's pinned pages are device state, so a pod reschedule
    # loses them — unless they ride the state volume like every other
    # thing worth keeping (the reference's whole resilience story is
    # PVC-backed state, README.md:88). dump writes tokens + K/V of every
    # registered entry; load re-pins them into a fresh server. K/V are
    # valid ONLY for the params that produced them: the caller passes a
    # fingerprint (checkpoint step + model geometry) and a mismatched
    # file is ignored, never half-trusted.

    def _node_tokens(self, node: int) -> list[int]:
        """A trie node's full token path (lock held)."""
        blocks = []
        cur = node
        while cur != 0:
            parent_id, block = self._prefix_nodes[cur]["parent"]
            blocks.append(block)
            cur = parent_id
        return [t for block in reversed(blocks) for t in block]

    def dump_prefix_cache(self, path: str, fingerprint: str) -> int:
        """Persist the prefix registry to ``path`` (.npz). Returns the
        number of entries written (0 = nothing registered, no file
        touched). Callable any time before close — the lock serializes
        against the decode loop."""
        import json

        with self._lock:
            entries = [
                {"tokens": self._node_tokens(node),
                 "pages": list(entry["pages"])}
                for node, entry in self._prefix_entry_nodes.items()
            ]
            if not entries:
                return 0
            page_ids = sorted({p for e in entries for p in e["pages"]})
            # Only the gather DISPATCH runs under the lock; the fresh
            # device arrays are donation-immune, so the big
            # device->host transfer below happens with decode running
            # — a periodic dump must not freeze token emission for the
            # duration of a multi-hundred-MB copy.
            snapshot = self._cache.snapshot_pages(page_ids)
        # Transfer as stored (int8 pools ship compact + scales), then
        # dequantize host-side. npz has no bfloat16; float32 holds bf16
        # (and fp16) exactly, and the load path casts back (or
        # re-quantizes) to the pool dtype.
        pool_k, pool_v = self._cache.snapshot_to_host(snapshot)
        doc = {
            "fingerprint": fingerprint,
            "page_size": self._cache.page_size,
            "entries": entries,
            "page_ids": page_ids,
        }
        tmp = f"{path}.tmp"
        with open(tmp, "wb") as f:
            np.savez(f, doc=np.frombuffer(
                json.dumps(doc).encode(), np.uint8
            ), pool_k=pool_k, pool_v=pool_v)
        import os

        os.replace(tmp, path)  # atomic: never a torn cache file
        return len(entries)

    def load_prefix_cache(self, path: str, fingerprint: str) -> int:
        """Re-pin a dumped registry into this (fresh) server. Returns
        entries loaded; 0 with a reason logged when the file is absent,
        stale (fingerprint/page-size mismatch), or the pool too full.
        Entries load ancestors-first so nested prefixes share pages
        exactly as they did live; an entry whose fresh pages exceed the
        free list is SKIPPED (later entries that fit — e.g. descendants
        sharing already-loaded pages — still load), and nothing is ever
        evicted — a cache must not displace capacity."""
        import json
        import os

        if not os.path.exists(path):
            return 0
        try:
            with np.load(path) as data:
                # Fingerprint first: a stale file (training advanced the
                # checkpoint) must not pay the K/V decompression — npz
                # members load lazily on access.
                doc = json.loads(bytes(data["doc"]).decode())
                if (doc.get("fingerprint") != fingerprint
                        or doc.get("page_size")
                        != self._cache.page_size):
                    print(f"[kvedge-serve] ignoring stale prefix cache "
                          f"{path} (fingerprint/page-size changed)",
                          flush=True)
                    return 0
                pool_k, pool_v = data["pool_k"], data["pool_v"]
        except Exception as e:
            print(f"[kvedge-serve] ignoring unreadable prefix cache "
                  f"{path}: {e!r}", flush=True)
            return 0
        old_pos = {p: i for i, p in enumerate(doc["page_ids"])}
        loaded = 0
        with self._lock:
            if (not self._prefix_enabled or self._closed
                    or self._prefix_entry_nodes):
                # Boot-time only: loading into a registry that already
                # has live entries would need dedup-against-live (and
                # two loads would double-pin); nothing needs it.
                return 0
            remap: dict[int, int] = {}
            writes: list[tuple[int, int]] = []  # (new_id, dump position)
            for e in sorted(doc["entries"],
                            key=lambda e: len(e["tokens"])):
                fresh = set(p for p in e["pages"] if p not in remap)
                if len(fresh) > self._cache.free_pages():
                    # Skip, don't stop: sibling subtrees are not ordered
                    # by fresh-page need (a later descendant sharing an
                    # already-loaded ancestor may need fewer pages than
                    # an unrelated same-length entry that didn't fit).
                    continue
                for p in fresh:
                    new = self._cache.allocate_pinned_page()
                    remap[p] = new
                    writes.append((new, old_pos[p]))
                # Refcount shape must equal live registration's: one ref
                # per entry per page it holds. A freshly allocated page's
                # ref 1 IS this entry's hold; pages shared from earlier
                # entries take one more.
                self._cache.retain_pages(
                    [remap[p] for p in e["pages"] if p not in fresh]
                )
                self._insert_prefix_entry(
                    e["tokens"], [remap[p] for p in e["pages"]]
                )
                loaded += 1
            if writes:
                ids = [w for w, _ in writes]
                pos = [i for _, i in writes]
                self._cache.write_pages(
                    ids, pool_k[:, pos], pool_v[:, pos]
                )
        return loaded

    def start_prefix_persistence(self, path: str, fingerprint: str,
                                 interval: float = 30.0) -> None:
        """Dump the prefix registry to ``path`` every ``interval``
        seconds while it has changed — so a SIGKILL'd pod (the
        reference's own failure story: PVC-backed state surviving
        rescheduling, README.md:88) keeps its warm prefixes, not just a
        gracefully drained one. The dump is atomic (os.replace) and
        takes the server lock itself; this thread never holds it across
        the write. Idempotent to call once; close() stops the timer."""
        if self._persist_stop is not None:
            raise RuntimeError("prefix persistence already started")
        self._persist_stop = threading.Event()
        # Remembered for the degraded path: a poisoned-but-readable
        # pool emergency-dumps to the same file on its way down.
        self._persist_path, self._persist_fp = path, fingerprint

        def loop() -> None:
            dumped_at = 0
            while not self._persist_stop.wait(interval):
                with self._lock:
                    registered = self._prefix_registrations
                if registered == dumped_at:
                    continue
                try:
                    self.dump_prefix_cache(path, fingerprint)
                    dumped_at = registered
                except Exception as e:  # never kill serving for a dump
                    print(f"[kvedge-serve] periodic prefix-cache dump "
                          f"failed: {e!r}", flush=True)

        self._persist_thread = threading.Thread(
            target=loop, name="kvedge-prefix-persist", daemon=True
        )
        self._persist_thread.start()

    # ---- speculative-mode economics (VERDICT r4 #7) ----------------------

    def resolve_speculation(self, auto: bool,
                            timings: dict | None = None) -> dict:
        """Decide whether speculative mode can pay under THIS session's
        relay, before traffic arrives. Call once, right after
        construction (single-host caches only — the probe runs device
        ops).

        Measures (or takes from ``timings`` — the test seam) the wall
        cost of one K-draft verify pass and one ``window``-step decode
        window at full batch, each including the host round trip, and
        compares best-case speculative throughput — every draft
        accepted, ``(K+1) / verify_s`` — against the windowed path's
        ``window / window_s``. When windows dominate even speculation's
        BEST case, the mode is a pure regression for greedy traffic
        (measured 7x in a degraded-relay session, BENCH_r04.json):
        ``auto=True`` falls back to windowed decode (speculation off);
        ``auto=False`` keeps the operator's explicit choice but logs a
        loud warning. Returns the decision dict, also exposed under
        ``stats()["spec_decision"]``.
        """
        if self._spec <= 0:
            raise RuntimeError("resolve_speculation needs spec mode on")
        t = timings or self._probe_spec_timings()
        return self._apply_spec_decision(auto, t)

    def disable_speculation(self, reason: str) -> dict:
        """Turn speculation off without probing, recording why — the
        multi-host slice path's resolution of "auto": the economics
        probe is single-host only (its device ops would enter the
        slice op-stream), and UNMEASURED speculation on a degraded
        relay is the exact regression auto mode exists to prevent, so
        unmeasured resolves to windows. Operators who want speculation
        on a slice set an explicit K."""
        with self._work:
            self._spec = 0
        decision = {"mode": f"windowed ({reason})",
                    "windows_dominate": None}
        self._spec_decision = decision
        return decision

    def _apply_spec_decision(self, auto: bool, t: dict) -> dict:
        k = self._spec
        window = t.get("probed_window", self._window)
        spec_best = (k + 1) / t["verify_s"]
        windowed = window / t["window_s"]
        fallback = windowed > spec_best
        decision = {
            "verify_ms": round(t["verify_s"] * 1e3, 2),
            "window_ms": round(t["window_s"] * 1e3, 2),
            "window": window,
            "draft_len": k,
            "spec_best_tokens_per_sec": round(spec_best, 1),
            "windowed_tokens_per_sec": round(windowed, 1),
            "windows_dominate": fallback,
            "mode": ("windowed (auto fallback)" if fallback and auto
                     else "speculative" if not fallback
                     else "speculative (operator override)"),
        }
        if self._spec_window > 0 and "spec_window_s" in t:
            # Sampled co-tenant pricing (rung 23): a sampled row
            # advances one token per pass on either path, so the
            # choice is W host round trips (legacy _spec_pass) vs one
            # (the windowed scan). Both rates are measured, not
            # modelled — the same W-pass token count divided by W
            # per-pass RTTs vs one windowed dispatch+harvest.
            w = self._spec_window
            legacy = 1 / t["verify_s"]
            windowed_sampled = w / t["spec_window_s"]
            decision["spec_window_ms"] = round(
                t["spec_window_s"] * 1e3, 2
            )
            decision["sampled_cotenant_legacy_tokens_per_sec"] = (
                round(legacy, 1)
            )
            decision["sampled_cotenant_windowed_tokens_per_sec"] = (
                round(windowed_sampled, 1)
            )
            decision["sampled_window_pays"] = (
                windowed_sampled >= legacy
            )
        if fallback:
            action = ("falling back to windowed decode"
                      if auto else
                      "serving_speculative is set explicitly — keeping "
                      "it; expect slower greedy traffic")
            print(
                "[kvedge-serve] WARNING: windowed decode dominates "
                f"speculation's best case on this relay "
                f"({windowed:.0f} vs {spec_best:.0f} tok/s best-case "
                f"per slot); {action}", flush=True,
            )
            if auto:
                with self._work:
                    self._spec = 0
        self._spec_decision = decision
        return decision

    def _probe_spec_timings(self) -> dict:
        """Measure one verify pass and one decode window on the live
        cache (slot 0, one-token prompt, admitted and released around
        each measurement so lengths never accumulate; compile excluded
        by a warmup call — the programs are the same ones real traffic
        uses, so the warmup cost is front-loaded, not added)."""
        import numpy as _np

        k = self._spec
        n = self._cache.bucket
        probe_tokens = _np.zeros((n, 1 + k), _np.int32)
        step_tokens = _np.zeros((n,), _np.int32)
        active = _np.zeros((n,), bool)
        active[0] = True
        spec_mask = active.copy()
        # The probed window must fit the model (positions 1..1+w) and
        # be one the serving loop can actually run: _window_steps
        # floors to a power of two, so probe the floored value — timing
        # an unrealizable window would overstate the windowed rate near
        # the crossover (and compile a program real traffic never
        # reuses).
        window = min(self._window, self._cfg.max_seq - 1 - k)
        if window > 1:
            window = 1 << (window.bit_length() - 1)
        with self._work:
            import jax.numpy as jnp

            def timed(op) -> float:
                self._cache.admit(0, 1)
                self._cache.prefill(
                    self._params, 0, jnp.zeros((1,), jnp.int32)
                )
                start = time.perf_counter()
                _np.asarray(op())
                elapsed = time.perf_counter() - start
                self._cache.release(0)
                return elapsed

            def verify():
                emitted, _, _ = self._cache.step_spec(
                    self._params, probe_tokens, active=active,
                    spec_mask=spec_mask,
                )
                return emitted

            def run_window():
                return self._cache.step_window(
                    self._params, jnp.asarray(step_tokens), window,
                    active=active,
                )

            def run_spec_window():
                # One full spec-window dispatch+harvest on slot 0 —
                # the program the windowed sampled co-tenant rides, so
                # its price is measured with the RTT amortization the
                # rung-23 decision needs.
                budgets = _np.zeros((n,), _np.int32)
                budgets[0] = self._spec_window
                ctx = _np.zeros((n, self._spec_ctx_cap), _np.int32)
                ctx_len = _np.zeros((n,), _np.int32)
                ctx_len[0] = 2  # prefilled token + pending
                handle = self._cache.dispatch_spec_window(
                    self._params, step_tokens, self._spec_window, k,
                    budgets, ctx=ctx, ctx_len=ctx_len,
                )
                emitted, _, _ = self._cache.harvest_spec_window(handle)
                self._cache.drop_carry()
                return emitted

            timed(verify)  # compile + first-execution cost, untimed
            timed(run_window)
            verify_s = min(timed(verify) for _ in range(2))
            window_s = min(timed(run_window) for _ in range(2))
            out = {"verify_s": verify_s, "window_s": window_s,
                   "probed_window": window}
            if self._spec_window > 0:
                timed(run_spec_window)
                out["spec_window_s"] = min(
                    timed(run_spec_window) for _ in range(2)
                )
        return out

    def close(self, drain: bool = False) -> None:
        """Shut down. Hard close (default) poisons in-flight requests
        with :class:`ServerClosed`; ``drain=True`` stops admission
        immediately (new submits fail with ServerClosed) but lets every
        accepted request decode out its budget before the loop exits —
        the graceful-restart path. Bounded: an in-flight budget is at
        most max_seq tokens."""
        if self._persist_stop is not None:
            # Stop the periodic dump timer first: a dump landing while
            # the pool tears down would read dying device state.
            self._persist_stop.set()
            self._persist_thread.join(timeout=60)
        with self._work:
            if drain:
                self._draining = True
            else:
                self._closed = True
            # Parked admission tickets wait on their OWN conditions —
            # wake them all into the refusal path.
            self._sched.wake_all_locked()
            self._work.notify_all()
        self._thread.join(timeout=600 if drain else 30)
        if not drain and self._thread.is_alive():
            # A healthy-but-slow step (first-time window/spec compile on
            # a large model can exceed 30 s) must not be classified as a
            # wedged follower below — retry the join once before
            # deciding the thread is dead.
            self._thread.join(timeout=60)
        if drain:
            with self._work:
                self._closed = True
                self._sched.wake_all_locked()
                self._work.notify_all()
        # A slice-aware cache (runtime/sliceserve.py) releases its
        # followers here — under the lock, so the stop op serializes
        # AFTER any in-flight request thread's cache call (a hard close
        # can race a chunked prefill whose error path still releases its
        # slot) and the cache's idempotence flag is check-then-act
        # atomic. Single-host caches define no stop. Slice ops are
        # deadline-bounded now (runtime/failures.py), so a dead
        # follower poisons the loop with SliceFollowerLost instead of
        # wedging it — the liveness guard below is the backstop for a
        # step wedged OUTSIDE the watchdog (single-host device hang):
        # skip the release rather than hang close() too. stop() itself
        # is also deadline-bounded, so close() stays bounded even when
        # the followers die between the last op and the STOP broadcast.
        with self._work:
            # A closed pool is never revived: journaled survivors of a
            # poison must not park forever behind a teardown — fail
            # them with the poison (retryable, hint attached) or plain
            # ServerClosed.
            if len(self._journal):
                self._fail_journal_locked(
                    self._poison if self._poison is not None
                    else ServerClosed("server is shut down")
                )
        stop = getattr(self._cache, "stop", None)
        if stop is not None and not self._thread.is_alive():
            with self._work:
                stop()

    @property
    def degraded(self) -> str | None:
        """The degraded-mode reason, or None while healthy. Lock-free
        on purpose: /healthz reads this and must answer even if some
        thread is misbehaving around the server lock."""
        return self._degraded_reason

    def _degrade(self) -> None:
        """Best-effort degraded-mode work, run once by the exiting
        decode loop, OUTSIDE the lock: emergency-dump the prefix cache
        if the pool is still readable (a follower-lost slice cache
        refuses persistence and a dead op stream would wedge — both
        surface as an exception and the dump is skipped; a single-host
        pool poisoned by a host-side bug is usually intact), then
        notify the workload observer."""
        if self._persist_path is not None and self._prefix_entry_nodes:
            try:
                n = self.dump_prefix_cache(
                    self._persist_path, self._persist_fp
                )
                print(f"[kvedge-serve] degraded: emergency prefix dump "
                      f"wrote {n} entries", flush=True)
            except Exception as e:
                print(f"[kvedge-serve] degraded: emergency prefix dump "
                      f"skipped ({e!r})", flush=True)
        cb = self.on_degraded
        if cb is not None:
            try:
                cb(self._degraded_reason, self._poison)
            except Exception as e:  # observers never re-poison teardown
                print(f"[kvedge-serve] on_degraded observer failed: "
                      f"{e!r}", flush=True)

    def revive(self, *, prefill_wait_s: float = 30.0) -> int:
        """Warm-restart a poisoned pool in place (recovery supervisor).
        Returns the number of journaled in-flight requests re-admitted.

        Pre-condition: the failed op stream is live again — for a slice
        cache the supervisor runs ``cache.reform()`` FIRST, because the
        slot releases below flow ``_sync`` ops to the (re-joined)
        followers. Raises RuntimeError when the pool is not poisoned or
        its decode loop has not finished exiting.

        The scrub drops everything poisoning stranded: prefix-registry
        pins are evicted (the device K/V behind them is suspect after a
        failure — the emergency dump reloads them from the reusable
        snapshot), every still-admitted slot is released, and the
        slot/reservation books reset to empty. Unjournaled in-flight
        requests were already failed by ``_poison_locked``; journaled
        ones (rung 22) re-admit below into fresh slots — original
        ticket and class preserved, pages restored verbatim via
        ``swapin_pages``, decode resumed from the checkpointed offset
        — transactionally: a re-admission fault re-journals everything
        (nothing lost) and leaves the pool poisoned for the next
        attempt. Compiled programs survive untouched — that is the
        point of reviving over rescheduling.
        """
        # The dying decode thread must be gone before a replacement
        # starts (two loops over one pool would interleave cache calls).
        self._thread.join(timeout=5.0)
        if self._thread.is_alive():
            raise RuntimeError("decode loop still running; cannot revive")
        deadline = time.monotonic() + prefill_wait_s
        with self._work:
            if self._poison is None:
                raise RuntimeError("pool is not poisoned; nothing to revive")
            # Chunked prefills caught mid-flight by the poison fail on
            # their next cache call and decrement under the lock; wait
            # them out so none can land tokens into the reset pool.
            while self._prefilling > 0:
                left = deadline - time.monotonic()
                if left <= 0:
                    raise RuntimeError(
                        f"{self._prefilling} prefill(s) still in flight "
                        f"after {prefill_wait_s:g}s; cannot revive"
                    )
                self._work.wait(timeout=left)
            for node in list(self._prefix_entry_nodes):
                # "revive" never demotes: device K/V are suspect after
                # a poison. The host tier and the journal's shadow
                # snapshots are host bytes taken BEFORE the failure —
                # they survive and stay trusted.
                self._evict_prefix_node(node, "revive")
            for slot in range(self._cache.slots):
                if self._cache.is_admitted(slot):
                    self._cache.release(slot)
            self._free_slots = list(range(self._cache.slots))
            self._reserved = 0
            self._lease.clear()
            self._bucket_step_wanted = False
            self._active.clear()
            # The failing loop drained its in-flight window before
            # poisoning; clear defensively and forget the device
            # carry — a revived pipeline restarts from host tokens
            # (a slice cache's reform() already dropped its own).
            self._inflight = None
            self._finish_ready.clear()
            self._stops_pending = 0
            self._cache.drop_carry()
            if self._cache.min_bucket:
                # Restore the PRE-POISON rung (floored at what the
                # journal re-admissions below need) instead of
                # resetting to the bottom: the compiled programs for
                # that rung survived, and a loaded server stepping up
                # from the bottom would pay a retrace storm the moment
                # traffic returns.
                rung = self._prebucket or self._cache.bucket_for(0)
                rung = max(rung,
                           self._cache.bucket_for(len(self._journal)))
                self._cache.set_bucket(rung)
            # Scheduler scrub: unjournaled swapped-out requests were
            # already failed by _poison_locked (snapshots freed);
            # straggler tickets were woken into the refusal path. The
            # queues restart empty; cumulative counters — including
            # the ticket sequence, so restored tickets stay ordered
            # ahead of post-revive arrivals — survive.
            self._sched.reset_locked()
            restored = self._restore_journal_locked()
            self._poison = None
            self._degraded_reason = None
            self._closed = False
            self._draining = False
            self._thread = threading.Thread(
                target=self._loop, name="kvedge-paged-serve", daemon=True
            )
            self._thread.start()
            if self.tracer is not None:
                # Same recorder, same timeline: the revival lands next
                # to the poison it heals, and the tracer itself needs
                # no reset (it holds no device or thread state).
                self.tracer.event("revive", "serve",
                                  args={"restored": restored})
            self._work.notify_all()
        return restored

    def _restore_journal_locked(self) -> int:
        """Re-admit every journaled request into the scrubbed pool
        (lock held, decode thread not yet started). Each entry rewinds
        its request to the checkpoint — ``generated`` truncates to the
        checkpointed length, the pending token and books restore, and
        the delivered watermark arms ``_emit``'s replay suppression —
        then takes a fresh slot with the verbatim page bytes swapped
        back in. The rewind is idempotent, so the failure path can
        re-journal already-restored entries and retry wholesale.

        Prefix-reference entries (rung 24c) re-materialize the shared
        bytes ONCE per cited node: the first restorer swaps the shadow
        snapshot into freshly pinned pages and resurrects the registry
        entry, every later citer of the same node re-leases those
        pages via ``admit(shared=...)`` — N conversations on one
        system prompt swap in 1 prefix + N suffixes. Shadow refs
        settle only after the WHOLE restore commits; the unwind
        re-puts entries with refs untouched, so a retry still finds
        its shadows."""
        entries = self._journal.take_all()
        all_drained = list(entries)
        node_pages: dict[int, tuple] = {}
        restored: list[tuple[int, JournalEntry]] = []
        t0 = time.perf_counter()
        try:
            while entries:
                entry = entries[0]
                req = entry.req
                if req.cancelled or req.done.is_set():
                    entries.pop(0)
                    continue
                if not self._free_slots:
                    # More checkpoints than slots (the poison caught
                    # swapped-out requests too): the overflow re-queues
                    # below, after the direct restores commit.
                    break
                req.stream_resume_at = max(req.stream_resume_at,
                                           entry.emitted)
                del req.generated[entry.gen_len:]
                req.next_token = entry.next_token
                req.inflight = 0
                # A stop detected after the checkpoint is replay state:
                # the rewound decode re-detects it bit-identically.
                req.stopped = False
                req.pages_reserved = entry.pages_reserved
                req.ticket_no = entry.ticket_no
                req.admit_seq = entry.admit_seq
                req.shared_pages = ()
                req.prefix_node = None
                slot = heapq.heappop(self._free_slots)
                self._reserved += entry.pages_reserved
                self._active[slot] = req
                # In ``restored`` BEFORE the device calls: a faulting
                # admit/swapin must find its slot and reservation in
                # the unwind below (the entry is then briefly in both
                # lists — the double re-journal is a same-key replace).
                restored.append((slot, entry))
                sh_n = entry.prefix_pages_n
                if entry.prefix_node is not None and sh_n:
                    node = entry.prefix_node
                    pins = node_pages.get(node)
                    if pins is None:
                        shadow = self._prefix_shadow[node]
                        fresh = [self._cache.allocate_pinned_page()
                                 for _ in range(sh_n)]
                        try:
                            self._cache.swapin_pages(
                                fresh, shadow["arrays"])
                        except Exception:
                            self._cache.release_pages(fresh)
                            raise
                        self._insert_prefix_entry(
                            req.prompt[:entry.prefix_tokens], fresh)
                        pins = node_pages[node] = tuple(fresh)
                    self._cache.admit(slot, entry.saved_len, pins)
                    self._cache.swapin_pages(
                        self._cache.slot_pages(slot)[sh_n:],
                        entry.arrays,
                    )
                    self._lease_take_locked(pins)
                    req.shared_pages = pins
                    req.prefix_node = node
                else:
                    self._cache.admit(slot, entry.saved_len)
                    self._cache.swapin_pages(
                        self._cache.slot_pages(slot), entry.arrays
                    )
                entries.pop(0)
        except Exception:
            # Transactional unwind: put everything back — restored
            # rows included (their rewind is idempotent) — so the next
            # revive attempt loses nothing. Shadow refs are NOT
            # settled (the re-put entries still cite them); registry
            # entries resurrected above stay until the next revive's
            # scrub evicts them.
            for slot, entry in restored:
                self._active.pop(slot, None)
                self._release_locked(slot, entry.pages_reserved,
                                     entry.req.shared_pages)
                entry.req.shared_pages = ()
                entry.req.prefix_node = None
            for _, entry in restored:
                self._journal.put(entry.req, entry)
            for entry in entries:
                self._journal.put(entry.req, entry)
            raise
        # Slot-overflow checkpoints go back to the SWAP SET under their
        # original tickets (host bookkeeping only — cannot fault): the
        # decode loop resumes them at boundaries exactly like preempted
        # victims, ahead of post-revive arrivals. Prefix-reference
        # entries materialize the FULL byte snapshot here (shadow
        # prefix + own suffix, page axis 1) — a swapped-out request
        # has no live pages to lease, so its resume is self-contained.
        requeued = 0
        for entry in entries:
            req = entry.req
            req.stream_resume_at = max(req.stream_resume_at,
                                       entry.emitted)
            del req.generated[entry.gen_len:]
            req.next_token = entry.next_token
            req.inflight = 0
            req.stopped = False
            arrays = entry.arrays
            pages_needed = entry.pages_reserved
            if entry.prefix_node is not None and entry.prefix_pages_n:
                shadow = self._prefix_shadow[entry.prefix_node]
                arrays = tuple(
                    np.concatenate([s, o], axis=1)
                    for s, o in zip(shadow["arrays"], entry.arrays)
                )
                pages_needed += entry.prefix_pages_n
            req.pages_reserved = pages_needed
            req.shared_pages = ()
            req.prefix_node = None
            self._sched.record_swapout_locked(
                req, entry.pclass, entry.ticket_no,
                pages_needed, entry.saved_len, arrays,
                restore=True,
            )
            requeued += 1
        # Full success: settle every drained entry's shadow reference
        # — restored requests will re-cite at their next checkpoint,
        # requeued ones became self-contained above.
        for entry in all_drained:
            if entry.prefix_node is not None:
                self._journal_drop_locked(entry)
        self._journal_restores += len(restored) + requeued
        if self.tracer is not None and (restored or requeued):
            self.tracer.span(
                "journal-restore", "serve", t0,
                args={"restored": len(restored), "requeued": requeued},
            )
        return len(restored) + requeued

    def stats(self) -> dict:
        # /metrics aggregation mostly off the work lock (rung 26
        # host-path budget): the lock covers only the raw counter and
        # histogram copies that mutate under it; the tracer/SLO/
        # occupancy/slice merges are documented ring-copy reads and
        # happen after release, so a scrape no longer taxes a decode
        # boundary with their assembly. The Prometheus text rendering
        # itself (runtime/status.py) was always outside.
        with self._lock:
            out = self._stats_core_locked()
        self._stats_merge_unlocked(out)
        return out

    def _stats_locked(self) -> dict:
        # The flight bundle's variant: ONE acquisition covers the
        # whole document so metrics/SLO/books stay mutually
        # consistent (the chaos invariant). The merge helpers are
        # lock-free reads, safe to run with the lock held too.
        out = self._stats_core_locked()
        self._stats_merge_unlocked(out)
        return out

    def _stats_core_locked(self) -> dict:
        out = {
            "degraded": 1 if self._degraded_reason else 0,
            "in_flight": len(self._active),
            "free_slots": len(self._free_slots),
            "free_pages": self._cache.free_pages(),
            "reserved_pages": self._reserved,
            # Capacity semantics (SERVING.md rung 21): the page
            # pool is the admission resource and the bucket is the
            # device batch dim — the gauges an operator needs to
            # see shed/preempt pressure coming.
            "pages_total": self._pages_total,
            "slots_total": self._cache.slots,
            "bucket": self._cache.bucket,
            "bucket_min": self._cache.min_bucket,
            "page_low_watermark": self._page_low_wm,
            "page_high_watermark": self._page_high_wm,
            "window": self._window,
            "kv_dtype": ("int8" if self._cache.kv_quantized
                         else str(self._cfg.dtype)),
            "prefix_entries": len(self._prefix_entry_nodes),
            "prefix_hits": self._prefix_hits,
            "prefix_lookups": self._prefix_lookups,
            "prefix_tokens_saved": self._prefix_tokens_saved,
            # Prefix-cache semantics (SERVING.md rung 24): COW
            # divergence copies, HBM bytes the shared prefixes
            # avoided re-prefilling, the host residency tier, and
            # evictions by cause (one labelled counter in
            # /metrics).
            "prefix_bytes_saved": self._prefix_tokens_saved * (
                self._page_bytes_locked()
                // self._cache.page_size),
            "prefix_cow_copies": self._prefix_cow_copies,
            "prefix_host_entries": len(self._prefix_host_nodes),
            "prefix_host_bytes": self._prefix_host_bytes,
            "prefix_demotions": self._prefix_demotions,
            "prefix_promotions": self._prefix_promotions,
            "prefix_evictions": dict(self._prefix_evictions),
            "journal_shadow_nodes": len(self._prefix_shadow),
            "journal_shadow_bytes": self._journal.extra_bytes,
            "overlap": 1 if self._overlap_on else 0,
            "overlap_windows_total": self._overlap_windows,
            "overlap_inflight_depth":
                1 if self._inflight is not None else 0,
            # Histogram snapshots (dict-valued; status.py renders
            # them as Prometheus histograms, scalar consumers
            # should skip them).
            "window_dispatch_harvest_ms": self._hist_rtt.snapshot(),
            "window_host_ms": self._hist_host.snapshot(),
            # Device-time attribution (SERVING.md rung 25): the
            # forced-sync leg of each window on its own, so RTT
            # minus device is host bookkeeping + pipeline slack.
            "window_device_ms": self._hist_device.snapshot(),
            "window_inflight_depth": self._hist_depth.snapshot(),
            # Per-request stage histograms (SERVING.md rung 18):
            # TTFT and the queue-vs-decode split.
            "ttft_ms": self._hist_ttft.snapshot(),
            "queue_ms": self._hist_queue.snapshot(),
            "decode_ms": self._hist_decode.snapshot(),
            # Per-request mean inter-token gap + completion
            # counters (rung 25 SLI inputs).
            "itl_ms": self._hist_itl.snapshot(),
            "requests_done_total": self._done_total,
            "tokens_done_total": self._tokens_done_total,
            # Durability semantics (SERVING.md rung 22): journal
            # occupancy, checkpoint throughput, and the restores
            # revive() performed — the gauges that prove in-flight
            # requests are actually covered.
            "checkpoint_every": self._checkpoint_every,
            "journal_entries": len(self._journal),
            "journal_bytes": self._journal.nbytes,
            "checkpoints_total": self._checkpoints_total,
            "checkpoint_skipped_total": self._checkpoint_skipped,
            "checkpoint_unchanged_total": self._checkpoints_unchanged,
            "journal_restores_total": self._journal_restores,
            # Device-resident endgame (SERVING.md rung 23):
            # windowed-path collapses by cause (rendered as one
            # labelled Prometheus counter) and stop-token finishes.
            "spec_window_fallbacks": dict(
                self._spec_window_fallbacks
            ),
            "stop_finishes_total": self._stop_finishes,
        }
        if self._autotune is not None:
            # Online window controller (SERVING.md rung 26): the
            # current pick and its EWMA inputs — R (host turnaround
            # per window) and t (per-step device time). R/t gauges
            # make the law auditable from a scrape: the pick should
            # be the smallest pow2 with window*t >= R.
            snap = self._autotune.snapshot()
            out["autotune_window"] = snap["window"]
            out["autotune_r_ms"] = round(snap["r_ms"], 3)
            out["autotune_t_ms"] = round(snap["t_ms"], 4)
            out["autotune_updates"] = snap["updates"]
        # Scheduler observability: per-class queue depth and wait
        # histograms, preemption/resume/shed counters, swap gauges.
        out.update(self._sched.stats_locked())
        if self._degraded_reason:
            out["degraded_reason"] = self._degraded_reason
        if self._spec:
            # Realized acceleration PER GREEDY SLOT: mean tokens a
            # greedy slot emits per verify pass it participates in
            # (1.0 = speculation never paid; K+1 = every draft
            # accepted) — normalized by slot-participations, not
            # passes, so concurrency cannot inflate it.
            out["spec_draft_len"] = self._spec
            out["spec_passes"] = self._spec_passes
            out["spec_emitted_per_pass"] = round(
                self._spec_emitted / self._spec_slot_passes, 3
            ) if self._spec_slot_passes else 0.0
        if self._spec_window:
            # Device-resident spec windows (SERVING.md rung 20):
            # the knob, the dispatch count, and the per-window
            # emitted-tokens histogram (in-window acceptance E —
            # logical passes per dispatch for the Perfetto view).
            out["spec_window"] = self._spec_window
            out["spec_windows_total"] = self._spec_windows
            out["spec_window_sampled"] = (
                1 if self._spec_sampled_window else 0
            )
            out["spec_window_emitted_tokens"] = (
                self._hist_spec_tokens.snapshot()
            )
        if self._spec_decision is not None:
            # The boot-time economics decision (resolve_speculation)
            # — present even after an auto fallback zeroed _spec, so
            # an operator can see WHY speculation is off.
            out["spec_decision"] = dict(self._spec_decision)
        return out

    def _stats_merge_unlocked(self, out: dict) -> None:
        """Merge the lock-free observability planes into a stats
        snapshot: the tracer, the SLO engine and the occupancy ring
        all read ring copies, and the slice cache's broadcast bill is
        a plain dict the runner thread owns. Callable with or without
        the work lock (stats() releases it first; flight_bundle()
        keeps its single-acquisition consistency contract)."""
        if self.tracer is not None:
            out.update(self.tracer.stats())
        if self._slo is not None:
            # Rolling SLI gauges + burn rates (fast window), flat
            # for /metrics; GET /slo carries the full document.
            out.update(self._slo.metrics())
        if self._occ_ring is not None:
            # Latest occupancy sample, flattened into gauges; the
            # timeline itself exports via the Chrome counter track
            # and the flight bundle's tail.
            out["occupancy_samples_total"] = (
                self._occ_ring.samples_total
            )
            last = self._occ_ring.last()
            if last:
                for k, v in last.items():
                    out["occupancy_" + k] = v
        op_ms = getattr(self._cache, "op_broadcast_ms", None)
        if op_ms:
            # Slice-cache per-op broadcast bill (rung 25): dict of
            # op kind -> [frames, cumulative ms], rendered as two
            # labelled counters in /metrics.
            out["slice_op_ms"] = {k: list(v) for k, v in op_ms.items()}

    # ---- SLO engine + flight bundle (SERVING.md rung 25) -----------------

    def slo_doc(self) -> dict | None:
        """The ``GET /slo`` document, or None when the engine is off
        (the route 404s with the knob pointer). Lock-free: the engine
        reads ring copies."""
        if self._slo is None:
            return None
        return self._slo.doc()

    def _config_doc_locked(self) -> dict:
        """The serving-shape config the bundle fingerprints — enough
        to tell 'same knobs, new failure' from 'different deployment'
        across two bundles without shipping the whole payload TOML."""
        return {
            "slots": self._cache.slots,
            "pages_total": self._pages_total,
            "page_size": self._cache.page_size,
            "window": self._window,
            "overlap": self._overlap,
            "speculative": self._spec,
            "spec_window": self._spec_window,
            "spec_sampled_window": int(self._spec_sampled_window),
            "prefill_chunk": self._prefill_chunk,
            "prefix_cache": int(self._prefix_enabled),
            "checkpoint_every": self._checkpoint_every,
            "page_low_watermark": self._page_low_wm,
            "page_high_watermark": self._page_high_wm,
            "kv_dtype": ("int8" if self._cache.kv_quantized
                         else str(self._cfg.dtype)),
            "slo": (dataclasses.asdict(self._slo.objectives)
                    if self._slo is not None else None),
        }

    def flight_bundle(self) -> dict:
        """The rung-25 post-mortem bundle: one versioned JSON document
        carrying everything a human (or the chaos harness) needs to
        explain a dead replica — metrics snapshot, SLO/burn state,
        occupancy timeline tail, journal summary, page-accounting
        books, config fingerprint, trace tail.

        Everything under the lock is ONE acquisition, so the metrics
        snapshot, the SLO state and the page books are mutually
        consistent (the chaos invariant compares them). Works on a
        poisoned pool: nothing here touches device state beyond the
        same host-side books stats() already reads."""
        with self._lock:
            doc = {
                "bundle_version": 1,
                "reason": self._degraded_reason,
                "degraded": 1 if self._degraded_reason else 0,
                "metrics": self._stats_locked(),
                "slo": (self._slo.doc()
                        if self._slo is not None else None),
                "occupancy_tail": (self._occ_ring.tail()
                                   if self._occ_ring is not None
                                   else []),
                "journal": {
                    "entries": len(self._journal),
                    "bytes": self._journal.nbytes,
                    "extra_bytes": self._journal.extra_bytes,
                    "budget_bytes": self._journal.max_bytes,
                },
                "config": self._config_doc_locked(),
            }
            books = getattr(self._cache, "page_accounting", None)
            if books is not None:
                try:
                    doc["page_accounting"] = books()
                except Exception:
                    # A torn-down cache must not take the bundle with
                    # it — the post-mortem is most valuable exactly
                    # when things are broken.
                    doc["page_accounting"] = None
        doc["config_fingerprint"] = hashlib.sha256(
            json.dumps(doc["config"], sort_keys=True).encode("utf-8")
        ).hexdigest()[:12]
        # Trace tail outside the lock: the tracer ring is lock-free by
        # contract and last_events() can retry its snapshot.
        doc["trace_tail"] = (self.tracer.last_events()
                             if self.tracer is not None else [])
        return doc

    def _occupancy_fields_locked(self) -> dict:
        """One occupancy sample (lock held): pool pages/HBM from the
        cache plus the serving layer's own residency gauges. All O(1)
        attribute reads — safe at every quiescent boundary."""
        fields = {
            "slots_active": len(self._active),
            "reserved_pages": self._reserved,
            "prefix_entries": len(self._prefix_entry_nodes),
            "prefix_host_bytes": self._prefix_host_bytes,
            "journal_bytes": self._journal.nbytes,
            "queue_depth": self._sched.depth_locked(),
        }
        occ = getattr(self._cache, "occupancy", None)
        if occ is not None:
            fields.update(occ())
        return fields

    def _observe_boundary_locked(self) -> None:
        """Quiescent-boundary observability feed (rung 25, lock held):
        one SLO-ring snapshot (throttled inside the engine) and one
        occupancy sample. Touches no device state and emits nothing —
        bit-identity with the knobs off is structural (None checks)."""
        if self._slo is None and self._occ_ring is None:
            return
        now = time.perf_counter()
        if self._slo is not None:
            self._slo.observe(now, {
                "ttft_ms": self._hist_ttft.snapshot(),
                "itl_ms": self._hist_itl.snapshot(),
                "queue_ms": self._hist_queue.snapshot(),
                "tokens_total": self._tokens_done_total,
                "done_total": self._done_total,
                "shed_total": self._sched.shed,
            })
        if self._occ_ring is not None:
            self._occ_ring.sample(
                now, self._occupancy_fields_locked()
            )

    # ---- decode loop -----------------------------------------------------

    def _lease_take_locked(self, pages) -> None:
        """Acquire one live-sharer lease per page (lock held). The
        FIRST sharer of a page books its one reservation unit; later
        sharers ride the existing lease for free (rung 24)."""
        for p in pages:
            n = self._lease.get(p, 0)
            self._lease[p] = n + 1
            if n == 0:
                self._reserved += 1

    def _lease_drop_locked(self, pages) -> None:
        """Release leases (lock held): a page's reservation unit frees
        only when its LAST live sharer leaves."""
        for p in pages:
            n = self._lease[p] - 1
            if n:
                self._lease[p] = n
            else:
                del self._lease[p]
                self._reserved -= 1

    def _release_locked(self, slot: int, pages_needed: int,
                        shared: tuple = ()) -> None:
        """Return a slot + its reservation to the pool (lock held).
        ``pages_needed`` is the request's PRIVATE reservation;
        ``shared`` drops its prefix-page leases too."""
        if self._cache.is_admitted(slot):
            self._cache.release(slot)
        heapq.heappush(self._free_slots, slot)
        self._reserved -= pages_needed
        self._lease_drop_locked(shared)
        # Targeted admission wakeup: the policy head (and ONLY the
        # head) re-checks capacity; the work condition still fans out
        # to the decode loop (which may now resume a swapped request).
        self._sched.wake_head_locked()
        self._work.notify_all()

    def _finish_request_locked(self, slot: int, req: _Request) -> None:
        """Complete a finished request (lock held): decode-stage
        histogram, completion span, slot/reservation release, waiter
        wakeup — the ONE exit path every normal finish site (budget
        sweep, inline overlap finish, speculative pass) shares."""
        t1 = time.perf_counter()
        if req.t_admit:
            self._hist_decode.observe((t1 - req.t_admit) * 1e3)
        # Goodput + inter-token SLI inputs (rung 25): every normal
        # finish funnels through here, so the counters are exact.
        self._done_total += 1
        self._tokens_done_total += len(req.generated)
        if req.t_first and len(req.generated) > 1:
            self._hist_itl.observe(
                (t1 - req.t_first) * 1e3 / (len(req.generated) - 1)
            )
        if req.trace:
            self.tracer.span(
                "decode", "serve", req.t_admit or t1, t1, rid=req.rid,
                args={"tokens": len(req.generated),
                      "class": req.pclass},
            )
        del self._active[slot]
        self._journal.pop(req)  # a finished request never resumes
        if self._prefix_enabled:
            # Multi-turn reuse (rung 24a): the finished slot's
            # committed K/V — prompt AND generated — is exact reusable
            # prefix state (K/V at position i depend only on tokens
            # 0..i), so a follow-up turn whose prompt embeds this
            # conversation hits. Registered before the release drops
            # the page refs; clamped to the committed device length so
            # a deferred stop can never register scribbled positions.
            tokens = (req.prompt + req.generated)[
                :self._cache.slot_length(slot)]
            self._register_prefixes(
                tokens, self._cache.slot_pages(slot)
            )
        self._release_locked(slot, self._pages_for(req),
                             req.shared_pages)
        if req.stream is not None:
            req.stream.put(_STREAM_DONE)
        req.done.set()

    def _pages_needed(self, total: int, slack: bool) -> int:
        """Worst-case pages for a ``total``-token request. ``slack``
        (greedy requests under spec mode) adds the K draft positions a
        verify pass writes at length..length+K regardless of
        acceptance. Sampled requests carry NO slack: they can never
        accept a draft, and the verify kernel drops their
        draft-position scatters (kvcache._spec_verify_core), so their
        footprint is exactly a plain request's."""
        pad = self._spec if slack else 0
        return -(-(total + pad) // self._cache.page_size)

    @staticmethod
    def _pages_for(req: _Request) -> int:
        return req.pages_reserved

    @staticmethod
    def _emit(req: _Request, token: int) -> None:
        """Record a generated token (and stream it when requested).
        After a journal restore, indices below ``stream_resume_at``
        are REPLAY — bit-identical regenerations of tokens the
        consumer already received — recorded but not re-streamed
        (exactly-once). The normal path's watermark is 0, so this is
        one dead comparison per token."""
        idx = len(req.generated)
        req.generated.append(token)
        if req.stream is not None and idx >= req.stream_resume_at:
            req.stream.put(token)

    @staticmethod
    def _emit_many(req: _Request, tokens: list) -> None:
        """Bulk :meth:`_emit`: one ``extend`` for the token log and
        the same exactly-once replay watermark for the stream. The
        harvest hot path hands whole per-row windows here (plain
        Python ints from ``ndarray.tolist()``) instead of looping
        ``_emit`` per token — the per-token Python frame was a
        measurable slice of the boundary budget at window 64."""
        if not tokens:
            return
        idx = len(req.generated)
        req.generated.extend(tokens)
        if req.stream is not None:
            skip = req.stream_resume_at - idx
            put = req.stream.put
            for t in (tokens[skip:] if skip > 0 else tokens):
                put(t)

    @staticmethod
    def _draft(req: _Request, k: int) -> list[int]:
        """K prompt-lookup drafts for a greedy request (host-side
        mirror of models/speculative.py's n-gram proposer — drafting
        needs no device work because the host owns every emitted
        token). Any draft is legal; verification makes correctness
        draft-independent."""
        ctx = req.prompt + req.generated + [req.next_token]
        g0, g1 = ctx[-2] if len(ctx) > 1 else ctx[-1], ctx[-1]
        for p in range(len(ctx) - 3, -1, -1):
            if ctx[p] == g0 and ctx[p + 1] == g1:
                start = max(0, min(p + 2, len(ctx) - k))
                cand = ctx[start:start + k]
                return cand + [g1] * (k - len(cand))
        return [g1] * k

    def _spec_pass(self) -> None:
        """One speculative verify pass for the active batch (lock
        held). Greedy slots emit their pending token plus up to K
        accepted drafts and a bonus; sampled slots advance exactly one
        sampled token from the pass's pending-position logits —
        identical schedule semantics to the per-step path, so the
        key-schedule exactness holds unchanged."""
        k = self._spec
        n = self._cache.bucket
        tokens = np.zeros((n, k + 1), np.int32)
        mask = np.zeros((n,), bool)
        spec_mask = np.zeros((n,), bool)
        for slot, req in self._active.items():
            tokens[slot, 0] = req.next_token
            mask[slot] = True
            if req.sampling is None:
                spec_mask[slot] = True
                tokens[slot, 1:] = self._draft(req, k)
        emitted, accepted, logits0 = self._cache.step_spec(
            self._params, tokens, active=mask, spec_mask=spec_mask
        )
        emitted = np.asarray(emitted)
        sampled_next = self._sample_slots(logits0, {
            slot: req for slot, req in self._active.items()
            if req.sampling is not None
        })
        self._spec_passes += 1
        for slot in list(self._active):
            req = self._active[slot]
            if req.sampling is not None:
                self._emit(req, req.next_token)
                req.next_token = sampled_next[slot]
                self._note_finish_candidate_locked(slot, req)
                continue
            a = int(accepted[slot])
            room = req.n_new - len(req.generated)
            seq = [req.next_token] + [int(t) for t in emitted[slot, :a]]
            emit_n, stopped = 0, False
            for t in seq[:room]:
                self._emit(req, t)
                emit_n += 1
                if t == req.stop_token:
                    stopped = True
                    break
            self._spec_emitted += emit_n
            self._spec_slot_passes += 1
            if stopped:
                # Passes run at boundaries only (nothing in flight):
                # the stop finish never needs the deferred path.
                self._stop_finishes += 1
                self._finish_request_locked(slot, req)
            elif len(req.generated) >= req.n_new:
                self._finish_request_locked(slot, req)
            else:
                # room > len(seq) here: room <= len(seq) means the
                # request just filled its budget and took the finished
                # branch above. The bonus token becomes pending.
                req.next_token = int(emitted[slot, a])
                self._note_finish_candidate_locked(slot, req)

    def _window_steps(self) -> int:
        """Steps the next device-side decode window may run (lock held).

        Bounded by the tightest remaining budget MINUS the pending token
        (which the finish-check emits without a step), so no slot ever
        decodes past its budget; capped at the operator window and
        floored to a power of two so the set of compiled window programs
        stays small ({2, 4, ..., window}). Multi-page windows are legal:
        ``grow_to`` allocates every page the window's scatters need up
        front, inside the request's admission-time reservation. Sampled
        requests ride windows too (round 5): their per-token keys are
        ``fold_in(seed, base + i)`` with ``base`` host-known at
        dispatch, so the schedule lives in the scan carry
        (kvcache.step_window_sampled).
        """
        w = min(req.n_new - len(req.generated) - 1
                for req in self._active.values())
        w = min(w, self._window)
        if w <= 1:
            return 1
        return 1 << (w.bit_length() - 1)

    def _sampled_window(self, tokens, window: int, mask, samplers):
        """Dispatch one mixed greedy/sampled device window (lock held).

        Builds the per-row sampling inputs: row seeds (raw key data),
        base token indices (``len(generated) + 1`` — the same schedule
        the per-step host path folds, so windowed and per-step sampled
        tokens are identical), temperature/top-p, and the sampled-row
        mask. Greedy rows get neutral values (temp 1, top_p 1, zero
        key) that the kernel's per-row select never reads."""
        n = self._cache.bucket
        key_data = np.zeros((n,) + self._key_data_shape(samplers),
                            np.uint32)
        base_steps = np.zeros((n,), np.int32)
        temps = np.ones((n,), np.float32)
        top_ps = np.ones((n,), np.float32)
        smask = np.zeros((n,), bool)
        for slot, req in samplers.items():
            key_data[slot] = req.key_data
            base_steps[slot] = len(req.generated) + 1
            temps[slot] = float(req.sampling[1])
            top_ps[slot] = float(req.sampling[2])
            smask[slot] = True
        return self._cache.step_window_sampled(
            self._params, tokens, window, mask, key_data, base_steps,
            temps, top_ps, smask,
        )

    @staticmethod
    def _key_data_shape(samplers) -> tuple:
        """Trailing shape of one row's raw key data (threefry: (2,));
        taken from a live request so the impl is never hardcoded."""
        return next(iter(samplers.values())).key_data.shape

    def _next_tokens(self, logits) -> dict[int, int]:
        """Every active slot's next token from the step's [slots, V]
        logits — ONE batched argmax plus (when any request samples) ONE
        batched fold_in/filter/categorical call and one host transfer,
        instead of per-slot eager chains under the lock."""
        import jax
        import jax.numpy as jnp

        from kvedge_tpu.models.decode import sample_token

        samplers = {
            slot: req for slot, req in self._active.items()
            if req.sampling is not None
        }
        out: dict[int, int] = {}
        if len(samplers) < len(self._active):
            # Greedy slots exist: one batched argmax + one host read.
            greedy = np.asarray(jnp.argmax(logits, axis=-1))
            out = {
                slot: int(greedy[slot])
                for slot in self._active if slot not in samplers
            }
        out.update(self._sample_slots(logits, samplers))
        return out

    @staticmethod
    def _sample_slots(logits, samplers: dict) -> dict[int, int]:
        """Sampled slots' tokens from [slots, V] logits: ONE vmapped
        fold_in (token index = each request's len(generated)+1, the
        cross-backend key schedule) + ONE batched filter/categorical +
        one host transfer. Shared by the per-step path and the
        speculative pass, which samples from the pass's pending-position
        logits without paying the greedy argmax."""
        if not samplers:
            return {}
        import jax
        import jax.numpy as jnp

        from kvedge_tpu.models.decode import sample_token

        slots = sorted(samplers)
        seed_keys = jnp.stack(
            [samplers[s].sampling[0] for s in slots]
        )
        steps = jnp.asarray(
            [len(samplers[s].generated) + 1 for s in slots], jnp.int32
        )
        keys = jax.vmap(jax.random.fold_in)(seed_keys, steps)
        temps = jnp.asarray(
            [samplers[s].sampling[1] for s in slots], jnp.float32
        )[:, None]
        top_ps = jnp.asarray(
            [samplers[s].sampling[2] for s in slots], jnp.float32
        )[:, None]
        picked = np.asarray(sample_token(
            logits[jnp.asarray(slots)], keys, temps, top_ps
        ))
        return {s: int(picked[i]) for i, s in enumerate(slots)}

    def _sweep_cancelled_locked(self) -> None:
        """Cancelled requests leave at a boundary: slot and pages
        return to the pool, the waiter (if any) gets RequestCancelled.
        Runs before the finish-sweep so a cancel that raced budget
        completion still wins — the consumer is gone either way."""
        for slot in list(self._active):
            req = self._active[slot]
            if not req.cancelled:
                continue
            del self._active[slot]
            self._journal.pop(req)  # a cancelled request never resumes
            self._release_locked(slot, self._pages_for(req),
                                 req.shared_pages)
            req.error = RequestCancelled(
                "request cancelled mid-decode"
            )
            if req.stream is not None:
                req.stream.put(req.error)
            req.done.set()

    def _note_finish_candidate_locked(self, slot: int,
                                      req: _Request) -> None:
        """Register a slot for the O(finishes) boundary sweep (lock
        held): called by every site that installs a pending token
        whose stepless emission would complete the request (budget
        filled, stop token, or an already-stopped row awaiting its
        deferred finish). The sweep re-validates, so a spurious
        registration is one wasted lookup, never a wrong finish."""
        if (req.stopped
                or len(req.generated) + 1 >= req.n_new
                or req.next_token == req.stop_token):
            self._finish_ready.add(slot)

    def _finish_stopped_locked(self, slot: int, req: _Request) -> None:
        """Complete a stop-terminated row (lock held, truncated stream
        already emitted with the stop token last). If an in-flight
        window still touches this slot its pages are still being
        scattered into on device — defer: mark the row stopped (later
        harvests skip its emission), force a boundary via
        ``_stops_pending``, and let the sweep finish it there."""
        self._stop_finishes += 1
        rec = self._inflight
        if rec is not None and any(
                s == slot for s, _, _ in rec["parts"]):
            req.stopped = True
            self._finish_ready.add(slot)
            self._stops_pending += 1
            return
        self._finish_request_locked(slot, req)

    def _sweep_finished_locked(self) -> None:
        """A request whose pending token completes its budget — or IS
        its stop token — needs no step at all (the token is already
        known): finish it before the batch, the same discipline as
        generate()'s n_new - 1 decode steps. O(active-finishes), not
        O(bucket): only slots registered in ``_finish_ready`` are
        examined (rung 23 — at bucket 256 the per-boundary scan was
        the last host cost scaling with slot count), and each entry is
        re-validated against the live request before acting."""
        for slot in sorted(self._finish_ready):
            req = self._active.get(slot)
            if req is None or req.cancelled:
                continue
            if req.stopped:
                # Deferred stop finish: the truncated stream (stop
                # token last) was emitted at harvest time.
                self._finish_request_locked(slot, req)
            elif len(req.generated) + 1 >= req.n_new:
                self._emit(req, req.next_token)
                self._finish_request_locked(slot, req)
            elif req.next_token == req.stop_token:
                self._emit(req, req.next_token)
                self._stop_finishes += 1
                self._finish_request_locked(slot, req)
        self._finish_ready.clear()
        # Every deferred stop finished (or was cancelled) above — this
        # sweep IS the boundary _stops_pending forced.
        self._stops_pending = 0

    # ---- scheduler boundary hooks (SERVING.md rung 17) -------------------

    def _sched_attention_locked(self, *,
                                ignore_inflight: bool = False) -> bool:
        """Does the decode loop need a non-overlapped boundary for the
        scheduler (lock held)? True when the policy head could RESUME
        right now, or is starved and a preemptable victim exists. A
        head ticket that already fits is its own thread's job — no
        boundary needed. ``ignore_inflight`` is the pipeline-collapse
        variant: at the harvest-or-dispatch decision every active row
        still carries in-flight window tokens, but the harvest that a
        collapse implies reconciles them — so a victim is judged by
        what it will be AT the boundary, not mid-window."""
        head = self._sched.head_locked()
        if head is None:
            return False
        if (self._free_slots
                and self._reserved + head.pages_needed
                <= self._pages_total):
            return head.resume
        return (self._sched.preemption_enabled
                and self._pick_victim_locked(
                    head, ignore_inflight=ignore_inflight) is not None)

    def _swap_cost_locked(self, req: _Request, *,
                          include_inflight: bool = False) -> int:
        """Host bytes req's swap snapshot would occupy (lock held) —
        the budget check BEFORE paying the device gather.
        ``include_inflight`` prices the snapshot AS OF the next
        reconciled boundary (live length + in-flight window tokens):
        the pipeline-collapse probe must predict the boundary-time
        cost, or it can collapse the pipeline for a victim whose
        grown snapshot the budget then declines — a wasted collapse."""
        n_tokens = len(req.prompt) + len(req.generated)
        if include_inflight:
            n_tokens += req.inflight
        n_pages = -(-n_tokens // self._cache.page_size)
        return n_pages * self._page_bytes_locked()

    def _page_bytes_locked(self) -> int:
        """Host bytes one KV page occupies (lock held; lazy — the
        pool's slab shapes are fixed at boot). Shared by swap-cost
        pricing and the prefix bytes-saved gauge."""
        if self._swap_page_bytes is None:
            st = self._cache.state
            per = st.pool_k.nbytes + st.pool_v.nbytes
            if st.scale_k is not None:
                per += st.scale_k.nbytes + st.scale_v.nbytes
            self._swap_page_bytes = -(-per // self._cache.num_pages)
        return self._swap_page_bytes

    def _pick_victim_locked(self, head, *,
                            ignore_inflight: bool = False) -> int | None:
        """The slot to preempt for ``head``, or None: a STRICTLY
        lower-class active request — never an equal (no intra-class
        churn) — preferring the lowest class, then the LATEST admitted
        (least progress lost), whose snapshot fits the host budget.
        Rows with in-flight window tokens are skipped: preemption
        joins only at reconciled boundaries (``ignore_inflight`` —
        the pipeline-collapse probe — looks past tokens the imminent
        harvest will reconcile)."""
        head_rank = self._sched.rank(head.pclass)
        best_slot, best_key = None, None
        for slot, req in self._active.items():
            if req.cancelled or (req.inflight and not ignore_inflight):
                continue
            rank = self._sched.rank(req.pclass)
            if rank <= head_rank:
                continue
            if not self._sched.swap_fits_locked(
                    self._swap_cost_locked(
                        req, include_inflight=ignore_inflight)):
                continue
            key = (rank, req.admit_seq)
            if best_key is None or key > best_key:
                best_slot, best_key = slot, key
        return best_slot

    def _maybe_resume_locked(self) -> None:
        """Re-admit swapped-out requests while the policy head is a
        resume entry that fits (lock held, boundary only). Worst-case
        reservation is re-acquired FIRST — the same invariant that
        makes normal admission safe makes swap-in safe: once the
        reservation is booked, ``admit`` + later ``grow`` can never
        starve (registry pins are evictable on demand). The page bytes
        go back verbatim (``swapin_pages`` — no dtype round trip), and
        the positional key schedule plus the host-held
        ``next_token``/``generated`` make the resumed stream
        bit-identical to a never-preempted run."""
        while True:
            head = self._sched.head_locked()
            if (head is None or not head.resume
                    or not self._free_slots
                    or self._reserved + head.pages_needed
                    > self._pages_total
                    or not self._resume_pages_ok_locked(
                        head.pages_needed)):
                return
            if self._free_slots[0] >= self._cache.bucket:
                # The resume row lies above the device bucket: step up
                # now if nothing is in flight, else at the next
                # boundary (this method only runs at boundaries, so
                # the flag lands one iteration later at worst).
                if (self._inflight is None
                        and not self._cache.spec_pending()):
                    self._cache.set_bucket(
                        self._cache.bucket_for(self._free_slots[0] + 1)
                    )
                else:
                    self._bucket_step_wanted = True
                    return
            arrays = head.arrays
            self._sched.pop_resume_locked(head)
            req = head.req
            slot = heapq.heappop(self._free_slots)
            self._reserved += head.pages_needed
            # Active BEFORE the device calls: if the swap-in faults,
            # the poison path owns this waiter like any other.
            self._active[slot] = req
            self._note_finish_candidate_locked(slot, req)
            self._cache.admit(slot, head.saved_len)
            self._cache.swapin_pages(
                self._cache.slot_pages(slot), arrays
            )

    def _maybe_preempt_locked(self) -> None:
        """Swap out lower-class victims while the policy head is
        starved for capacity (lock held, boundary only). The victim's
        live pages — exactly ceil(len/page_size), as stored — move to
        host RAM, its slot and reservation free, and a resume entry
        under its ORIGINAL ticket re-enters the queue; the freed
        capacity wakes the head ticket."""
        if not self._sched.preemption_enabled:
            return
        while True:
            head = self._sched.head_locked()
            if head is None:
                return
            if (self._free_slots
                    and self._reserved + head.pages_needed
                    <= self._pages_total):
                self._sched.wake_head_locked()
                return
            victim = self._pick_victim_locked(head)
            if victim is None:
                return
            req = self._active[victim]
            saved_len = len(req.prompt) + len(req.generated)
            n_pages = -(-saved_len // self._cache.page_size)
            # slot_pages is position-ordered; pages grown past the
            # live length hold no committed K/V and are simply freed.
            ids = self._cache.slot_pages(victim)[:n_pages]
            arrays = self._cache.swapout_pages(ids)
            del self._active[victim]
            # A preempted victim becomes SELF-CONTAINED: the verbatim
            # gather above copied its shared-prefix pages too, so its
            # leases dissolve and the resume prices (and later
            # re-reserves) the full footprint. Conservative — a resume
            # could in principle re-match the trie — but a resume that
            # cannot depend on cache state is a resume that always
            # fits its books.
            full = req.pages_reserved + len(req.shared_pages)
            self._release_locked(victim, req.pages_reserved,
                                 req.shared_pages)
            req.pages_reserved = full
            req.shared_pages = ()
            req.prefix_node = None
            self._sched.record_swapout_locked(
                req, req.pclass, req.ticket_no, full,
                saved_len, arrays,
            )

    def _loop(self) -> None:
        step = (self._loop_once_overlap if self._overlap_on
                else self._loop_once)
        while True:
            if step() == "exit":
                if self._poison is not None:
                    self._degrade()  # outside the lock, loop exited
                return
            # Fair handoff: the loop would otherwise reacquire the lock
            # immediately, and under CPython's GIL an admission waiter
            # whose timeout already expired can lose that race at EVERY
            # boundary while device steps hold the lock (lock convoy —
            # observed as a waiter never getting to raise ServerBusy
            # until the occupying request finished). One zero-sleep with
            # the lock released yields the GIL so waiters can take it.
            # locklint: allow[sleep-under-lock] deliberate GIL yield with the lock RELEASED — breaks the decode loop's lock convoy so expired admission waiters win the reacquisition race (rung 17 fair handoff; removing it starves ServerBusy)
            time.sleep(0)

    def _loop_once(self) -> str:
        """One decode-loop iteration under the lock ("exit" ends it)."""
        import jax.numpy as jnp

        with self._work:
            while (not self._active and not self._closed
                   and not self._sched_attention_locked()
                   and not (self._draining
                            and not self._prefilling)):
                self._work.wait()
            if (self._draining and not self._active
                    and not self._prefilling
                    and not self._sched.resume_pending_locked()):
                # Drained: every accepted request — including any
                # whose chunked prefill was in flight when the drain
                # began, and any swapped-out awaiting resume — has
                # finished.
                return "exit"
            if self._closed:
                for req in self._active.values():
                    req.error = ServerClosed("server shut down mid-"
                                             "request")
                    if req.stream is not None:
                        req.stream.put(req.error)
                    req.done.set()
                self._active.clear()
                self._fail_swapped_closed_locked()
                return "exit"
            try:
                self._sweep_cancelled_locked()
                self._sweep_finished_locked()
                # Scheduler boundary: resume swapped-out requests into
                # freed capacity, then preempt for a starved head.
                self._maybe_resume_locked()
                self._maybe_preempt_locked()
                self._maybe_step_bucket_locked()
                self._maybe_checkpoint_locked()
                self._observe_boundary_locked()
                if not self._active:
                    return "ran"
                if (self._spec > 0
                        and any(req.sampling is None
                                for req in self._active.values())):
                    # Speculative mode: greedy slots advance by verify
                    # passes (sampled slots ride along one token at a
                    # time); an all-sampled batch falls through to the
                    # cheaper single-query step below.
                    if self._spec_window > 0:
                        # Spec windows ride the overlap pipeline; the
                        # serial loop can only run legacy passes.
                        self._spec_window_fallbacks["overlap_off"] += 1
                    self._spec_pass()
                    return "ran"
                # Feed every active slot's pending token through ONE
                # batched step; inactive slots carry zeros (masked).
                # The explicit mask (not "every admitted slot") is
                # what keeps interleaved chunked prefills safe: a
                # half-prefilled slot is admitted but NOT active.
                tokens = np.zeros((self._cache.bucket,), np.int32)
                mask = np.zeros((self._cache.bucket,), bool)
                for slot, req in self._active.items():
                    tokens[slot] = req.next_token
                    mask[slot] = True
                window = self._window_steps()
                if window > 1:
                    # Device-side window: `window` steps in one
                    # dispatched scan — the host pays one round trip
                    # per window, not per token. Admission re-syncs
                    # between windows (a submitter blocks on this lock
                    # until the window returns, then joins the next
                    # one). Greedy-only batches run the plain argmax
                    # scan; a batch with sampled rows runs the mixed
                    # kernel, whose on-device key schedule emits the
                    # SAME tokens as the per-step path (pinned by
                    # tests) — one sampled co-tenant no longer drags
                    # the batch onto per-step dispatch.
                    samplers = {
                        slot: req
                        for slot, req in self._active.items()
                        if req.sampling is not None
                    }
                    t0 = time.perf_counter()
                    if not samplers:
                        produced = np.asarray(self._cache.step_window(
                            self._params, jnp.asarray(tokens), window,
                            active=mask,
                        ))
                    else:
                        produced = np.asarray(self._sampled_window(
                            tokens, window, mask, samplers
                        ))
                    # Serial path: the host blocks for the whole
                    # dispatch+force, so device time IS the call
                    # (rung 25 attribution; no pipeline slack here).
                    self._hist_device.observe(
                        (time.perf_counter() - t0) * 1e3
                    )
                    if self.tracer is not None:
                        # Fabric span (ungated): every window stamps,
                        # sampled request spans hang from them.
                        self.tracer.span(
                            "window", "serve", t0,
                            args={"w": window,
                                  "rows": len(self._active),
                                  "depth": 0},
                        )
                    for slot, req in list(self._active.items()):
                        self._emit(req, req.next_token)
                        finished = False
                        for i in range(window - 1):
                            t = int(produced[i, slot])
                            self._emit(req, t)
                            if t == req.stop_token:
                                # Host-side stop truncation: the serial
                                # window path touches every token here
                                # anyway, so the uncapped kernels carry
                                # no device-side stop rows. Nothing is
                                # in flight — finish immediately.
                                self._stop_finishes += 1
                                self._finish_request_locked(slot, req)
                                finished = True
                                break
                        if not finished:
                            req.next_token = int(
                                produced[window - 1, slot]
                            )
                            self._note_finish_candidate_locked(
                                slot, req
                            )
                    return "ran"
                t0 = time.perf_counter()
                if all(req.sampling is None
                       for req in self._active.values()):
                    # All-greedy per-step batch: the fused step+argmax
                    # program (kvcache.step_tokens) — one dispatch and
                    # a [B]-int read instead of a dispatch, a second
                    # argmax dispatch, and a [B, V] logits transfer.
                    # Token-identical: same argmax on the same logits.
                    picked = np.asarray(self._cache.step_tokens(
                        self._params, jnp.asarray(tokens), active=mask
                    ))
                    next_tokens = {
                        slot: int(picked[slot])
                        for slot in self._active
                    }
                else:
                    logits = self._cache.step(
                        self._params, jnp.asarray(tokens), active=mask
                    )
                    next_tokens = self._next_tokens(logits)
                # Per-step device time (serial path, rung 25): the
                # pick inside _next_tokens is the forcing read.
                self._hist_device.observe(
                    (time.perf_counter() - t0) * 1e3
                )
                if self.tracer is not None:
                    self.tracer.span(
                        "step", "serve", t0,
                        args={"rows": len(self._active)},
                    )
                for slot, req in self._active.items():
                    self._emit(req, req.next_token)
                    req.next_token = next_tokens[slot]
                    self._note_finish_candidate_locked(slot, req)
            except Exception as e:  # poison: fail every waiter loudly
                # Typed poisoning (runtime/failures.py): an already-
                # typed failure (e.g. SliceFollowerLost from the op
                # watchdog) passes through; anything else is wrapped as
                # PoolPoisoned with the cause chained. Waiters get the
                # typed error, new submits get _refusal()'s retry-after
                # hint, and the degraded flag flips for stats/healthz.
                self._poison_locked(classify_failure(e))
                return "exit"
        return "ran"

    # ---- overlapped decode loop ------------------------------------------

    def _loop_once_overlap(self) -> str:
        """One iteration of the double-buffered decode loop.

        Two alternating shapes. At a NON-OVERLAPPED BOUNDARY
        (``_inflight is None``) it reconciles exactly like the serial
        loop — cancel sweep, finish sweep, admissions implicitly via
        ``_active``, speculative passes — then DISPATCHES a window
        without harvesting it. With a window IN FLIGHT it first
        enqueues the next window on the device-resident carry (no host
        round trip between the two — this is the overlap), then
        harvests and processes the previous window's tokens while the
        next one runs. Whenever exactness needs a boundary (a cancel
        arrived, a newcomer admitted, budgets exhausted) it harvests
        WITHOUT dispatching, so the next iteration reconciles serially.

        A speculatively dispatched window can never corrupt state: each
        row's device-side ``steps_left`` cap freezes it at its true
        budget (frozen rows stop scattering K/V and stop advancing
        length — kvcache._paged_decode_window_capped_impl), and the
        host truncates each row's emitted stream at its own cap.
        """
        with self._work:
            while (not self._active and self._inflight is None
                   and not self._closed
                   and not self._sched_attention_locked()
                   and not (self._draining
                            and not self._prefilling)):
                self._work.wait()
            if (self._draining and not self._active
                    and self._inflight is None
                    and not self._prefilling
                    and not self._sched.resume_pending_locked()):
                return "exit"
            if self._closed:
                # Hard close: abandon the in-flight window unforced
                # (the device finishes it harmlessly; never block a
                # close on a potentially dead op stream) and fail the
                # waiters, as in the serial loop.
                rec, self._inflight = self._inflight, None
                if rec is not None:
                    for _, req, adv in rec["parts"]:
                        req.inflight -= adv
                for req in self._active.values():
                    req.error = ServerClosed("server shut down mid-"
                                             "request")
                    if req.stream is not None:
                        req.stream.put(req.error)
                    req.done.set()
                self._active.clear()
                self._fail_swapped_closed_locked()
                return "exit"
            try:
                if self._inflight is None:
                    self._sweep_cancelled_locked()
                    self._sweep_finished_locked()
                    # Preemption/resume join ONLY here — the
                    # non-overlapped boundary, where every row's
                    # tokens are reconciled and cache state is
                    # quiescent. Checkpoints share the boundary for
                    # the same reason: the swapout bytes must cover a
                    # reconciled, nothing-in-flight snapshot.
                    self._maybe_resume_locked()
                    self._maybe_preempt_locked()
                    self._maybe_step_bucket_locked()
                    self._maybe_checkpoint_locked()
                    self._observe_boundary_locked()
                    if not self._active:
                        return "ran"
                    if (self._spec > 0
                            and any(req.sampling is None
                                    for req in self._active.values())):
                        all_greedy = all(
                            req.sampling is None
                            for req in self._active.values()
                        )
                        if (self._spec_window > 0
                                and (all_greedy
                                     or self._spec_sampled_window)):
                            # Device-resident spec windows: draft +
                            # verify + accept/reject run IN the
                            # dispatched scan, so spec mode joins the
                            # double-buffered pipeline instead of
                            # forcing a boundary per pass. Sampled
                            # co-tenants ride the scan too (rung 23,
                            # knob-gated): one token per pass with
                            # their positional keys split on device.
                            self._inflight = (
                                self._dispatch_spec_window_locked(
                                    first=True
                                )
                            )
                            return "ran"
                        if self._spec_window > 0:
                            # Mixed batch with the sampled-window knob
                            # off: the one remaining windowed-path
                            # collapse, now counted instead of silent.
                            self._spec_window_fallbacks["sampled"] += 1
                        # Legacy per-pass speculation: drafting reads
                        # emitted tokens on the host, so passes run at
                        # boundaries only and never overlap.
                        self._spec_pass()
                        return "ran"
                    self._inflight = self._dispatch_window_locked(
                        first=True
                    )
                    return "ran"
                prev, self._inflight = self._inflight, None
                try:
                    if not self._boundary_wanted_locked(prev):
                        # Enqueue N+1 on the carry BEFORE touching
                        # N's result — the device starts N+1 the
                        # moment N retires, while the host is still
                        # in the harvest below. The next window rides
                        # the SAME carry kind as the previous one
                        # (plain and spec carries are separate device
                        # state); a kind change joins at a boundary.
                        if prev.get("kind") not in ("spec",
                                                    "spec_sampled"):
                            self._inflight = (
                                self._dispatch_window_locked(
                                    first=False
                                )
                            )
                        elif (self._spec > 0
                              and self._spec_window > 0):
                            # Kind-matched redispatch: both spec kinds
                            # share the device spec carry (pending +
                            # drafting context), so a mixed pipeline
                            # whose sampled rows all finished simply
                            # redispatches as plain "spec" on the same
                            # carry.
                            self._inflight = (
                                self._dispatch_spec_window_locked(
                                    first=False
                                )
                            )
                        else:
                            # Speculation was disabled with a spec
                            # window in flight — collapse to a
                            # boundary (counted: the next boundary
                            # runs the non-windowed path).
                            self._spec_window_fallbacks["spec_off"] += 1
                    elif self.tracer is not None:
                        # Overlap boundary: the pipeline collapses so a
                        # cancel/newcomer/swap can join reconciled.
                        self.tracer.event("boundary", "serve",
                                          args={"reason": "reconcile"})
                    if prev.get("kind") in ("spec", "spec_sampled"):
                        self._harvest_spec_window_locked(prev)
                    else:
                        self._harvest_locked(prev)
                except Exception:
                    # prev was not reconciled — restore its inflight
                    # accounting and drain it with whatever else is
                    # queued, then poison below.
                    self._drain_rec_locked(prev)
                    raise
            except Exception as e:
                # Poison path: drain the in-flight window FIRST so
                # recovery (revive/reform) never races a queued device
                # program, then fail every waiter loudly.
                self._drain_inflight_locked()
                self._poison_locked(classify_failure(e))
                return "exit"
        return "ran"

    def _boundary_wanted_locked(self, prev: dict) -> bool:
        """Should the pipeline fall back to a non-overlapped boundary
        instead of dispatching the next window? Yes when a cancel must
        be honored, or when a slot is active that the in-flight window
        never dispatched (a newcomer admission — it may only join at a
        boundary, where its first token is host-known; the carry row
        of a slot that sat out the previous window is garbage). The
        scheduler adds a third reason: a resumable or starved-but-
        preemptable head collapses the pipeline to a boundary, where
        the swap may join. A pending bucket step is a fourth: the
        device batch dim can only resize with nothing in flight."""
        dispatched = {slot for slot, _, _ in prev["parts"]}
        for slot, req in self._active.items():
            if req.cancelled or slot not in dispatched:
                return True
        # A fifth: an overdue checkpoint clock (rung 22). A saturated
        # pipeline can run windows back-to-back indefinitely; durability
        # needs a real boundary every ``checkpoint_every`` windows, so
        # the due clock forces the collapse the checkpoint rides.
        return (self._bucket_step_wanted
                or self._stops_pending > 0
                or (self._checkpoint_every > 0
                    and self._ckpt_clock >= self._checkpoint_every)
                or self._sched_attention_locked(ignore_inflight=True))

    def _fail_swapped_closed_locked(self) -> None:
        """Hard close reaches the swap set like the active set: a
        swapped-out request will never be resumed by an exiting loop —
        fail its waiter and free the host snapshot."""
        for entry in self._sched.take_swapped_locked():
            entry.arrays = ()  # nothing will journal this snapshot
            entry.req.error = ServerClosed(
                "server shut down mid-request (swapped out)"
            )
            if entry.req.stream is not None:
                entry.req.stream.put(entry.req.error)
            entry.req.done.set()
        self._sched.wake_all_locked()

    def _dispatch_window_locked(self, first: bool) -> dict | None:
        """Enqueue one capped window for every active slot with budget
        remaining (lock held); returns the in-flight record, or None
        when no slot can advance.

        ``first`` distinguishes the boundary dispatch (explicit
        host-known pending tokens) from the overlapped dispatch
        (``tokens=None`` — the cache feeds the previous window's final
        token row, still resident on device). The per-row cap is
        ``n_new - len(generated) - inflight - 1``: committed position
        plus the pending token the finish-check emits stepless, so a
        speculative window can never decode past a budget the host
        has not reconciled yet. A row whose previous window froze it
        early always reaches cap 0 here and sits the window out.
        """
        parts = []
        for slot, req in self._active.items():
            cap = req.n_new - len(req.generated) - req.inflight - 1
            if cap > 0 and not req.stopped:
                parts.append((slot, req, cap))
            elif req.inflight == 0:
                # Self-healing backstop for the O(finishes) sweep:
                # this loop is already O(active), so re-registering an
                # idle zero-budget (or stop-terminated) row costs
                # nothing and bounds a missed registration at one
                # extra iteration.
                self._finish_ready.add(slot)
        if not parts:
            return None
        # The widest remaining budget sets the window (pow2-floored,
        # same compiled-program set as the serial path): rows with
        # less budget freeze mid-window on device instead of dragging
        # every co-tenant down to the tightest budget.
        w = min(self._window, max(cap for _, _, cap in parts))
        if w > 1:
            w = 1 << (w.bit_length() - 1)
        n = self._cache.bucket
        tokens = np.zeros((n,), np.int32)
        mask = np.zeros((n,), bool)
        steps_left = np.zeros((n,), np.int32)
        stop_tokens = np.full((n,), -1, np.int32)
        recs = []
        for slot, req, cap in parts:
            adv = min(w, cap)
            tokens[slot] = req.next_token
            mask[slot] = True
            steps_left[slot] = adv
            stop_tokens[slot] = req.stop_token
            recs.append((slot, req, adv))
        samplers = {slot: req for slot, req, _ in parts
                    if req.sampling is not None}
        tok_arg = tokens if first else None
        if samplers:
            key_data = np.zeros(
                (n,) + self._key_data_shape(samplers), np.uint32
            )
            base_steps = np.zeros((n,), np.int32)
            temps = np.ones((n,), np.float32)
            top_ps = np.ones((n,), np.float32)
            smask = np.zeros((n,), bool)
            for slot, req in samplers.items():
                key_data[slot] = req.key_data
                # Committed position: the serial schedule's
                # len(generated)+1 with the unharvested advance
                # folded in, so token t still samples with
                # fold_in(seed, t) regardless of pipelining.
                base_steps[slot] = (len(req.generated)
                                    + req.inflight + 1)
                temps[slot] = float(req.sampling[1])
                top_ps[slot] = float(req.sampling[2])
                smask[slot] = True
            handle = self._cache.dispatch_window_sampled(
                self._params, tok_arg, w, mask, key_data, base_steps,
                temps, top_ps, smask, steps_left=steps_left,
                stop_tokens=stop_tokens,
            )
        else:
            handle = self._cache.dispatch_window(
                self._params, tok_arg, w, active=mask,
                steps_left=steps_left, stop_tokens=stop_tokens,
            )
        for _, req, adv in recs:
            req.inflight += adv
        self._hist_depth.observe(0.0 if first else 1.0)
        return {"window": w, "parts": recs, "handle": handle,
                "depth": 0 if first else 1,
                "t0": time.perf_counter()}

    def _harvest_locked(self, rec: dict) -> None:
        """Force an in-flight window's tokens and reconcile (lock
        held): emission, budget finishes, carry of the new pending
        token. Each row's stream truncates at its own dispatch-time
        cap (``adv``) — rows past their cap were frozen on device and
        their produced entries merely repeat the last live token."""
        t_force = time.perf_counter()
        produced = np.asarray(self._cache.harvest_window(rec["handle"]))
        t_harvest = time.perf_counter()
        # Device-time attribution (rung 25): the forced transfer is
        # where the host actually waits on the device — the RTT minus
        # this is pure host bookkeeping and pipeline slack.
        self._hist_device.observe((t_harvest - t_force) * 1e3)
        self._hist_rtt.observe((t_harvest - rec["t0"]) * 1e3)
        if self.tracer is not None:
            # Dispatch -> harvest span with the pipeline depth the
            # window was dispatched at (0 = boundary, 1 = overlapped).
            self.tracer.span(
                "window", "serve", rec["t0"], t_harvest,
                args={"w": rec["window"],
                      "rows": len(rec["parts"]),
                      "depth": rec.get("depth", 0)},
            )
        t_host = time.perf_counter()
        rec["counted"] = True
        self._ckpt_clock += 1  # window of progress at risk (rung 22)
        for _, req, adv in rec["parts"]:
            req.inflight -= adv
        w = rec["window"]
        stop_row = produced[w + 1]
        for slot, req, adv in rec["parts"]:
            if self._active.get(slot) is not req or req.stopped:
                # Released while in flight (hard-close/cancel races
                # resolve at boundaries, so normally unreachable), or
                # stop-terminated at an earlier harvest with its
                # finish deferred — nothing to emit into.
                continue
            # Device-resident finish bookkeeping (rung 23): rows
            # n_steps and n_steps+1 of the harvested block are the
            # packed per-slot finish reason (0 window-capped /
            # 1 budget-frozen / 2 stop) and the 1-based step of the
            # first stop hit — ONE transfer carries tokens and
            # bookkeeping both, and the host never compares per-token.
            stop_at = int(stop_row[slot])
            if 0 < stop_at and not req.cancelled:
                # Emit the pending token plus everything up to AND
                # INCLUDING the stop token, then finish; steps past
                # the stop decoded garbage inside the granted cap and
                # are discarded (the slot releases, so the device-side
                # over-advance is moot).
                room = req.n_new - len(req.generated)
                seq = [req.next_token]
                seq += produced[:stop_at, slot].tolist()
                self._emit_many(req, seq[:room])
                self._finish_stopped_locked(slot, req)
                continue
            # Bulk emission: one C-level column->list conversion per
            # LIVE row (rows the window advanced — O(changes), idle
            # bucket slots never touched), one extend, no per-token
            # Python frames.
            toks = produced[:adv, slot].tolist()
            self._emit_many(req, [req.next_token] + toks[:-1])
            req.next_token = toks[-1]
            if (len(req.generated) + 1 >= req.n_new
                    and not req.cancelled):
                # Inline finish: with the pipeline saturated the loop
                # may never visit a boundary, so a filled budget must
                # complete here. The cancelled guard preserves the
                # serial cancel-beats-finish order — the cancel sweep
                # at the forced boundary takes it.
                self._emit(req, req.next_token)
                self._finish_request_locked(slot, req)
        self._overlap_windows += 1
        host_ms = (time.perf_counter() - t_host) * 1e3
        self._hist_host.observe(host_ms)
        if self._autotune is not None:
            # Close the rung-16 loop (rung 26): feed the controller
            # this window's measured split and adopt its pick for the
            # NEXT dispatch. The carry redispatch takes the window as
            # a plain scan length, so mid-pipeline changes are safe —
            # the device carry is one token row, shape-independent of
            # the window.
            self._autotune.observe(
                rtt_ms=(t_harvest - rec["t0"]) * 1e3,
                device_ms=(t_harvest - t_force) * 1e3,
                host_ms=host_ms, window=w,
            )
            self._window = self._autotune.window()

    def _dispatch_spec_window_locked(self, first: bool) -> dict | None:
        """Enqueue one device-resident spec window — ``_spec_window``
        draft+verify passes in a single dispatched program — for every
        active greedy slot with budget remaining (lock held); returns
        the in-flight record (``kind="spec"``), or None when no slot
        can advance.

        ``first`` distinguishes the boundary dispatch (host-known
        pending tokens plus each row's drafting context: prompt +
        generated + pending) from the overlapped dispatch
        (``tokens=None`` — pending, context, and context lengths ride
        the device-resident spec carry). The per-row budget is
        ``n_new - len(generated) - inflight`` — the pending token is
        CONSUMED by the window (each pass emits it), unlike the plain
        window path's stepless finish-check emission, so there is no
        ``- 1``. The request's ``inflight`` advances by the cache's
        worst-case cap (``min(budget + K, W*(1+K))``); the true
        advance lands at harvest, truncated at the budget exactly like
        the legacy per-pass path's room cap.

        SAMPLED rows (rung 23, ``spec_sampled_window``) join the same
        window: the scan advances them exactly one token per live pass
        with on-device ``fold_in(seed, base + i)`` keys, so their cap
        is EXACT (``min(budget, W)`` — kvcache.spec_window_caps) and
        ``base = len(generated) + inflight + 1`` reproduces the legacy
        per-pass schedule bit-identically even across pipelined
        redispatches. The record's kind is ``"spec_sampled"`` when any
        sampled row rides (``"spec"`` otherwise); both kinds share the
        device spec carry, so kind-matched redispatch treats them as
        one family.
        """
        k = self._spec
        w = self._spec_window
        n = self._cache.bucket
        budgets = np.zeros((n,), np.int32)
        parts = []
        for slot, req in self._active.items():
            room = req.n_new - len(req.generated) - req.inflight
            if room > 0 and not req.stopped:
                budgets[slot] = room
                parts.append((slot, req))
            elif req.inflight == 0:
                # Same self-healing backstop as the plain dispatch.
                self._finish_ready.add(slot)
        if not parts:
            return None
        samplers = {slot: req for slot, req in parts
                    if req.sampling is not None}
        sampling = None
        if samplers:
            key_data = np.zeros(
                (n,) + self._key_data_shape(samplers), np.uint32
            )
            base_steps = np.zeros((n,), np.int32)
            temps = np.ones((n,), np.float32)
            top_ps = np.ones((n,), np.float32)
            smask = np.zeros((n,), bool)
            for slot, req in samplers.items():
                key_data[slot] = req.key_data
                # Committed position, as in the plain sampled window:
                # token t samples with fold_in(seed, t) regardless of
                # pipelining, because a sampled row's in-window advance
                # is exactly its cap (1 token per live pass).
                base_steps[slot] = (len(req.generated)
                                    + req.inflight + 1)
                temps[slot] = float(req.sampling[1])
                top_ps[slot] = float(req.sampling[2])
                smask[slot] = True
            sampling = (key_data, base_steps, temps, top_ps, smask)
        if first:
            ctx = np.zeros((n, self._spec_ctx_cap), np.int32)
            ctx_len = np.zeros((n,), np.int32)
            tokens = np.zeros((n,), np.int32)
            for slot, req in parts:
                seq = req.prompt + req.generated + [req.next_token]
                ctx[slot, :len(seq)] = seq
                ctx_len[slot] = len(seq)
                tokens[slot] = req.next_token
            handle = self._cache.dispatch_spec_window(
                self._params, tokens, w, k, budgets,
                ctx=ctx, ctx_len=ctx_len, sampling=sampling,
            )
        else:
            handle = self._cache.dispatch_spec_window(
                self._params, None, w, k, budgets, sampling=sampling,
            )
        recs = []
        for slot, req in parts:
            cap = int(handle["caps"][slot])
            req.inflight += cap
            recs.append((slot, req, cap))
        self._hist_depth.observe(0.0 if first else 1.0)
        return {"kind": "spec_sampled" if samplers else "spec",
                "window": w, "parts": recs,
                "handle": handle, "depth": 0 if first else 1,
                "t0": time.perf_counter()}

    def _harvest_spec_window_locked(self, rec: dict) -> None:
        """Force an in-flight spec window's results and reconcile
        (lock held). Each row replays its pending-token chain — pass
        ``p`` emits the pending token plus the accepted drafts
        (``counts[p] - 1`` of the emitted row; the final entry is the
        next pending) — truncated at the row's remaining budget, so a
        device-side overshoot (the last live pass may exceed the
        budget by up to K) never over-emits, exactly like the legacy
        path's room cap."""
        t_force = time.perf_counter()
        emitted, counts, _pending = self._cache.harvest_spec_window(
            rec["handle"]
        )
        t_harvest = time.perf_counter()
        # Device-time attribution (rung 25), as in _harvest_locked.
        self._hist_device.observe((t_harvest - t_force) * 1e3)
        self._hist_rtt.observe((t_harvest - rec["t0"]) * 1e3)
        if self.tracer is not None:
            self.tracer.span(
                "spec-window", "serve", rec["t0"], t_harvest,
                args={"w": rec["window"],
                      "rows": len(rec["parts"]),
                      "depth": rec.get("depth", 0)},
            )
        t_host = time.perf_counter()
        rec["counted"] = True
        self._ckpt_clock += 1  # window of progress at risk (rung 22)
        for _, req, cap in rec["parts"]:
            req.inflight -= cap
        self._spec_passes += rec["window"]
        for slot, req, cap in rec["parts"]:
            if self._active.get(slot) is not req or req.stopped:
                # Released while in flight (normally unreachable —
                # cancels resolve at boundaries) or stop-terminated at
                # an earlier harvest awaiting its deferred finish;
                # nothing to emit into.
                continue
            before = len(req.generated)
            stopped = False
            counts_col = counts[:, slot].tolist()
            for p in range(rec["window"]):
                c = counts_col[p]
                if c == 0:
                    # Frozen pass: the row's budget ran out on device
                    # (rem <= 0) — no tokens, no pending advance.
                    continue
                room = max(req.n_new - len(req.generated), 0)
                # Sampled rows advance exactly one token per pass
                # (c == 1): seq is just the pending token and the
                # device-sampled token becomes the next pending —
                # the legacy _spec_pass semantics, scanned.
                row = emitted[p, slot, :c].tolist()
                seq = ([req.next_token] + row[:-1])[:room]
                try:
                    # Host-side stop truncation, now a C-level list
                    # search instead of a per-token compare loop:
                    # later passes decoded garbage and are discarded.
                    stop_i = seq.index(req.stop_token)
                    seq = seq[:stop_i + 1]
                    stopped = True
                except ValueError:
                    pass
                self._emit_many(req, seq)
                emit_n = len(seq)
                req.next_token = row[-1]
                if req.sampling is None:
                    # Greedy acceleration stats only — sampled rows
                    # ride at one token per pass by construction and
                    # would drag the realized-acceptance gauge down.
                    self._spec_emitted += emit_n
                    self._spec_slot_passes += 1
                if stopped:
                    break
            self._hist_spec_tokens.observe(
                float(len(req.generated) - before)
            )
            if stopped and not req.cancelled:
                self._finish_stopped_locked(slot, req)
            elif (len(req.generated) >= req.n_new
                    and not req.cancelled):
                # Inline finish, as in the plain harvest: a saturated
                # pipeline may never visit a boundary. The cancelled
                # guard preserves cancel-beats-finish ordering.
                self._finish_request_locked(slot, req)
            else:
                # The carried pending may itself be the stop token (a
                # sampled row's device-sampled next, or a bonus token):
                # register it for the boundary sweep.
                self._note_finish_candidate_locked(slot, req)
        self._spec_windows += 1
        self._overlap_windows += 1
        host_ms = (time.perf_counter() - t_host) * 1e3
        self._hist_host.observe(host_ms)
        if self._autotune is not None:
            # Spec-depth channel (rung 26): verify passes have their
            # own per-pass device cost t_v, so the spec window keeps
            # its own EWMA stream. The pick applies only at a TRUE
            # boundary (nothing in flight — the next spec dispatch is
            # first=True and rebuilds from host tokens), never between
            # kind-matched carry redispatches, and never above the
            # operator's configured depth cap.
            self._autotune.observe(
                rtt_ms=(t_harvest - rec["t0"]) * 1e3,
                device_ms=(t_harvest - t_force) * 1e3,
                host_ms=host_ms, window=rec["window"],
                channel="spec",
            )
            if self._inflight is None and self._spec_window_cap > 0:
                pick = self._autotune.window(
                    "spec", default=self._spec_window_cap)
                self._spec_window = max(
                    1, min(self._spec_window_cap, pick))

    def _drain_rec_locked(self, rec: dict | None) -> None:
        """Unwind one in-flight record on the failure path: restore
        the inflight counters and block (deadline-bounded for a slice
        cache; its runner is dead-latched after a failure and returns
        immediately) until the device has retired the window, so
        recovery never tears down state a queued program still
        writes."""
        if rec is None:
            return
        if not rec.get("counted"):
            for _, req, adv in rec["parts"]:
                req.inflight -= adv
        try:
            if rec.get("kind") in ("spec", "spec_sampled"):
                self._cache.harvest_spec_window(rec["handle"])
            else:
                self._cache.harvest_window(rec["handle"])
        except Exception:
            pass

    def _drain_inflight_locked(self) -> None:
        rec, self._inflight = self._inflight, None
        self._drain_rec_locked(rec)
