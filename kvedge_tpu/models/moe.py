"""Mixture-of-experts FFN: expert parallelism over an ``expert`` mesh axis.

The third payload scale-out dimension alongside ``model`` (tensor) and
``seq`` (sequence) — the reference has no parallelism of any kind
(SURVEY.md §5); this exists because MoE is how a TPU-native payload
scales parameter count past one chip's HBM without scaling per-token
FLOPs.

TPU-first design decisions:

* **Top-k routing (k = 1 Switch, k = 2 GShard) with a static capacity.**
  Every shape is compile-time constant: each expert processes exactly
  ``C = ceil(k * tokens/E * capacity_factor)`` slots, and dispatches
  routed past an expert's capacity are *dropped* (their FFN contribution
  is zero and the residual connection carries them through — the
  standard trade that keeps XLA shapes static instead of introducing
  data-dependent gather/scatter). First choices take capacity priority
  over second choices; top-1 gates with the raw router probability,
  top-2 normalizes the pair.
* **Dispatch and combine are einsums with one-hot tensors**, not
  scatters: ``[N, E, C]`` dispatch against ``[N, D]`` activations gives
  ``[E, C, D]`` expert inputs on the MXU, and the transpose einsum
  combines outputs back. XLA partitions these einsums over the mesh.
* **Sharding is annotation-only**, like the rest of the package: expert
  weights are stacked on a leading ``E`` axis sharded over the
  ``expert`` mesh axis (parallel/sharding.py), activations get a
  ``with_sharding_constraint`` pinning the ``E`` dim of the dispatched
  block — XLA's SPMD partitioner inserts the all-to-alls. No shard_map.
* **Router math in fp32** (softmax over expert logits is tiny but
  numerically load-bearing); expert FFN matmuls in the model's compute
  dtype (bf16 on TPU).

The router's load-balancing aux loss (Switch eq. 4 over *first* choices:
``E * Σ_e f_e·P_e``, minimized at 1.0 when routing is uniform) is
returned alongside the output and folded into the training loss by
``loss_fn`` — without it, learned routing collapses onto a few experts.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P


def warn_if_train_serve_divergence(cfg) -> None:
    """Warn when cached serving can silently disagree with training.

    The serving paths route droplessly; training drops dispatches past
    capacity. Per-expert demand is at most ``n_tokens`` (a token's top-k
    choices are distinct experts) and capacity is
    ``ceil(top_k * n_tokens * factor / E)``, so
    ``expert_capacity_factor * expert_top_k >= n_experts`` guarantees
    zero training drops (the two paths then compute the same function);
    below that, an operator who trains with drops and serves dropless
    diverges *silently* — hence a loud warning at the serving boundary
    (cache construction), where the pairing actually happens. Training
    alone with a binding capacity is a deliberate, standard trade and
    stays silent.
    """
    import warnings

    if (cfg.n_experts
            and cfg.expert_capacity_factor * cfg.expert_top_k
            < cfg.n_experts):
        warnings.warn(
            f"MoE serving with expert_capacity_factor="
            f"{cfg.expert_capacity_factor} * expert_top_k="
            f"{cfg.expert_top_k} < n_experts={cfg.n_experts}: training "
            "may have dropped dispatches that dropless serving will "
            "route, so cached decode can disagree with the "
            "teacher-forced forward pass. Train with "
            "expert_capacity_factor >= n_experts / expert_top_k for "
            "exact train/serve agreement (models/moe.py).",
            RuntimeWarning, stacklevel=3,
        )


def expert_capacity(n_tokens: int, n_experts: int,
                    capacity_factor: float) -> int:
    """Per-expert slot count: ceil(tokens/E * factor), at least 1."""
    import math

    return max(1, math.ceil(n_tokens * capacity_factor / n_experts))


def _route(x, router_w, top_k: int):
    """Shared routing decision. Returns (probs [N, E], idx [N, k],
    gates [N, k] fp32).

    Gate convention follows the source papers: top-1 uses the raw router
    probability (Switch); top-2 normalizes the pair to sum to 1 (GShard).
    Both training dispatch and the dropless serving path call this, so
    the two cannot disagree about gating.
    """
    logits = x.astype(jnp.float32) @ router_w.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)                 # [N, E]
    topk_probs, topk_idx = lax.top_k(probs, top_k)          # [N, k]
    if top_k == 1:
        gates = topk_probs
    else:
        gates = topk_probs / jnp.sum(topk_probs, axis=-1, keepdims=True)
    return probs, topk_idx, gates


def moe_ffn(x, router_w, w_up, w_down, *, capacity_factor: float,
            top_k: int = 1, mesh=None, expert_axis: str = "expert"):
    """Top-k (k = 1 or 2) MoE feed-forward. x: [N, D] tokens.

    router_w: [D, E] fp32; w_up: [E, D, F]; w_down: [E, F, D] (compute
    dtype). Returns ``(out [N, D], aux_loss scalar fp32)``.

    Top-2: each token dispatches to its two highest-probability experts
    with gates normalized over the pair (GShard). Capacity accounting
    gives first choices strict priority — every token's first choice
    claims its expert slot before any second choice does — and capacity
    itself scales with k (k dispatches per token).
    """
    n_tokens, d = x.shape
    n_experts = router_w.shape[-1]
    capacity = expert_capacity(top_k * n_tokens, n_experts, capacity_factor)

    probs, topk_idx, gates = _route(x, router_w, top_k)
    onehots = jax.nn.one_hot(topk_idx, n_experts,
                             dtype=jnp.float32)             # [N, k, E]

    # Flatten (choice, token) with all FIRST choices before any second
    # choice, so the cumsum-based capacity positions give first choices
    # strict priority. Each flat row then routes independently, exactly
    # like the top-1 scheme.
    flat = onehots.transpose(1, 0, 2).reshape(
        top_k * n_tokens, n_experts
    )                                                        # [kN, E]
    position = jnp.cumsum(flat, axis=0) * flat - 1.0
    within = (position < capacity) & (position >= 0)
    dispatch = jnp.where(within, flat, 0.0)                 # [kN, E]
    # Each kept row's slot index (dropped rows contribute a zero row in
    # dispatch_ohc regardless of the slot value picked here).
    slot_index = jnp.sum(position * dispatch, axis=-1).astype(jnp.int32)
    slot = jax.nn.one_hot(slot_index, capacity, dtype=jnp.float32)
    dispatch_ohc = dispatch[:, :, None] * slot[:, None, :]  # [kN, E, C]

    # Aux load-balancing loss over the *pre-capacity* FIRST-choice
    # routing (Switch Transformer eq. 4): minimized at 1.0 when uniform.
    fraction = jnp.mean(onehots[:, 0, :], axis=0)           # [E]
    mean_prob = jnp.mean(probs, axis=0)                     # [E]
    aux_loss = n_experts * jnp.sum(fraction * mean_prob)

    # Merge the k choices back to per-token dispatch/combine tensors
    # before the big einsums: a token's choices route to *distinct*
    # experts and every kept dispatch owns a unique (expert, slot), so
    # the per-choice one-hots never overlap and summing them is exact —
    # and the dispatch/combine einsums then run over N rows, not kN.
    dispatch_tok = dispatch_ohc.reshape(
        top_k, n_tokens, n_experts, capacity
    )                                                        # [k, N, E, C]
    gates_flat = gates.transpose(1, 0).reshape(top_k * n_tokens)
    combine_tok = (
        dispatch_ohc * gates_flat[:, None, None]
    ).reshape(top_k, n_tokens, n_experts, capacity)

    dtype = x.dtype
    expert_in = jnp.einsum(
        "nec,nd->ecd", dispatch_tok.sum(axis=0).astype(dtype), x
    )                                                        # [E, C, D]
    if mesh is not None and expert_axis in mesh.axis_names:
        constrain = NamedSharding(mesh, P(expert_axis, None, None))
        expert_in = lax.with_sharding_constraint(expert_in, constrain)
    hidden = jax.nn.gelu(
        jnp.einsum("ecd,edf->ecf", expert_in, w_up.astype(dtype))
    )
    expert_out = jnp.einsum("ecf,efd->ecd", hidden, w_down.astype(dtype))
    if mesh is not None and expert_axis in mesh.axis_names:
        expert_out = lax.with_sharding_constraint(expert_out, constrain)

    combine = combine_tok.sum(axis=0).astype(dtype)          # [N, E, C]
    out = jnp.einsum("nec,ecd->nd", combine, expert_out)     # [N, D]
    return out, aux_loss


def moe_ffn_dropless(x, router_w, w_up, w_down, *, top_k: int = 1):
    """Per-token routed FFN without capacity limits — the serving path.

    x: [N, D]; router_w [D, E] fp32; w_up [E, D, F] / w_down [E, F, D]
    (compute dtype). Returns [N, D].

    At decode time there is no load to balance and no batch-wide cumsum
    to keep static: each token simply runs through its top-k experts,
    combined with the same gates the training path uses (:func:`_route`),
    so cached decode agrees with the teacher-forced forward pass
    *provided training capacity never bound* (capacity_factor * top_k >=
    n_experts guarantees zero drops — see
    :func:`warn_if_train_serve_divergence`; a dispatch dropped in
    training forward but served here would diverge).

    Implementation gathers each token's expert weights ([N, D, F] per
    choice) — ideal for decode (N = batch). Large prefills go through
    :func:`routed_ffn_block`, which switches to einsum dispatch past
    ``_GATHER_MAX_TOKENS``.
    """
    _, topk_idx, gates = _route(x, router_w, top_k)
    dtype = x.dtype
    out = None
    for choice in range(top_k):
        idx = topk_idx[:, choice]
        w_up_tok = w_up[idx].astype(dtype)                  # [N, D, F]
        w_down_tok = w_down[idx].astype(dtype)              # [N, F, D]
        hidden = jax.nn.gelu(jnp.einsum("nd,ndf->nf", x, w_up_tok))
        contrib = jnp.einsum("nf,nfd->nd", hidden, w_down_tok)
        contrib = contrib * gates[:, choice, None].astype(dtype)
        out = contrib if out is None else out + contrib
    return out


# The per-token weight gather materializes [chunk, D, F] weight copies —
# ideal at decode (chunk = batch) but ~N/E x the whole layer's weights
# for a long prefill. Past this many tokens the serving block runs the
# SAME gather in lax.map'd chunks: routing stays per-token identical,
# memory stays bounded at one chunk's weight copies, and cost stays
# linear in N (matmul rounding can differ across chunk shapes, as it
# already does between the gather and training-dispatch paths). A
# dropless einsum dispatch is NOT a substitute here: guaranteeing zero
# drops needs capacity = k*N, making the dispatch one-hots O(N^2).
_GATHER_MAX_TOKENS = 64


def routed_ffn_block(normed, router_w, w_up, w_down, *, top_k: int = 1):
    """The serving layers' MoE MLP block: [B, Q, D] in, [B, Q, D] out.

    Shared by the contiguous (decode.py) and paged (kvcache.py) decode
    paths so the two cannot drift. Decode steps gather per-token expert
    weights directly; long prefills run the identical gather chunked
    under ``lax.map`` so weight-copy memory stays bounded.
    """
    batch, q_len, d = normed.shape
    n_tokens = batch * q_len
    flat = normed.reshape(n_tokens, d)
    if n_tokens <= _GATHER_MAX_TOKENS:
        out = moe_ffn_dropless(flat, router_w, w_up, w_down, top_k=top_k)
    else:
        chunk = _GATHER_MAX_TOKENS
        pad = -n_tokens % chunk
        padded = jnp.pad(flat, ((0, pad), (0, 0)))
        out = lax.map(
            lambda c: moe_ffn_dropless(
                c, router_w, w_up, w_down, top_k=top_k
            ),
            padded.reshape(-1, chunk, d),
        ).reshape(-1, d)[:n_tokens]
    return out.reshape(batch, q_len, d)
