"""Mixture-of-experts FFN: expert parallelism over an ``expert`` mesh axis.

The third payload scale-out dimension alongside ``model`` (tensor) and
``seq`` (sequence) — the reference has no parallelism of any kind
(SURVEY.md §5); this exists because MoE is how a TPU-native payload
scales parameter count past one chip's HBM without scaling per-token
FLOPs.

TPU-first design decisions:

* **Switch-style top-1 routing with a static capacity.** Every shape is
  compile-time constant: each expert processes exactly
  ``C = ceil(tokens/E * capacity_factor)`` slots, tokens routed past an
  expert's capacity are *dropped* (their FFN contribution is zero and
  the residual connection carries them through — the standard Switch
  Transformer trade that keeps XLA shapes static instead of introducing
  data-dependent gather/scatter).
* **Dispatch and combine are einsums with one-hot tensors**, not
  scatters: ``[N, E, C]`` dispatch against ``[N, D]`` activations gives
  ``[E, C, D]`` expert inputs on the MXU, and the transpose einsum
  combines outputs back. XLA partitions these einsums over the mesh.
* **Sharding is annotation-only**, like the rest of the package: expert
  weights are stacked on a leading ``E`` axis sharded over the
  ``expert`` mesh axis (parallel/sharding.py), activations get a
  ``with_sharding_constraint`` pinning the ``E`` dim of the dispatched
  block — XLA's SPMD partitioner inserts the all-to-alls. No shard_map.
* **Router math in fp32** (softmax over expert logits is tiny but
  numerically load-bearing); expert FFN matmuls in the model's compute
  dtype (bf16 on TPU).

The router's load-balancing aux loss (Switch eq. 4: ``E * Σ_e f_e·P_e``,
minimized at 1.0 when routing is uniform) is returned alongside the
output and folded into the training loss by ``loss_fn`` — without it,
top-1 routing collapses onto a few experts.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P


def expert_capacity(n_tokens: int, n_experts: int,
                    capacity_factor: float) -> int:
    """Per-expert slot count: ceil(tokens/E * factor), at least 1."""
    import math

    return max(1, math.ceil(n_tokens * capacity_factor / n_experts))


def moe_ffn(x, router_w, w_up, w_down, *, capacity_factor: float,
            mesh=None, expert_axis: str = "expert"):
    """Top-1 MoE feed-forward. x: [N, D] tokens (any leading flattening).

    router_w: [D, E] fp32; w_up: [E, D, F]; w_down: [E, F, D] (compute
    dtype). Returns ``(out [N, D], aux_loss scalar fp32)``.
    """
    n_tokens, d = x.shape
    n_experts = router_w.shape[-1]
    capacity = expert_capacity(n_tokens, n_experts, capacity_factor)

    # Routing in fp32.
    router_logits = x.astype(jnp.float32) @ router_w.astype(jnp.float32)
    probs = jax.nn.softmax(router_logits, axis=-1)          # [N, E]
    expert_index = jnp.argmax(probs, axis=-1)               # [N]
    onehot = jax.nn.one_hot(expert_index, n_experts,
                            dtype=jnp.float32)              # [N, E]
    gate = jnp.sum(probs * onehot, axis=-1)                 # [N]

    # Position of each token within its expert's capacity buffer; tokens
    # past capacity get dropped (mask -> 0) — shapes stay static.
    position = jnp.cumsum(onehot, axis=0) * onehot - 1.0    # [N, E]
    within = (position < capacity) & (position >= 0)
    dispatch = jnp.where(within, onehot, 0.0)               # [N, E]
    # Each kept token's slot index: position at its expert's column
    # (dispatch is the mask, so dropped tokens contribute a zero row in
    # dispatch_ohc regardless of the slot value picked here).
    slot_index = jnp.sum(position * dispatch, axis=-1).astype(jnp.int32)
    slot = jax.nn.one_hot(slot_index, capacity, dtype=jnp.float32)  # [N, C]
    dispatch_ohc = dispatch[:, :, None] * slot[:, None, :]  # [N, E, C]

    # Aux load-balancing loss over the *pre-capacity* routing decision
    # (Switch Transformer eq. 4): minimized at 1.0 for uniform routing.
    fraction = jnp.mean(onehot, axis=0)                     # [E]
    mean_prob = jnp.mean(probs, axis=0)                     # [E]
    aux_loss = n_experts * jnp.sum(fraction * mean_prob)

    dtype = x.dtype
    expert_in = jnp.einsum(
        "nec,nd->ecd", dispatch_ohc.astype(dtype), x
    )                                                        # [E, C, D]
    if mesh is not None and expert_axis in mesh.axis_names:
        constrain = NamedSharding(mesh, P(expert_axis, None, None))
        expert_in = lax.with_sharding_constraint(expert_in, constrain)
    hidden = jax.nn.gelu(
        jnp.einsum("ecd,edf->ecf", expert_in, w_up.astype(dtype))
    )
    expert_out = jnp.einsum("ecf,efd->ecd", hidden, w_down.astype(dtype))
    if mesh is not None and expert_axis in mesh.axis_names:
        expert_out = lax.with_sharding_constraint(expert_out, constrain)

    combine = (dispatch_ohc * gate[:, None, None]).astype(dtype)
    out = jnp.einsum("nec,ecd->nd", combine, expert_out)    # [N, D]
    return out, aux_loss


def moe_ffn_dropless(x, router_w, w_up, w_down):
    """Per-token routed FFN without capacity limits — the serving path.

    x: [N, D]; router_w [D, E] fp32; w_up [E, D, F] / w_down [E, F, D]
    (compute dtype). Returns [N, D].

    At decode time there is no load to balance and no batch-wide cumsum
    to keep static: each token simply runs through its argmax expert,
    scaled by the router gate — the same per-token math as the training
    path's dispatch/combine, so cached decode agrees with the
    teacher-forced forward pass *provided training capacity never bound*
    (capacity_factor >= n_experts guarantees zero drops; a token dropped
    in training forward but served here would diverge).

    Implementation gathers each token's expert weights ([N, D, F]) —
    ideal for decode (N = batch) and fine for probe-scale prefill;
    large-batch MoE prefill wants the einsum-dispatch path instead
    (future work, README).
    """
    logits = x.astype(jnp.float32) @ router_w.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)                 # [N, E]
    expert_index = jnp.argmax(probs, axis=-1)               # [N]
    gate = jnp.max(probs, axis=-1)                          # [N]
    dtype = x.dtype
    w_up_tok = w_up[expert_index].astype(dtype)             # [N, D, F]
    w_down_tok = w_down[expert_index].astype(dtype)         # [N, F, D]
    hidden = jax.nn.gelu(jnp.einsum("nd,ndf->nf", x, w_up_tok))
    out = jnp.einsum("nf,nfd->nd", hidden, w_down_tok)
    return out * gate[:, None].astype(dtype)


def routed_ffn_block(normed, router_w, w_up, w_down):
    """The serving layers' MoE MLP block: [B, Q, D] in, [B, Q, D] out.

    Shared by the contiguous (decode.py) and paged (kvcache.py) decode
    paths so the two cannot drift — just the flatten/route/unflatten
    around :func:`moe_ffn_dropless`.
    """
    batch, q_len, d = normed.shape
    out = moe_ffn_dropless(
        normed.reshape(batch * q_len, d), router_w, w_up, w_down
    )
    return out.reshape(batch, q_len, d)
