"""SLO-aware admission scheduling for the paged serving stack.

SERVING.md rung 17. The serving layer (models/serving.py) used to admit
through a bare ``Condition.notify_all`` wait: admission order was
whatever the lock handed out, a long batch job and a latency-critical
interactive request were indistinguishable, and the only overload
behavior was each caller burning its full timeout into ``ServerBusy``.
This module is the policy layer that turns the paged pool's existing
mechanisms (worst-case reservation, refcounted pages, boundary-only
mutation) into controlled behavior under contention. Three pillars:

* **Priority admission.** Every request carries a priority class
  (``interactive``/``batch`` by default — the class list is a
  constructor argument, so it is extensible) and an optional deadline.
  Waiters park on a per-class ticketed queue: each ticket gets its OWN
  condition variable on the server lock, and only the policy head is
  ever woken, so admission is FIFO within a class by construction —
  no thundering herd, no lock-convoy ordering races. Across classes
  the ``policy`` knob picks strict priority (head = best class with a
  waiter), weighted sharing (deficit-style weighted round-robin, so a
  flood of interactive work cannot starve batch forever), or plain
  global FIFO (the baseline the bench's overload leg compares
  against).

* **Preemptive KV swap.** When the head of the queue cannot admit and
  a strictly lower-class request holds a slot, the decode loop (at a
  non-overlapped window boundary — the only place cache state is
  quiescent) swaps the victim out: its live pages are snapshotted to
  host RAM AS STORED (``PagedKVCache.swapout_pages`` — verbatim pool
  bytes, including the int8 scale slabs, so restore is bit-identical),
  its slot and reservation are released, and a resume entry carrying
  its ORIGINAL ticket number re-enters the class queue. Resume re-runs
  admission (worst-case reservation first — the same invariant that
  makes normal admission safe makes swap-in safe), writes the bytes
  back, and the request continues from its saved length; the
  positional sampling-key schedule makes the resumed token stream
  bit-identical to a never-preempted run. Host memory for snapshots is
  bounded by ``swap_budget_mb``; 0 disables preemption entirely.

* **Overload shedding.** Queue-depth and measured-queue-wait
  watermarks reject at submit time with the measured ``retry_after``
  hint (an EWMA of recent per-class admission waits), instead of
  letting every caller burn its full timeout. A request whose own
  deadline is provably unmeetable (estimated wait exceeds
  ``deadline_ms``) is shed the same way. Two guards keep shedding
  honest: the depth watermark only counts tickets the policy would
  actually serve ahead of the arrival (a parked batch flood must not
  shed an interactive request it cannot delay), and the wait estimate
  ages toward zero between admissions — shed requests never enqueue,
  so without decay a transient spike would freeze the EWMA above the
  watermark and shed a class forever on an idle server.

The scheduler is pure policy + bookkeeping: it raises no serving
exceptions and touches no cache state. Every method that ends in
``_locked`` MUST be called with the server's work lock held — the
scheduler deliberately shares that one lock (SERVING.md invariant 5)
instead of adding its own, so queue state, slot state, and page
accounting mutate atomically together.

The reference has no serving at all (SURVEY.md §0); the scheduling
design follows vLLM's preempt-via-swap (Kwon et al., SOSP '23) and
Sarathi-Serve's SLO-aware admission (Agrawal et al., OSDI '24) adapted
to this repo's boundary-only, exactness-pinned serving loop.
"""

from __future__ import annotations

import bisect
import threading
import time

# Priority classes in RANK ORDER: index 0 is the most latency-critical.
# The serving layer passes this default; deployments with more tiers
# hand AdmissionScheduler a longer tuple.
DEFAULT_CLASSES = ("interactive", "batch")

# Queue-wait histogram buckets (milliseconds). Sub-ms admissions land
# in the first bucket; 120 s was the old cap (the default submit
# timeout) — the log-spaced tail past it keeps overload p99s
# measurable instead of clamped. Existing edges are unchanged so
# cumulative bucket deltas stay comparable across snapshots.
_WAIT_EDGES_MS = (1.0, 5.0, 10.0, 50.0, 100.0, 500.0, 1000.0,
                  5000.0, 10000.0, 30000.0, 60000.0, 120000.0,
                  240000.0, 480000.0, 960000.0)

# EWMA smoothing for the measured per-class queue wait (the shed
# watermark and the retry_after hint): ~5 admissions of memory.
_EWMA_ALPHA = 0.2

# Prefix-affinity bypass bound (SERVING.md rung 24): at most this many
# consecutive hot (HBM-resident-prefix) admissions may jump past a
# head that does not fit before the head MUST admit next — bounded
# priority inversion, never starvation.
_BYPASS_CAP = 4


class _Hist:
    """Fixed-bucket histogram in Prometheus shape: ``edges`` are ``le``
    upper bounds, counts are stored PER bucket (last slot = +Inf) and
    cumulated at render time (runtime/status.py), so one observation
    touches one counter. Mutated only under the server lock; snapshots
    copy plain ints/floats."""

    __slots__ = ("edges", "counts", "total", "n")

    def __init__(self, edges: tuple):
        self.edges = tuple(float(e) for e in edges)
        self.counts = [0] * (len(self.edges) + 1)
        self.total = 0.0
        self.n = 0

    def observe(self, v: float) -> None:
        # bisect_left: v == edge lands IN that edge's bucket (le means
        # "less than or equal", the Prometheus boundary convention).
        self.counts[bisect.bisect_left(self.edges, v)] += 1
        self.total += v
        self.n += 1

    def snapshot(self) -> dict:
        return {"edges": list(self.edges), "counts": list(self.counts),
                "sum": self.total, "count": self.n}


class _Entry:
    """One queued admission unit.

    Either a parked ticket (a live submitter thread waiting on
    ``cond``) or a resume entry (a preempted request's swapped-out
    state, serviced by the decode loop at a boundary — no thread, no
    condition). ``no`` is the global arrival ticket: FIFO within a
    class orders by it, and a resume entry KEEPS the number it was
    first admitted under, so a preempted request re-enters ahead of
    everything that arrived after it.
    """

    __slots__ = ("no", "pclass", "req", "pages_needed", "cond",
                 "enqueued_at", "resume", "saved_len", "arrays",
                 "nbytes", "hot")

    def __init__(self, no: int, pclass: str, req, pages_needed: int,
                 cond, enqueued_at: float, *, resume: bool = False,
                 saved_len: int = 0, arrays: tuple = (),
                 nbytes: int = 0):
        self.no = no
        self.pclass = pclass
        self.req = req
        self.pages_needed = pages_needed
        self.cond = cond
        self.enqueued_at = enqueued_at
        self.resume = resume
        self.saved_len = saved_len
        self.arrays = arrays
        self.nbytes = nbytes
        # Prefix affinity (SERVING.md rung 24): the serving layer
        # refreshes this on each park-loop wake — True iff the
        # ticket's prompt currently matches an HBM-resident cached
        # prefix, making it cheaper to admit than its page count says.
        self.hot = False


class AdmissionScheduler:
    """Per-class ticketed admission queue + preemption bookkeeping.

    Owns WHO runs: queue order, the policy head, shed watermarks, the
    swapped-out set, and every scheduling counter/histogram exported
    through ``/metrics``. It does not own HOW anything runs — slot
    assignment, page reservation, and the actual swap device calls stay
    in the serving layer, which calls in under its own lock.
    """

    def __init__(self, lock, *, policy: str = "strict",
                 weights: dict | None = None,
                 classes: tuple = DEFAULT_CLASSES,
                 max_queue_depth: int = 0,
                 max_queue_wait_s: float = 0.0,
                 swap_budget_mb: int = 0,
                 tracer=None):
        if policy not in ("fifo", "strict", "weighted"):
            raise ValueError(
                f"scheduler policy must be 'fifo', 'strict' or "
                f"'weighted', got {policy!r}"
            )
        if not classes:
            raise ValueError("need at least one priority class")
        self._lock = lock
        self.policy = policy
        self.classes = tuple(classes)
        self._rank = {c: i for i, c in enumerate(self.classes)}
        self._weights = {c: 1.0 for c in self.classes}
        for c, w in (weights or {}).items():
            if c not in self._rank:
                raise ValueError(f"weight for unknown priority class "
                                 f"{c!r} (known: {self.classes})")
            if w <= 0:
                raise ValueError(f"priority weight for {c!r} must be "
                                 f"> 0, got {w}")
            self._weights[c] = float(w)
        self.max_queue_depth = int(max_queue_depth)
        self.max_queue_wait_s = float(max_queue_wait_s)
        self.swap_budget_bytes = int(swap_budget_mb) * (1 << 20)
        # Per-class queues of _Entry, kept sorted by ticket number
        # (resume entries re-enter with OLD numbers, so insertion is a
        # sorted insert, not an append).
        self._queues: dict[str, list] = {c: [] for c in self.classes}
        self._next_no = 0
        # Admission sequence: victim selection preempts the LATEST
        # admitted request of the lowest class (least progress lost).
        self._next_admit_seq = 0
        # Weighted policy state: admissions served per class; the head
        # is the nonempty class minimizing (served+1)/weight, which is
        # deterministic and stable between admissions.
        self._served = {c: 0 for c in self.classes}
        # Measured queue wait per class (seconds, EWMA) — the shed
        # watermark input and the retry_after hint — plus the time of
        # the class's last admission, which ages the estimate: shed
        # decisions happen BEFORE enqueue, so a shed request never
        # feeds a sample back, and an undecayed estimate would keep
        # shedding long after the overload passed.
        self._wait_ewma: dict[str, float | None] = {
            c: None for c in self.classes
        }
        self._last_admit = {c: time.monotonic() for c in self.classes}
        self._hist_wait = {c: _Hist(_WAIT_EDGES_MS)
                           for c in self.classes}
        # Swap residency (swap-out to resume) is observed separately:
        # folding it into the queue-wait histogram would inflate the
        # admission p99 operators read, while the EWMA deliberately
        # excludes it — the two consumers must measure the same thing.
        self._hist_swap = {c: _Hist(_WAIT_EDGES_MS)
                           for c in self.classes}
        # Request-scoped tracing (SERVING.md rung 18): an optional
        # runtime/tracing.py Tracer shared with the serving layer. All
        # emissions here run under the server lock and are one ring
        # append each — lock-cheap by the tracer's contract.
        self.tracer = tracer
        # Host bytes currently held by swap snapshots.
        self.swap_bytes = 0
        # Counters (cumulative; survive revive()).
        self.preemptions = 0
        self.resumes = 0
        self.shed = 0
        # Prefix-affinity bypass (rung 24): consecutive hot admissions
        # taken past a non-fitting head. Bounded (_BYPASS_CAP) so a
        # stream of cache-hitting arrivals cannot starve a cold head —
        # the streak resets every time the true head admits.
        self.hot_bypasses = 0
        self._bypass_streak = 0
        # Error-budget burn gate (SERVING.md rung 25, knob-gated via
        # [payload] serving_slo_shed): a () -> bool the serving layer
        # installs when the knob is on — True while the SLO engine's
        # multi-window burn-rate alert fires, at which point non-top
        # classes shed at the door (batch work is the error budget's
        # cheapest relief valve). None (the default) keeps every shed
        # path byte-for-byte the rung-17 one.
        self.burn_input = None

    # ---- ranks & small queries ------------------------------------------

    def rank(self, pclass: str) -> int:
        """Smaller = more latency-critical. Raises on unknown class."""
        try:
            return self._rank[pclass]
        except KeyError:
            raise ValueError(
                f"unknown priority class {pclass!r} "
                f"(known: {self.classes})"
            ) from None

    def next_admit_seq_locked(self) -> int:
        seq = self._next_admit_seq
        self._next_admit_seq += 1
        return seq

    def depth_locked(self, pclass: str | None = None) -> int:
        """Parked tickets (resume entries excluded — those hold no
        caller thread and are invisible to the shed watermark)."""
        qs = ([self._queues[pclass]] if pclass is not None
              else self._queues.values())
        return sum(1 for q in qs for e in q if not e.resume)

    def depths_locked(self) -> dict:
        return {c: self.depth_locked(c) for c in self.classes}

    def depth_text_locked(self) -> str:
        """Per-class queue depth for refusal messages: satellite 2 —
        a shed or busy caller learns WHAT it is queued behind."""
        return ", ".join(f"{c}={self.depth_locked(c)}"
                         for c in self.classes)

    def swapped_locked(self) -> list:
        return [e for q in self._queues.values() for e in q if e.resume]

    def resume_pending_locked(self) -> bool:
        return any(e.resume for q in self._queues.values() for e in q)

    @property
    def preemption_enabled(self) -> bool:
        """Preemption needs both a class ordering to act on (FIFO has
        none) and host memory to park victims in."""
        return self.policy != "fifo" and self.swap_budget_bytes > 0

    # ---- the policy head -------------------------------------------------

    def head_locked(self):
        """The ONE entry eligible to admit next, or None.

        * ``fifo``: global ticket order — the scheduler degenerates to
          a fair FIFO (still fixes the notify_all ordering race).
        * ``strict``: best-ranked class with a waiter, FIFO within.
        * ``weighted``: deficit-style weighted round-robin — the
          nonempty class minimizing (served+1)/weight, rank breaking
          ties — so every class with weight > 0 makes progress.

        Head-of-line is intentional: a later, smaller request never
        bypasses the head (bypass would starve large requests — the
        fairness bug this module exists to fix). Preemption, not
        bypass, is how a blocked high-class head gets capacity.
        """
        nonempty = [c for c in self.classes if self._queues[c]]
        if not nonempty:
            return None
        if self.policy == "fifo":
            return min((self._queues[c][0] for c in nonempty),
                       key=lambda e: e.no)
        if self.policy == "strict":
            return self._queues[nonempty[0]][0]
        best = min(nonempty,
                   key=lambda c: ((self._served[c] + 1)
                                  / self._weights[c], self._rank[c]))
        return self._queues[best][0]

    def bypass_ok_locked(self, entry: _Entry) -> bool:
        """Prefix-affinity exception to head-of-line (rung 24): may
        ``entry`` admit even though it is not the policy head?

        Yes iff it is the FIRST hot parked ticket of the HEAD's class
        (same class — cross-class bypass would reintroduce the priority
        inversion this module removed) and the bypass streak is under
        ``_BYPASS_CAP``. The serving layer additionally requires that
        the head itself does NOT fit — bypass fills capacity the head
        cannot use, it never delays a head that could run."""
        if entry.resume or not entry.hot:
            return False
        if self._bypass_streak >= _BYPASS_CAP:
            return False
        head = self.head_locked()
        if head is None or head is entry or head.pclass != entry.pclass:
            return False
        for e in self._queues[entry.pclass]:
            if e.resume or e is head:
                continue
            if e.hot:
                return e is entry
        return False

    # ---- overload shedding -----------------------------------------------

    def wait_estimate_locked(self, pclass: str) -> float | None:
        """Measured queue wait for ``pclass``, aged for staleness.

        Shed rejections happen BEFORE enqueue, so a shed request never
        admits and never feeds a sample back into the EWMA. Without
        decay, a transient overload that drains would freeze the
        estimate above the watermark and shed the class forever on an
        idle server (and spuriously fail the deadline check for
        requests that would admit instantly). Instead the raw EWMA is
        aged from the class's last admission: unchanged for one
        estimate-width of silence, then halving per estimate-width."""
        est = self._wait_ewma[pclass]
        if not est:
            return est
        age = time.monotonic() - self._last_admit[pclass]
        if age > est:
            est *= 0.5 ** (age / est - 1.0)
        return est

    def shed_depth_locked(self, pclass: str) -> int:
        """Parked tickets the depth watermark weighs against a
        ``pclass`` arrival. Under ``fifo`` every ticket is ahead of
        it; under ``strict``/``weighted`` parked work of strictly
        lower classes cannot hold it back (the policy serves the
        better rank first), so counting it would let a flood of parked
        batch requests shed an interactive arrival the policy would
        admit ahead of all of them — priority inversion in the
        shedding path."""
        if self.policy == "fifo":
            return self.depth_locked()
        r = self.rank(pclass)
        return sum(self.depth_locked(c) for c in self.classes
                   if self._rank[c] <= r)

    def shed_check_locked(self, pclass: str, deadline_ms: int | None,
                          rid: str = "") -> dict | None:
        """Reject-early decision BEFORE enqueue. Returns None (admit to
        the queue) or ``{"reason", "retry_after_s"}`` — the serving
        layer turns the latter into a typed refusal carrying the
        measured hint (satellite 2), so an overloaded server costs a
        client one RTT, not its full timeout."""
        est = self.wait_estimate_locked(pclass)
        depth = self.shed_depth_locked(pclass)
        if self.max_queue_depth and depth >= self.max_queue_depth:
            return self._note_shed(pclass, rid, est,
                                   f"admission queue is full "
                                   f"({depth} tickets ahead of class "
                                   f"{pclass!r} >= watermark "
                                   f"{self.max_queue_depth})")
        # Burn-rate gate (rung 25): while BOTH SLO burn windows run
        # hot, protect the interactive error budget by shedding every
        # lower class up front. The top class never burn-sheds — the
        # alert exists to keep ITS latency inside objective.
        if (self.burn_input is not None and self.rank(pclass) > 0
                and self.burn_input()):
            return self._note_shed(pclass, rid, est,
                                   f"error-budget burn-rate alert is "
                                   f"firing; class {pclass!r} sheds "
                                   f"until the budget recovers")
        # Wait-based sheds only apply while same-class work is parked:
        # with an empty class queue the arrival becomes the class head
        # immediately, and letting it park is the only way the wait
        # estimate ever gets a fresh sample (shed requests never
        # admit) — the second half of the anti-livelock guard.
        if self.depth_locked(pclass) == 0:
            return None
        if self.max_queue_wait_s and est is not None \
                and est > self.max_queue_wait_s:
            return self._note_shed(pclass, rid, est,
                                   f"measured {pclass} queue wait "
                                   f"{est:.2f}s exceeds watermark "
                                   f"{self.max_queue_wait_s:.2f}s")
        if deadline_ms is not None and est is not None \
                and est > deadline_ms / 1000.0:
            return self._note_shed(pclass, rid, est,
                                   f"deadline {deadline_ms}ms is "
                                   f"unmeetable (measured {pclass} "
                                   f"queue wait {est:.2f}s)")
        return None

    def _note_shed(self, pclass: str, rid: str, est, reason: str) -> dict:
        self.shed += 1
        tr = self.tracer
        if tr is not None:
            # Sheds always record (they are rare and diagnostic gold),
            # carrying the rid so a refused request's trace says why.
            tr.event("shed", "sched", rid=rid,
                     args={"class": pclass, "reason": reason})
        return {"reason": reason, "retry_after_s": est}

    # ---- ticket lifecycle ------------------------------------------------

    def enqueue_locked(self, req, pclass: str,
                       pages_needed: int) -> _Entry:
        """Park a submitter: a fresh ticket at the class tail. The
        caller waits on ``entry.cond`` until it is the head AND
        capacity fits (serving.py's admission loop)."""
        self.rank(pclass)  # validates
        e = _Entry(self._next_no, pclass, req, pages_needed,
                   threading.Condition(self._lock), time.monotonic())
        self._next_no += 1
        self._queues[pclass].append(e)  # fresh no == max -> tail
        tr = self.tracer
        if tr is not None and getattr(req, "trace", False):
            tr.event("enqueue", "sched", rid=getattr(req, "rid", ""),
                     args={"class": pclass, "ticket": e.no})
        return e

    def admit_locked(self, entry: _Entry) -> None:
        """The head ticket won capacity: dequeue, record its measured
        queue wait (histogram + EWMA — the shed/hint input), charge the
        weighted policy, and wake whoever is head now. A non-head
        admission is a prefix-affinity bypass (``bypass_ok_locked``):
        counted, and the streak advances so the cap can bite."""
        if self.head_locked() is entry:
            self._bypass_streak = 0
        else:
            self._bypass_streak += 1
            self.hot_bypasses += 1
        self._remove(entry)
        self._served[entry.pclass] += 1
        now = time.monotonic()
        wait = now - entry.enqueued_at
        self._hist_wait[entry.pclass].observe(wait * 1000.0)
        prev = self._wait_ewma[entry.pclass]
        self._wait_ewma[entry.pclass] = (
            wait if prev is None
            else (1 - _EWMA_ALPHA) * prev + _EWMA_ALPHA * wait
        )
        self._last_admit[entry.pclass] = now
        tr = self.tracer
        if tr is not None and getattr(entry.req, "trace", False):
            # The queue span: enqueue -> admit, anchored on the tracer
            # clock (the wait itself was measured on time.monotonic —
            # both clocks are monotonic, only the epoch differs).
            t1 = tr.now()
            tr.span("queue", "sched", t1 - wait, t1,
                    rid=getattr(entry.req, "rid", ""),
                    args={"class": entry.pclass, "ticket": entry.no,
                          "wait_ms": round(wait * 1000.0, 3)})
        self.wake_head_locked()

    def remove_locked(self, entry: _Entry) -> None:
        """Abandon a ticket (timeout, cancel, refusal). Idempotent."""
        self._remove(entry)
        self.wake_head_locked()

    def _remove(self, entry: _Entry) -> None:
        q = self._queues[entry.pclass]
        for i, e in enumerate(q):
            if e is entry:
                del q[i]
                return

    # ---- wakeups ---------------------------------------------------------

    def wake_head_locked(self) -> None:
        """Targeted wakeup: only the policy head's waiter stirs — the
        ticketed replacement for notify_all's thundering herd. Resume
        entries have no thread; the decode loop is woken by the serving
        layer's own ``notify_all`` on the work condition."""
        h = self.head_locked()
        if h is None:
            return
        if not h.resume:
            h.cond.notify_all()
        # Also stir the head class's first hot ticket (rung 24): its
        # park predicate may pass via bypass_ok_locked even while the
        # head's cannot. Bounded: one extra notify, same class only.
        if self._bypass_streak >= _BYPASS_CAP:
            return
        for e in self._queues[h.pclass]:
            if e.resume or e is h:
                continue
            if e.hot:
                e.cond.notify_all()
                return

    def wake_all_locked(self) -> None:
        """Every parked waiter re-evaluates (close/drain/poison/cancel:
        the predicate changed for reasons other than queue order)."""
        for q in self._queues.values():
            for e in q:
                if not e.resume:
                    e.cond.notify_all()

    # ---- preemptive swap bookkeeping ------------------------------------

    def swap_fits_locked(self, nbytes: int) -> bool:
        return (self.swap_budget_bytes > 0
                and self.swap_bytes + nbytes <= self.swap_budget_bytes)

    def record_swapout_locked(self, req, pclass: str, ticket_no: int,
                              pages_needed: int, saved_len: int,
                              arrays: tuple, *,
                              restore: bool = False) -> _Entry:
        """A victim left the device: park its as-stored page bytes and
        re-queue it under its ORIGINAL ticket number, so it resumes
        ahead of later arrivals of its class. ``restore`` marks a
        rung-22 journal re-queue (revive found more checkpoints than
        slots): same parking, but it is not a preemption — the counter
        and its trace event stay honest."""
        nbytes = sum(a.nbytes for a in arrays)
        e = _Entry(ticket_no, pclass, req, pages_needed, None,
                   time.monotonic(), resume=True, saved_len=saved_len,
                   arrays=arrays, nbytes=nbytes)
        bisect.insort(self._queues[pclass], e, key=lambda x: x.no)
        self.swap_bytes += nbytes
        if not restore:
            self.preemptions += 1
        tr = self.tracer
        if tr is not None:
            # Preemptions always record: they reshape every timeline on
            # the pool, not just the victim's.
            tr.event("journal-requeue" if restore else "swap-out",
                     "sched", rid=getattr(req, "rid", ""),
                     args={"class": pclass, "ticket": ticket_no,
                           "saved_len": saved_len, "bytes": nbytes})
        return e

    def pop_resume_locked(self, entry: _Entry) -> None:
        """The decode loop re-admitted a swapped request: drop the host
        snapshot accounting and charge the policy like any admission.
        The swapped-out residency (``enqueued_at`` was reset at
        swap-out) goes to its OWN histogram: it is not an admission
        wait, and the queue-wait histogram must keep measuring the same
        thing the EWMA does."""
        self._remove(entry)
        self.swap_bytes -= entry.nbytes
        entry.arrays = ()
        self._served[entry.pclass] += 1
        self.resumes += 1
        wait = time.monotonic() - entry.enqueued_at
        self._hist_swap[entry.pclass].observe(wait * 1000.0)
        tr = self.tracer
        if tr is not None:
            tr.event("swap-in", "sched",
                     rid=getattr(entry.req, "rid", ""),
                     args={"class": entry.pclass, "ticket": entry.no,
                           "residency_ms": round(wait * 1000.0, 3)})
        self.wake_head_locked()

    def drop_swapped_locked(self, req) -> _Entry | None:
        """Cancel-while-swapped-out (satellite 3): free the host
        snapshot and forget the entry. Returns it (the serving layer
        fails the waiter) or None if ``req`` is not swapped out."""
        for q in self._queues.values():
            for i, e in enumerate(q):
                if e.resume and e.req is req:
                    del q[i]
                    self.swap_bytes -= e.nbytes
                    e.arrays = ()
                    self.wake_head_locked()
                    return e
        return None

    def take_swapped_locked(self) -> list:
        """Remove and return EVERY resume entry (degraded mode / hard
        close: swapped-out requests fail like active ones — rung 14's
        contract extends to the swap set). The snapshots ride along
        INTACT: the caller either journals them (rung 22 — the host
        bytes are already a verbatim checkpoint) or zeroes
        ``entry.arrays`` to free them."""
        out = []
        for c, q in self._queues.items():
            keep = []
            for e in q:
                if e.resume:
                    self.swap_bytes -= e.nbytes
                    out.append(e)
                else:
                    keep.append(e)
            self._queues[c] = keep
        return out

    def reset_locked(self) -> None:
        """Revive/reform: queues and the swap set restart empty (any
        straggler tickets were woken into the refusal path; snapshots
        were failed by take_swapped_locked). Cumulative counters and
        histograms survive — they are observability, not state."""
        for c in self._queues:
            self._queues[c] = []
        self.swap_bytes = 0

    # ---- observability ---------------------------------------------------

    def stats_locked(self) -> dict:
        out = {
            "sched_policy": self.policy,
            "sched_swapped_out": len(self.swapped_locked()),
            "sched_swap_bytes_host": self.swap_bytes,
            "sched_preemptions_total": self.preemptions,
            "sched_resumes_total": self.resumes,
            "sched_shed_total": self.shed,
            "sched_hot_bypass_total": self.hot_bypasses,
        }
        for c in self.classes:
            out[f"sched_queue_depth_{c}"] = self.depth_locked(c)
            out[f"sched_queue_wait_ms_{c}"] = (
                self._hist_wait[c].snapshot()
            )
            out[f"sched_swap_residency_ms_{c}"] = (
                self._hist_swap[c].snapshot()
            )
        return out
