"""Flagship payload: a compact decoder-only transformer LM, TPU-first.

Design notes (why it looks like this, not like a CUDA/torch port):

* **Params are a flat pytree of stacked arrays.** All layers' weights are
  stacked on a leading layer axis and the forward pass is one
  ``lax.scan`` over that axis — XLA compiles ONE layer body regardless of
  depth, and the layer axis is never sharded.
* **bf16 compute, fp32 master params.** Matmuls (the MXU work) run in
  bfloat16; params and optimizer state stay float32.
* **Static shapes everywhere**; the causal mask is a compile-time constant.
* **Sharding is annotation-only** (see parallel/sharding.py): this file
  contains no collectives — XLA inserts them from the in_shardings.
* **Weight tying**: logits = hidden @ embedding.T, halving embedding HBM.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax import lax

from kvedge_tpu.compat import shard_map


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab: int = 32000
    d_model: int = 512
    n_heads: int = 8
    # Grouped-query attention: number of K/V heads. 0 means n_heads (MHA).
    # Fewer KV heads shrink the decode-time KV cache by n_heads/n_kv_heads —
    # the HBM-bandwidth lever for inference serving (models/decode.py).
    n_kv_heads: int = 0
    n_layers: int = 8
    d_ff: int = 2048
    max_seq: int = 1024
    dtype: str = "bfloat16"  # compute dtype
    # Rematerialize each layer in backward instead of saving activations
    # (notably the [T, T] attention scores, which otherwise live for every
    # layer at once under lax.scan) — the standard HBM-for-FLOPs trade.
    remat: bool = True
    # What remat may keep: "full" recomputes everything in backward;
    # "dots" saves matmul outputs (jax.checkpoint_policies
    # .dots_with_no_batch_dims_saveable) and recomputes only the cheap
    # elementwise work — less recompute FLOPs for modest extra HBM.
    remat_policy: str = "full"
    # Mixture-of-experts FFN (models/moe.py): 0 = dense. With n_experts
    # set, every layer's FFN becomes E switch-routed experts whose
    # stacked weights shard over an ``expert`` mesh axis — parameter
    # scale-out without per-token FLOP growth. The serving paths
    # (models/decode.py, models/kvcache.py) route per-token without
    # capacity limits; cached decode agrees with the teacher-forced
    # forward pass exactly when training capacity never binds
    # (expert_capacity_factor * expert_top_k >= n_experts guarantees
    # that; the serving boundary warns otherwise — models/moe.py).
    n_experts: int = 0
    # Per-expert slot headroom: capacity = ceil(k*tokens/E * factor);
    # dispatches routed past capacity are dropped (residual carries them).
    expert_capacity_factor: float = 1.25
    # Experts per token: 1 = Switch (gate = raw router prob), 2 = GShard
    # (gates normalized over the pair; first choices take capacity
    # priority over second choices).
    expert_top_k: int = 1
    # Weight of the router's load-balancing aux loss in the training
    # loss (Switch Transformer uses 1e-2).
    moe_aux_weight: float = 0.01
    # Pipeline parallelism (parallel/pipeline.py): 0 = off. With S > 1
    # the layer-stacked params shard their leading L axis over a
    # ``stage`` mesh axis (L/S whole layers per device) and forward runs
    # a GPipe microbatch schedule with ppermute stage hand-offs.
    # Requires a mesh with a ``stage`` axis; currently dense-FFN +
    # local-attention configs only.
    pipeline_stages: int = 0
    # Microbatches per step under pipelining; 0 = one per stage. More
    # microbatches shrink the pipeline bubble (M / (M + S - 1)).
    pipeline_microbatches: int = 0
    # Pipeline backward schedule: "gpipe" (autodiff through the forward
    # schedule + remat — general, composes with MoE/seq-parallel) or
    # "1f1b" (the fused forward+backward schedule with an O(stages)
    # activation stash — dense models, standard attention;
    # parallel/pipeline1f1b.py). Training-only: inference never
    # differentiates, so decode/serve paths ignore it.
    pipeline_schedule: str = "gpipe"
    # Fused cross-entropy readout (ops/xent.py): the training loss skips
    # materializing [B*T, V] logits entirely — blockwise Pallas matmuls
    # with an online logsumexp and an LSE-recompute backward. Measured on
    # v5e the fp32 logits tensor (4.2 GB at the bench shape) and its
    # cotangent dominated the step's HBM traffic. Inference paths
    # (forward/decode) still materialize logits — they need them.
    # Requires vocab % 128 == 0 and batch*seq % 8 == 0; does not compose
    # with tensor-parallel ('model' > 1) meshes yet — the D contraction
    # would need a psum before the online softmax.
    fused_xent: bool = False
    # "naive" materializes [T, T] scores (XLA-fused); "flash" streams K/V
    # blocks through a Pallas kernel with an online softmax (no [T, T] in
    # forward); "ring" shards the sequence over the mesh's ``seq`` axis
    # with ppermute rotation (parallel/ringattention.py); "ulysses"
    # shards the sequence too, but re-shards heads<->sequence with one
    # all-to-all each way and attends locally (parallel/ulysses.py —
    # needs n_heads % (sp * tp) == 0; a ``model`` axis shards heads
    # first). Both sequence modes require passing a mesh with a ``seq``
    # axis to forward(). Flash requires seq to be a multiple of its
    # block size.
    attention: str = "naive"
    # Paged DECODE attention (models/kvcache.py single-query steps and
    # windows): "gather" materializes the per-sequence pool view
    # (pool[tables] — cost scales with the pool CAP); "kernel" streams
    # K/V pages block-table-indexed through a Pallas kernel with an
    # online softmax — per-step cost scales with each sequence's LIVE
    # length (ops/paged_attention.py; numerically equivalent to the
    # gather within bf16 rounding, not bit-identical). "auto" picks the
    # kernel on TPU at long-context caps (max_seq >= 2048, where the
    # cap-vs-live difference is the bill) and the gather elsewhere.
    # Prefill and the speculative verify pass always use the gather
    # path (multi-query shapes).
    paged_attention: str = "auto"

    @property
    def d_head(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads

    @property
    def param_count(self) -> int:
        """Exact parameter count of the tree init_params builds."""
        d, f, L, v = self.d_model, self.d_ff, self.n_layers, self.vocab
        h, kv, dh = self.n_heads, self.kv_heads, self.d_head
        per_layer = d * (h + 2 * kv) * dh + h * dh * d + 2 * d  # attn + norms
        if self.n_experts:
            per_layer += d * self.n_experts * (1 + 2 * f)  # router + experts
        else:
            per_layer += 2 * d * f  # dense FFN
        return v * d + L * per_layer + d  # embed + layers + final norm

    @property
    def needs_mesh(self) -> bool:
        """True when the concrete mesh is required at trace time: the
        sequence-parallel and pipeline shard_maps, the MoE layer's
        expert-placement ``with_sharding_constraint`` (without which XLA
        may replicate the experts), and the fused cross-entropy kernel
        (its shard_map over the data axis, and the tensor-parallel
        rejection — without the mesh the guard could never fire).
        Callers pass ``mesh`` to :func:`forward`/:func:`make_train_step`
        iff this is set."""
        return (self.attention in ("ring", "ulysses")
                or self.n_experts > 0 or self.pipeline_stages > 1
                or self.fused_xent)

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads or self.n_heads

    def validate(self) -> None:
        if self.d_model % self.n_heads:
            raise ValueError("d_model must be divisible by n_heads")
        if self.n_kv_heads and self.n_heads % self.n_kv_heads:
            raise ValueError("n_heads must be divisible by n_kv_heads")
        if self.attention not in ("naive", "flash", "ring", "ulysses"):
            raise ValueError(
                "attention must be 'naive', 'flash', 'ring', or "
                f"'ulysses', got {self.attention!r}"
            )
        if self.paged_attention not in ("auto", "kernel", "gather"):
            raise ValueError(
                "paged_attention must be 'auto', 'kernel', or "
                f"'gather', got {self.paged_attention!r}"
            )
        if self.n_experts < 0:
            raise ValueError("n_experts must be >= 0 (0 = dense FFN)")
        if self.n_experts and self.expert_capacity_factor <= 0:
            raise ValueError("expert_capacity_factor must be > 0")
        if self.n_experts:
            if self.expert_top_k not in (1, 2):
                raise ValueError("expert_top_k must be 1 or 2")
            if self.expert_top_k > self.n_experts:
                raise ValueError(
                    f"expert_top_k {self.expert_top_k} needs at least "
                    f"that many experts (n_experts={self.n_experts})"
                )
        if self.remat_policy not in ("full", "dots"):
            raise ValueError(
                f"remat_policy must be 'full' or 'dots', got "
                f"{self.remat_policy!r}"
            )
        if self.pipeline_stages < 0:
            raise ValueError("pipeline_stages must be >= 0 (0 = off)")
        if self.pipeline_microbatches < 0:
            raise ValueError(
                "pipeline_microbatches must be >= 0 (0 = one per stage)"
            )
        if (self.pipeline_stages > 1
                and self.n_layers % self.pipeline_stages):
            raise ValueError(
                f"n_layers {self.n_layers} must divide by "
                f"pipeline_stages {self.pipeline_stages}"
            )
        if self.pipeline_schedule not in ("gpipe", "1f1b"):
            raise ValueError(
                "pipeline_schedule must be 'gpipe' or '1f1b', got "
                f"{self.pipeline_schedule!r}"
            )
        if self.pipeline_schedule == "1f1b":
            # Config-time refusals (loud at derive/validate, not at the
            # first train step) — parallel/pipeline1f1b.py's docstring
            # carries the reasons.
            if self.n_experts:
                raise ValueError(
                    "pipeline_schedule='1f1b' does not support MoE "
                    "layers (use 'gpipe')"
                )
            if self.attention in ("ring", "ulysses"):
                raise ValueError(
                    "pipeline_schedule='1f1b' does not compose with "
                    "sequence-parallel attention (use 'gpipe')"
                )
            if self.fused_xent:
                raise ValueError(
                    "pipeline_schedule='1f1b' computes its loss head "
                    "inside the pipeline's manual region, where the "
                    "Pallas fused-xent kernel cannot run (use 'gpipe' "
                    "or disable fused_xent)"
                )


# Named model shapes for the runtime's [model] TOML section. One
# definition shared by the payload pipeline (runtime/workload.py), the
# bench, and the driver entry (__graft_entry__.FLAGSHIP): the shape every
# performance number describes must be the shape the product path trains
# and serves. "probe" is the machinery-verification default (deliberately
# tiny); "flagship" is the 41.6M-param bench model. Only shape fields —
# everything execution-related (attention, remat, pipeline, max_seq)
# stays derived from the mesh and the [payload] knobs.
PRESETS: dict[str, dict] = {
    "probe": dict(vocab=512, d_model=128, n_heads=4, n_kv_heads=0,
                  n_layers=2, d_ff=512),
    "flagship": dict(vocab=32000, d_model=512, n_heads=8, n_kv_heads=0,
                     n_layers=8, d_ff=2048),
}


def init_params(key, cfg: TransformerConfig) -> dict:
    """Initialize the flat, layer-stacked param tree (fp32)."""
    cfg.validate()
    k_embed, k_qkv, k_out, k_up, k_down = jax.random.split(key, 5)
    d, h, kv, dh, f, layers = (
        cfg.d_model, cfg.n_heads, cfg.kv_heads, cfg.d_head, cfg.d_ff,
        cfg.n_layers,
    )

    def normal(k, shape, scale):
        return (jax.random.normal(k, shape, jnp.float32) * scale)

    params = {
        "embedding": normal(k_embed, (cfg.vocab, d), 0.02),
        # Fused projection: [q | k | v] along the output dim; k/v carry
        # cfg.kv_heads heads (== n_heads unless GQA is on).
        "w_qkv": normal(k_qkv, (layers, d, (h + 2 * kv) * dh), d ** -0.5),
        "w_out": normal(k_out, (layers, h * dh, d), (h * dh) ** -0.5),
        "ln_attn": jnp.ones((layers, d), jnp.float32),
        "ln_mlp": jnp.ones((layers, d), jnp.float32),
        "ln_final": jnp.ones((d,), jnp.float32),
    }
    if cfg.n_experts:
        e = cfg.n_experts
        k_router = jax.random.fold_in(k_up, 1)
        params["router"] = normal(k_router, (layers, d, e), d ** -0.5)
        params["w_up_experts"] = normal(k_up, (layers, e, d, f), d ** -0.5)
        params["w_down_experts"] = normal(
            k_down, (layers, e, f, d), f ** -0.5
        )
    else:
        params["w_up"] = normal(k_up, (layers, d, f), d ** -0.5)
        params["w_down"] = normal(k_down, (layers, f, d), f ** -0.5)
    return params


def tied_readout(x, embedding):
    """Weight-tied logits readout: bf16 operands with fp32 accumulation.

    The MXU multiplies in bf16 and accumulates in fp32 natively, so this
    keeps the largest matmul in the model (D x vocab — roughly half its
    FLOPs) at full MXU rate while logits still come out fp32 for a stable
    softmax; a plain fp32 x fp32 matmul here runs at a fraction of the
    bf16 rate. Shared by forward(), contiguous decode, and paged decode:
    the inference probe (runtime/workload.py) asserts those paths agree
    token for token, so they must round identically — one helper makes
    that invariant structural.
    """
    return jnp.dot(
        x, embedding.T.astype(x.dtype), preferred_element_type=jnp.float32
    )


def stacked_layer_params(params: dict, cfg: TransformerConfig) -> tuple:
    """The per-layer param tuple in the order ``_layer`` (and the decode
    paths' layer bodies) unpack it. One definition, switched on
    ``cfg.n_experts``, so training and serving cannot disagree about the
    tuple shape or ordering."""
    if cfg.n_experts:
        return (
            params["w_qkv"], params["w_out"], params["router"],
            params["w_up_experts"], params["w_down_experts"],
            params["ln_attn"], params["ln_mlp"],
        )
    return (
        params["w_qkv"], params["w_out"], params["w_up"], params["w_down"],
        params["ln_attn"], params["ln_mlp"],
    )


def _remat_policy(cfg: TransformerConfig):
    """jax.checkpoint policy for cfg.remat_policy (None = save nothing)."""
    if cfg.remat_policy == "dots":
        return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    return None


def _rmsnorm(x, gain):
    scale = jax.lax.rsqrt(
        jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
        + 1e-6
    )
    return (x * scale.astype(x.dtype)) * gain.astype(x.dtype)


def _rotary(x, positions):
    """Rotary position embedding over the head dim (applied to q and k)."""
    *_, dh = x.shape
    half = dh // 2
    freqs = jnp.exp(
        -jnp.arange(0, half, dtype=jnp.float32) * (jnp.log(10000.0) / half)
    )
    angles = positions[:, None].astype(jnp.float32) * freqs[None, :]  # [T, half]
    cos = jnp.cos(angles).astype(x.dtype)
    sin = jnp.sin(angles).astype(x.dtype)
    x1, x2 = x[..., :half], x[..., half:]
    # broadcast [T, half] over [B, T, H, half]
    cos = cos[None, :, None, :]
    sin = sin[None, :, None, :]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1
    )


def split_qkv(cfg: TransformerConfig, qkv):
    """Split a fused [..., (H+2K)*Dh] projection into q/k/v head tensors."""
    *lead, _ = qkv.shape
    h, kv, dh = cfg.n_heads, cfg.kv_heads, cfg.d_head
    q = qkv[..., : h * dh].reshape(*lead, h, dh)
    k = qkv[..., h * dh : (h + kv) * dh].reshape(*lead, kv, dh)
    v = qkv[..., (h + kv) * dh :].reshape(*lead, kv, dh)
    return q, k, v


def _layer(cfg: TransformerConfig, x, layer_params, mesh=None,
           constrain_moe: bool = True, seq_manual=None):
    """One pre-norm decoder block. x: [B, T, D] in compute dtype.

    Returns ``(x, aux)`` — ``aux`` is the MoE router's load-balancing
    loss for this layer (0.0 for a dense FFN). ``constrain_moe=False``
    drops the MoE activation sharding constraint: inside the pipeline's
    partial-manual shard_map a NamedSharding over the mesh cannot be
    expressed (manual axes are rejected), and expert placement instead
    rides the expert weights' own sharding through the dispatch/combine
    einsums.

    ``seq_manual = (axis_name, sp)`` means this body is ALREADY inside a
    shard_map whose manual axes include the sequence axis (the pp x sp
    composition, parallel/pipeline.py): ``x`` is a local ``T/sp`` chunk,
    rotary positions offset by the device's chunk index, and ring
    attention calls its per-device body directly — the axis collectives
    (ppermute) resolve against the enclosing manual context instead of
    opening a nested shard_map.
    """
    if cfg.n_experts:
        w_qkv, w_out, router, w_up, w_down, ln_attn, ln_mlp = layer_params
    else:
        w_qkv, w_out, w_up, w_down, ln_attn, ln_mlp = layer_params
    batch, seq, d = x.shape
    h, kv, dh = cfg.n_heads, cfg.kv_heads, cfg.d_head
    dtype = x.dtype

    # Attention.
    normed = _rmsnorm(x, ln_attn)
    qkv = normed @ w_qkv.astype(dtype)  # [B, T, (H+2K)*Dh]
    q, k, v = split_qkv(cfg, qkv)
    positions = jnp.arange(seq)
    if seq_manual is not None:
        # seq here is the LOCAL chunk length; chunks are contiguous in
        # sequence order, so global positions offset by the ring index.
        positions = lax.axis_index(seq_manual[0]) * seq + positions
    q = _rotary(q, positions)
    k = _rotary(k, positions)
    if kv != h:
        # GQA at train time: broadcast each KV head over its query group.
        # XLA fuses the broadcast into the batched matmuls — no repeated
        # K/V is materialized in HBM.
        k = jnp.repeat(k, h // kv, axis=2)
        v = jnp.repeat(v, h // kv, axis=2)
    if seq_manual is not None and cfg.attention == "ring":
        from kvedge_tpu.parallel.ringattention import _ring_attention_local

        attended = _ring_attention_local(
            q, k, v, axis_name=seq_manual[0], sp=seq_manual[1]
        )
        attended = attended.reshape(batch, seq, h * dh)
    elif seq_manual is not None and cfg.attention == "ulysses":
        # Same move that converted ring x stage in round 3: the
        # per-device body runs directly inside the enclosing manual
        # region — lax.all_to_all resolves against a manual axis exactly
        # like ppermute does, so the head scatter/gather needs no nested
        # shard_map. A 'model' axis stays automatic out here too: the
        # all_to_all splits each model shard's local heads over the seq
        # axis (n_heads % (sp*tp), enforced by ulysses_attention's
        # non-pipeline twin and derive_model_config).
        from kvedge_tpu.parallel.ulysses import _ulysses_local

        attended = _ulysses_local(q, k, v, axis_name=seq_manual[0])
        attended = attended.reshape(batch, seq, h * dh)
    elif cfg.attention in ("ring", "ulysses"):
        if mesh is None:
            raise ValueError(
                f"attention={cfg.attention!r} needs a mesh with a 'seq' "
                "axis passed to forward()/make_train_step()"
            )
        if cfg.attention == "ring":
            from kvedge_tpu.parallel.ringattention import ring_attention

            attended = ring_attention(q, k, v, mesh)
        else:
            from kvedge_tpu.parallel.ulysses import ulysses_attention

            attended = ulysses_attention(q, k, v, mesh)
        attended = attended.reshape(batch, seq, h * dh)
    elif cfg.attention == "flash":
        from kvedge_tpu.ops.attention import flash_attention, pick_block

        # [B, T, H, dh] -> [B*H, T, dh] (head-major programs for the grid).
        def heads_to_programs(x):
            return x.transpose(0, 2, 1, 3).reshape(batch * h, seq, dh)

        attended = flash_attention(
            heads_to_programs(q), heads_to_programs(k), heads_to_programs(v),
            pick_block(seq),
            jax.default_backend() != "tpu",  # interpret kernels off-TPU
        )
        attended = (
            attended.reshape(batch, h, seq, dh)
            .transpose(0, 2, 1, 3)
            .reshape(batch, seq, h * dh)
        )
    else:
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / (dh ** 0.5)
        causal = jnp.tril(jnp.ones((seq, seq), jnp.bool_))
        scores = jnp.where(causal[None, None], scores, jnp.finfo(dtype).min)
        weights = jax.nn.softmax(
            scores.astype(jnp.float32), axis=-1
        ).astype(dtype)
        attended = jnp.einsum("bhqk,bkhd->bqhd", weights, v)
        attended = attended.reshape(batch, seq, h * dh)
    x = x + attended @ w_out.astype(dtype)

    # MLP — dense, or switch-routed experts (models/moe.py).
    normed = _rmsnorm(x, ln_mlp)
    if cfg.n_experts:
        from kvedge_tpu.models.moe import moe_ffn

        out, aux = moe_ffn(
            normed.reshape(batch * seq, d), router, w_up, w_down,
            capacity_factor=cfg.expert_capacity_factor,
            top_k=cfg.expert_top_k, mesh=mesh if constrain_moe else None,
        )
        x = x + out.reshape(batch, seq, d)
    else:
        up = normed @ w_up.astype(dtype)
        x = x + jax.nn.gelu(up) @ w_down.astype(dtype)
        aux = jnp.zeros((), jnp.float32)
    return x, aux


def forward_hidden(params: dict, tokens, cfg: TransformerConfig,
                   mesh=None):
    """tokens [B, T] int32 -> (hidden [B, T, D] compute-dtype, aux fp32).

    The transformer stack up to and including the final RMSNorm — i.e.
    everything except the readout matmul. Split out so the training loss
    can feed the hidden states straight into the fused cross-entropy
    kernel (ops/xent.py) without logits ever materializing; the inference
    paths apply :func:`tied_readout` on top via :func:`forward_with_aux`.

    ``aux`` is the mean per-layer MoE load-balancing loss (0.0 for dense
    configs). ``mesh`` is only needed for the sequence-parallel attention
    modes (``'ring'``/``'ulysses'``); when given, activations are pinned
    seq-sharded between layers so the LN/MLP work stays sequence-parallel
    too.
    """
    dtype = jnp.dtype(cfg.dtype)
    embedding = params["embedding"]
    x = embedding[tokens].astype(dtype)  # [B, T, D]

    constrain = None
    if cfg.attention in ("ring", "ulysses") and mesh is not None:
        from kvedge_tpu.parallel.ringattention import sequence_sharding

        sharding = sequence_sharding(mesh)

        def constrain(x):
            return lax.with_sharding_constraint(x, sharding)

        x = constrain(x)

    stacked = stacked_layer_params(params, cfg)

    if cfg.pipeline_stages > 1:
        if mesh is None:
            raise ValueError(
                "pipeline_stages > 1 needs a mesh with a 'stage' axis "
                "passed to forward()/make_train_step()"
            )
        from kvedge_tpu.parallel.pipeline import pipeline_layers

        # The ``expert`` axis (like ``model``) stays automatic inside the
        # pipeline's shard_map; constrain_moe=False because an activation
        # NamedSharding cannot be expressed in that partial-manual
        # context — expert placement propagates from the stacked expert
        # weights' own sharding instead. A ``seq`` axis joins the
        # pipeline's manual axes: the layer body runs seq-local and
        # calls its strategy's per-device body directly — the ring's
        # ppermute fold or ulysses' all_to_all scatter both resolve
        # against the enclosing manual axis (pp x sp).
        sp = dict(zip(mesh.axis_names, mesh.devices.shape)).get("seq", 0)
        seq_manual = (("seq", sp)
                      if cfg.attention in ("ring", "ulysses") and sp
                      else None)
        x, aux = pipeline_layers(
            x, stacked,
            lambda carry, lp: _layer(cfg, carry, lp, mesh,
                                     constrain_moe=False,
                                     seq_manual=seq_manual),
            mesh, n_layers=cfg.n_layers,
            seq_axis="seq" if seq_manual else None,
            n_microbatches=cfg.pipeline_microbatches, remat=cfg.remat,
            remat_policy=_remat_policy(cfg),
        )
        return _rmsnorm(x, params["ln_final"]), aux

    def body(carry, layer_params):
        out, aux = _layer(cfg, carry, layer_params, mesh)
        if constrain is not None:
            out = constrain(out)
        return out, aux

    if cfg.remat:
        body = jax.checkpoint(body, policy=_remat_policy(cfg))
    x, aux_per_layer = lax.scan(body, x, stacked)
    return _rmsnorm(x, params["ln_final"]), jnp.mean(aux_per_layer)


def forward_with_aux(params: dict, tokens, cfg: TransformerConfig,
                     mesh=None):
    """tokens [B, T] int32 -> (logits [B, T, V] fp32, aux scalar fp32).

    See :func:`forward_hidden` for the mesh/aux semantics; this applies
    the weight-tied readout on top.
    """
    x, aux = forward_hidden(params, tokens, cfg, mesh)
    return tied_readout(x, params["embedding"]), aux


def forward(params: dict, tokens, cfg: TransformerConfig, mesh=None):
    """tokens [B, T] int32 -> logits [B, T, V] (fp32).

    See :func:`forward_with_aux` for the mesh semantics; this wrapper
    drops the MoE aux loss for callers that only want logits.
    """
    logits, _ = forward_with_aux(params, tokens, cfg, mesh)
    return logits


def _fused_xent_loss(params: dict, inputs, targets,
                     cfg: TransformerConfig, mesh=None):
    """Training CE via the Pallas fused readout kernel (ops/xent.py).

    Hidden states go straight into blockwise logsumexp/target-logit
    kernels — the [B, T, V] logits tensor never exists in either pass.
    Mesh handling (``needs_mesh`` guarantees the mesh reaches here
    whenever fused_xent is on):

    * ``model`` axis > 1 — rejected: the D contraction would need a psum
      before the online softmax.
    * ``data`` axis > 1 — the kernel runs under ``shard_map`` over the
      batch rows (embedding replicated); without it XLA cannot partition
      an opaque custom call and would gather the full batch per device.
    * single-device meshes (and mesh=None from non-training callers) run
      the kernel directly.
    """
    from kvedge_tpu.ops.xent import fused_xent

    interpret = jax.default_backend() != "tpu"  # interpret kernels off-TPU
    hidden, aux = forward_hidden(params, inputs, cfg, mesh)
    b, t, d = hidden.shape
    rows = hidden.reshape(b * t, d)
    flat_targets = targets.reshape(b * t)

    axis_sizes = dict(mesh.shape) if mesh is not None else {}
    if axis_sizes.get("model", 1) > 1:
        raise ValueError(
            "fused_xent does not compose with tensor parallelism "
            "('model' axis > 1): the D contraction would need a psum "
            "before the online softmax; disable fused_xent"
        )
    if axis_sizes.get("data", 1) > 1:
        from jax.sharding import PartitionSpec as P

        # check_vma off: pallas_call out_shapes don't declare mesh-axis
        # variance, which the checker would otherwise require.
        per_row = shard_map(
            lambda x, e, tg: fused_xent(x, e, tg, interpret),
            mesh=mesh,
            in_specs=(P("data", None), P(), P("data")),
            out_specs=P("data"),
            check_vma=False,
        )(rows, params["embedding"], flat_targets)
    else:
        per_row = fused_xent(rows, params["embedding"], flat_targets,
                             interpret)
    return jnp.mean(per_row), aux


def loss_fn(params: dict, batch, cfg: TransformerConfig, mesh=None):
    """Next-token cross-entropy. batch [B, T] int32; targets are shifted."""
    inputs = batch[:, :-1]
    targets = batch[:, 1:]
    if cfg.fused_xent:
        ce, aux = _fused_xent_loss(params, inputs, targets, cfg, mesh)
    else:
        logits, aux = forward_with_aux(params, inputs, cfg, mesh)
        # Fused cross-entropy (XLA level): logsumexp(logits) -
        # logits[target] needs only two [B, T] reductions over the vocab
        # axis, instead of materializing a second [B, T, V] fp32
        # log-probs tensor (which at vocab=32000 would be the largest
        # buffer in the step).
        target_logit = jnp.take_along_axis(
            logits, targets[..., None], axis=-1
        )[..., 0]
        ce = jnp.mean(jax.nn.logsumexp(logits, axis=-1) - target_logit)
    if cfg.n_experts:
        # Router load balancing: without it, top-1 routing collapses onto
        # a few experts and the rest never train.
        ce = ce + cfg.moe_aux_weight * aux
    return ce


def make_train_step(cfg: TransformerConfig, optimizer=None, mesh=None):
    """Build (init_opt_state, train_step). Donates params/opt_state buffers.

    ``mesh`` is required for the sequence-parallel attention modes
    (``'ring'``/``'ulysses'`` — their shard_map needs the concrete mesh);
    otherwise sharding stays annotation-only and the mesh argument is
    unused.
    """
    import optax

    if optimizer is None:
        optimizer = optax.adamw(3e-4, weight_decay=0.01)

    def init_opt_state(params):
        return optimizer.init(params)

    use_1f1b = cfg.pipeline_stages > 1 and cfg.pipeline_schedule == "1f1b"
    if use_1f1b:
        from kvedge_tpu.parallel.pipeline1f1b import (
            pipeline_1f1b_loss_and_grads,
        )

        if mesh is None:
            raise ValueError(
                "pipeline_schedule='1f1b' needs the mesh passed to "
                "make_train_step()"
            )

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def train_step(params, opt_state, batch):
        if use_1f1b:
            # The fused 1F1B schedule builds the backward itself —
            # autodiff cannot produce a 1F1B schedule from a forward
            # scan (parallel/pipeline1f1b.py).
            loss, grads = pipeline_1f1b_loss_and_grads(
                params, batch, cfg, mesh
            )
        else:
            loss, grads = jax.value_and_grad(loss_fn)(
                params, batch, cfg, mesh
            )
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return init_opt_state, train_step
