"""Paged KV cache: fixed-size pages + block tables for ragged serving.

The contiguous cache (models/decode.py) assumes one uniform-length batch.
Serving wants many sequences of different lengths sharing one memory pool —
the paged-attention scheme: K/V live in fixed-size **pages** out of a global
pool, and each sequence owns an ordered **block table** of page indices.
Admitting a sequence allocates pages; finishing one frees them; fragmentation
is bounded by the page size.

TPU-first shape discipline:

* The pool ``[L, P, page, K, Dh]`` and block tables ``[B, max_pages]`` are
  **static**; growth happens by table entries, never by reshaping arrays —
  nothing retraces as sequences come and go.
* The per-step gather (``pool[tables]``) and scatter (one page row per
  sequence) are batched ``take``/``scatter`` ops XLA lowers to dynamic
  gathers — no per-sequence Python.
* Allocation policy (free lists, admission) is host-side Python — it is
  control plane, runs once per request, and must not live inside ``jit``.

Attention math (grouped einsum, fp32 softmax) matches decode.py exactly, so
paged and contiguous decoding agree bit-for-bit on the same prompts.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp

from kvedge_tpu.models.transformer import (
    TransformerConfig,
    _rmsnorm,
    _rotary,
    split_qkv,
    stacked_layer_params,
    tied_readout,
)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class PagedState:
    """Device-side paged cache state (a pytree; host policy lives in
    :class:`PagedKVCache`).

    ``scale_k``/``scale_v`` ([L, P, page, K] fp32) exist only for an
    int8-quantized pool (``kv_dtype="int8"``): each token row of each
    kv head carries one scale — the standard per-token KV quantization
    — and the pools hold ``round(x / scale)`` int8. None (the bf16
    default) keeps every compiled program identical to the
    pre-quantization ones (None is an empty pytree node).
    """

    pool_k: jax.Array   # [L, P, page, K, Dh]
    pool_v: jax.Array   # [L, P, page, K, Dh]
    tables: jax.Array   # [B, max_pages] int32 page ids (0 = also a real page;
                        # entries past a sequence's page count are unused)
    lengths: jax.Array  # [B] int32 valid positions per sequence
    scale_k: "jax.Array | None" = None  # [L, P, page, K] fp32 (int8 only)
    scale_v: "jax.Array | None" = None

    @property
    def page_size(self) -> int:
        return self.pool_k.shape[2]

    @property
    def max_seq(self) -> int:
        return self.tables.shape[1] * self.page_size


_KV_QMAX = 127.0


def _kv_quantize(x):
    """Per-row symmetric int8: x [..., Dh] -> (int8 [..., Dh],
    fp32 scale [...]). amax/127 scaling; the epsilon floor keeps an
    all-zero row (fresh pool) from dividing by zero."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.maximum(amax / _KV_QMAX, 1e-8)
    q = jnp.round(xf / scale[..., None])
    return q.astype(jnp.int8), scale


def _kv_dequantize(q, scale, dtype):
    return (q.astype(jnp.float32) * scale[..., None]).astype(dtype)


class PagedCacheError(RuntimeError):
    pass


_PAGED_KERNEL_AUTO_MIN_SEQ = 2048


def _use_paged_kernel(cfg: TransformerConfig, page_size: int,
                      width: int, max_pages: int | None = None) -> bool:
    """Resolve ``cfg.paged_attention`` at trace time (page_size/width/
    max_pages are static pool-shape facts under jit). "auto" picks the
    Pallas block-table kernel where it wins: TPU, long-context caps
    (max_seq >= 2048), page_size % 128 == 0 (each page's score columns
    land at lane offset j * page in the kernel's phase-2 scratch, which
    Mosaic requires tile-aligned — and the per-page DMA loop is
    latency-bound anyway: at 16-token pages its 4 KB copies lose to
    XLA's bulk gather), kv_heads*d_head % 128 == 0 (TPU DMA lane
    alignment; MHA at one kv head takes the gather), and the two-phase
    kernel's VMEM scratch fitting the budget (over-cap pools route to
    the gather). The kernel is BIT-IDENTICAL to the gather — it stages
    the gather's own rounded score rows and runs the same softmax +
    flat V contraction (pinned exactly in tests/test_paged_attention
    .py) — so "auto" is a pure routing choice, never a numerics one;
    short-context pools keep the gather only because the kernel's DMA
    loop has nothing to win there. Either choice can be forced with
    "kernel"/"gather"; cfg is a static jit argument, so changing the
    choice retraces rather than silently reusing a cached program.
    Multi-process (slice) pools never auto-pick the kernel: it has no
    partitioning rule, so tracing it over a sharded pool would poison
    the first decode step on a real slice — SlicePagedKVCache
    additionally pins its cfg to "gather" so even a forced "kernel"
    cannot reach a sharded trace."""
    if cfg.paged_attention == "kernel":
        return True
    if cfg.paged_attention == "gather":
        return False
    from kvedge_tpu.ops.paged_attention import decode_scratch_fits_vmem

    if max_pages is None:
        max_pages = -(-cfg.max_seq // max(page_size, 1))
    return (jax.default_backend() == "tpu"
            and jax.process_count() == 1
            and cfg.max_seq >= _PAGED_KERNEL_AUTO_MIN_SEQ
            and page_size % 128 == 0
            and width % 128 == 0
            and decode_scratch_fits_vmem(
                max_pages, page_size, width, cfg.n_heads))


class PagedKVCache:
    """Host-side pool manager wrapping a :class:`PagedState`.

    ``slots`` is the max concurrent sequences (the batch dim of every step).
    Unused slots keep ``lengths == 0`` and are masked out of attention.
    """

    def __init__(self, cfg: TransformerConfig, *, slots: int, pages: int,
                 page_size: int = 16, max_pages_per_seq: int | None = None,
                 kv_dtype: str = "", min_bucket: int = 0):
        from kvedge_tpu.models.moe import warn_if_train_serve_divergence

        cfg.validate()
        warn_if_train_serve_divergence(cfg)
        if kv_dtype not in ("", "int8"):
            raise ValueError(
                f"kv_dtype must be '' (the compute dtype) or 'int8', "
                f"got {kv_dtype!r}"
            )
        self.cfg = cfg
        self.slots = slots
        self.num_pages = pages
        self.page_size = page_size
        # Bucketed compile cache (capacity scaling): host bookkeeping is
        # always ``slots``-sized, but the DEVICE batch dim (tables,
        # lengths — the only arrays that carry it; the page pool is
        # slot-count-independent) is ``self.bucket``: a power of two
        # from ``min_bucket`` up, capped at ``slots``. jit keys on array
        # shapes, so every program compiles once per bucket and
        # admissions within a bucket ride the dead-row masks with zero
        # retraces; :meth:`set_bucket` steps the batch dim at quiescent
        # points. ``min_bucket=0`` disables bucketing (bucket pinned to
        # ``slots`` — the pre-bucketing behavior, and REQUIRED for the
        # slice cache, whose broadcast op stream fixes payload shapes
        # at ``slots``).
        if min_bucket < 0:
            raise ValueError(f"min_bucket must be >= 0, got {min_bucket}")
        self.min_bucket = min(min_bucket, slots) if min_bucket else 0
        self.bucket = self.bucket_for(0)
        self.max_pages_per_seq = (
            max_pages_per_seq or -(-cfg.max_seq // page_size)
        )
        # int8 KV (kv_dtype="int8"): pools hold per-row-quantized int8
        # with fp32 scales riding alongside (PagedState docstring) —
        # the HBM bill per cached token drops ~2x (Dh bytes + 4 vs
        # 2*Dh), which doubles servable context/slots on the same pool
        # budget. Quantization is LOSSY (bounded by one int8 step per
        # row amax): decode tokens may diverge from the bf16 pool at
        # near-ties, which is why it is an explicit operator opt-in
        # ([payload] serving_kv_dtype), never a default.
        self.kv_quantized = kv_dtype == "int8"
        if self.kv_quantized and cfg.paged_attention == "kernel":
            from kvedge_tpu.ops.paged_attention import scales_fit_vmem

            if not scales_fit_vmem(pages * page_size * cfg.kv_heads):
                # A forced kernel that cannot run must refuse at
                # construction, not silently degrade to the cap-sized
                # gather at the long-context shapes the force exists
                # for.
                raise ValueError(
                    "paged_attention='kernel' with int8 KV needs both "
                    "scale arrays to fit the kernel's VMEM budget; "
                    f"this pool ({pages} pages x {page_size} x "
                    f"{cfg.kv_heads} kv heads) exceeds it — shrink the "
                    "pool/page geometry or use 'auto'/'gather'"
                )
        dtype = jnp.int8 if self.kv_quantized else jnp.dtype(cfg.dtype)
        shape = (cfg.n_layers, pages, page_size, cfg.kv_heads, cfg.d_head)
        self.state = self._init_state(shape, dtype)
        self._free: list[int] = list(range(pages))[::-1]  # pop() -> lowest last
        self._pages_of: dict[int, list[int]] = {}
        self._host_tables = [
            [0] * self.max_pages_per_seq for _ in range(slots)
        ]
        self._host_lengths = [0] * slots
        # Page reference counts (prefix sharing): a page may be held by
        # several slots' tables at once (read-only shared prompt
        # prefixes) and/or by the serving layer's prefix registry
        # (retain_pages). A page returns to the free list only when its
        # count reaches zero. Pages on the free list carry count 0.
        self._refs = [0] * pages
        # Optional callback (serving layer): registry pins live outside
        # every request's worst-case reservation, so an allocation that
        # finds the free list short asks the owner to reclaim pins
        # before failing. Signature: pressure_relief(needed) -> bool.
        self.pressure_relief = None
        # Device-resident last-token carry for the overlap pipeline:
        # (produced tokens [n, slots], n) of the most recent
        # dispatch_window*. Window N+1's input row is carry[0][n-1] —
        # sliced on device, so dispatching N+1 never forces N's result
        # to the host.
        self._carry = None
        # Device-resident speculative carry for the windowed-spec
        # pipeline: (pending [slots], ctx [slots, S_ctx],
        # ctx_len [slots]) of the most recent dispatch_spec_window.
        # Unlike the greedy carry, the next window needs the whole
        # drafting context, not just the last token row.
        self._spec_carry = None
        # Worst-case tokens per slot advanced by dispatched-but-not-yet
        # -harvested spec windows. While any are in flight, the DEVICE
        # lengths are data-dependent (acceptance counts the host learns
        # only at harvest) and _sync must merge instead of clobber.
        self._spec_unharvested = [0] * slots
        # Memoized host->device uploads for the small per-dispatch
        # operand rows (active mask, per-row caps, stop tokens): in
        # pipeline steady state these repeat verbatim window after
        # window, and re-uploading them cost a device_put per operand
        # per dispatch — pure boundary overhead the rung-16 model
        # charges to R. Keyed by the operand's raw bytes; cleared with
        # the carries (drop_carry) so a revived/reformed pool never
        # reuses arrays from torn-down device state.
        self._dev_memo: dict = {}

    def _dev_const(self, kind: str, arr):
        """Device copy of a small host operand, reused while its bytes
        are unchanged (see ``_dev_memo``). ``arr`` must be a concrete
        ndarray — callers normalize dtype first so equal content hits
        regardless of the caller's input type."""
        key = arr.tobytes()
        hit = self._dev_memo.get(kind)
        if hit is not None and hit[0] == key:
            return hit[1]
        dev = jnp.asarray(arr)
        self._dev_memo[kind] = (key, dev)
        return dev

    def _init_state(self, shape, dtype) -> PagedState:
        """Fresh zeroed device state. The slice-serving subclass
        (runtime/sliceserve.py) overrides this to create GLOBAL arrays
        over a multi-host mesh; everything above is host bookkeeping
        that neither knows nor cares where the pools live."""
        def scale():
            # Two DISTINCT arrays: the jitted steps donate the whole
            # state, and donating one buffer twice is an error.
            return (jnp.zeros(shape[:-1], jnp.float32)
                    if self.kv_quantized else None)

        return PagedState(
            pool_k=jnp.zeros(shape, dtype),
            pool_v=jnp.zeros(shape, dtype),
            tables=jnp.zeros((self.bucket, self.max_pages_per_seq),
                             jnp.int32),
            lengths=jnp.zeros((self.bucket,), jnp.int32),
            scale_k=scale(),
            scale_v=scale(),
        )

    # ---- bucketed device batch dim --------------------------------------

    def bucket_for(self, n: int) -> int:
        """The smallest bucket that holds ``n`` rows: powers of two from
        ``min_bucket`` up, capped at ``slots`` (the top bucket is
        ``slots`` itself even when that is not a power of two). With
        bucketing disabled the only bucket is ``slots``."""
        if not self.min_bucket:
            return self.slots
        b = self.min_bucket
        while b < n and b < self.slots:
            b *= 2
        return min(b, self.slots)

    def quiescent(self) -> bool:
        """No device-resident carry (greedy or spec) and no unharvested
        spec reservation — the state in which :meth:`set_bucket` is
        safe AND free: nothing in flight references the old batch
        shape."""
        return (self._carry is None and self._spec_carry is None
                and not any(self._spec_unharvested))

    def spec_pending(self) -> bool:
        """Any dispatched-but-unharvested spec reservation? The ONE
        hard blocker for :meth:`set_bucket` (device lengths are
        data-dependent until harvest); mere carries are droppable at a
        pipeline boundary, where the next dispatch re-feeds host
        tokens."""
        return any(self._spec_unharvested)

    def rows_in_use(self) -> int:
        """1 + the highest admitted slot (0 when empty): the smallest
        device batch dim that still covers every live row — what the
        serving layer's bucket step-down must not shrink below."""
        return max(self._pages_of, default=-1) + 1

    def set_bucket(self, n: int) -> None:
        """Resize the DEVICE batch dim to bucket ``n`` (a quiescent-point
        operation: no window/spec carry may be in flight — the serving
        loop collapses its pipeline to a boundary first). The page pool
        never moves; only tables/lengths rebuild from the host mirrors,
        so the resize is a host->device upload of two small arrays and
        the next program traces once for the new shape. Any device
        carry is dropped (the pipeline restarts from host tokens, which
        the overlap path already proves bit-identical)."""
        if n == self.bucket:
            return
        if not self.min_bucket:
            raise PagedCacheError(
                "bucketing is disabled on this cache (min_bucket=0); "
                "the device batch dim is pinned to slots"
            )
        if n != self.bucket_for(n) or n < self.min_bucket or n > self.slots:
            raise PagedCacheError(
                f"bucket {n} is not on this cache's ladder "
                f"(powers of two from {self.min_bucket} capped at "
                f"{self.slots})"
            )
        if any(self._spec_unharvested):
            raise PagedCacheError(
                "cannot resize the device batch dim with spec windows "
                "in flight — harvest them first (device lengths are "
                "data-dependent until then)"
            )
        top = max(self._pages_of, default=-1)
        if top >= n:
            raise PagedCacheError(
                f"slot {top} is admitted but bucket {n} holds rows "
                f"0..{n - 1} — release or migrate it first"
            )
        self.drop_carry()
        self.bucket = n
        self._sync()

    # ---- control plane (host) -------------------------------------------

    def free_pages(self) -> int:
        return len(self._free)

    def page_accounting(self) -> dict:
        """Full-pool page census for the conservation audit
        (``serving_debug_pages`` and the chaos soak's invariant 1).
        Every page is either on the free list (ref 0) or referenced by
        some holder — a slot table, a registry pin, or a spec-window
        pre-allocation, all of which live inside slot page lists and
        therefore inside ``live``. Conservation holds iff
        ``free + live == pages_total`` with no duplicate free entries,
        no negative refcounts, and no page both free and referenced.
        Pure host bookkeeping: no device work, safe at any boundary."""
        free_set = set(self._free)
        return {
            "free": len(self._free),
            "live": sum(1 for r in self._refs if r > 0),
            "pages_total": self.num_pages,
            "spec_unharvested": sum(self._spec_unharvested),
            "free_dup": len(self._free) - len(free_set),
            "neg_refs": sum(1 for r in self._refs if r < 0),
            "free_live": sum(
                1 for p in free_set if self._refs[p] > 0
            ),
        }

    def occupancy(self) -> dict:
        """Cheap pool-occupancy gauges for the rung-25 timeline ring:
        unlike :meth:`page_accounting` (a full census for the
        conservation audit) this is O(slots) attribute reads, safe to
        sample at every quiescent boundary. ``hbm_bytes_used`` prices
        live pages at the pool's per-page K+V footprint (scale slabs
        included for int8 pools)."""
        live = self.num_pages - len(self._free)
        page_bytes = 0
        state = self.state
        if state is not None and state.pool_k is not None:
            for arr in (state.pool_k, state.pool_v):
                page_bytes += arr.nbytes // max(1, self.num_pages)
            if state.scale_k is not None:
                for arr in (state.scale_k, state.scale_v):
                    page_bytes += arr.nbytes // max(1, self.num_pages)
        return {
            "pages_total": self.num_pages,
            "pages_live": live,
            "pages_free": len(self._free),
            "slots_admitted": len(self._pages_of),
            "bucket": self.bucket,
            "hbm_bytes_used": live * page_bytes,
        }

    def is_admitted(self, slot: int) -> bool:
        return slot in self._pages_of

    def slot_pages(self, slot: int) -> list[int]:
        """The slot's current page list (a copy — callers registering
        prefix pins must not alias the live allocation list)."""
        return list(self._pages_of[slot])

    def slot_length(self, slot: int) -> int:
        """The slot's committed host-mirror length: positions
        ``[0, slot_length)`` hold valid K/V for tokens 0..length-1 of
        prompt + generated (spec drafts scribble only at or past the
        committed length and are overwritten on acceptance), which is
        what makes finish-time prefix registration exact."""
        if slot not in self._pages_of:
            raise PagedCacheError(f"slot {slot} is not admitted")
        return self._host_lengths[slot]

    def page_refcount(self, page: int) -> int:
        """Current reference count of ``page`` (host bookkeeping only —
        the chaos soak's refcount-aware conservation check reads it)."""
        return self._refs[page]

    def retain_pages(self, pages: list[int]) -> None:
        """Take an extra reference on ``pages`` (the serving layer's
        prefix registry pins cached-prefix pages with this so releasing
        the request that wrote them does not free them)."""
        for page in pages:
            if self._refs[page] < 1:
                raise PagedCacheError(
                    f"page {page} is free — cannot retain K/V that no "
                    "longer exists"
                )
            self._refs[page] += 1

    def release_pages(self, pages: list[int]) -> None:
        """Drop a reference taken with :meth:`retain_pages`."""
        for page in pages:
            self._unref(page)

    def _unref(self, page: int) -> None:
        self._refs[page] -= 1
        if self._refs[page] < 0:
            raise PagedCacheError(f"page {page} over-released")
        if self._refs[page] == 0:
            self._free.append(page)

    def admit(self, slot: int, prompt_len: int,
              shared_pages: tuple[int, ...] = ()) -> None:
        """Reserve pages for a prompt landing in ``slot``.

        ``shared_pages`` (prefix sharing) prepends already-written,
        read-only pages holding the prompt's cached prefix: the slot's
        table starts with them (reference counts bumped — they are
        never written by this slot, because prefill starts at the
        shared token count and decode writes past the prompt), and only
        the remainder allocates from the free list.
        """
        if slot in self._pages_of:
            raise PagedCacheError(f"slot {slot} already admitted")
        if slot >= self.bucket:
            raise PagedCacheError(
                f"slot {slot} is outside the current device bucket "
                f"({self.bucket} rows) — step the bucket up first"
            )
        total = -(-prompt_len // self.page_size) or 1
        needed = total - len(shared_pages)
        if needed < 0:
            raise PagedCacheError(
                f"{len(shared_pages)} shared pages exceed the prompt's "
                f"{total}-page footprint"
            )
        if total > self.max_pages_per_seq:
            raise PagedCacheError(
                f"prompt of {prompt_len} needs {total} pages > "
                f"max_pages_per_seq={self.max_pages_per_seq}"
            )
        if needed > len(self._free) and not (
            self.pressure_relief and self.pressure_relief(needed)
        ):
            raise PagedCacheError(
                f"pool exhausted: need {needed} pages, {len(self._free)} free"
            )
        self.retain_pages(list(shared_pages))
        fresh = []
        for _ in range(needed):
            page = self._free.pop()
            self._refs[page] += 1
            fresh.append(page)
        self._pages_of[slot] = list(shared_pages) + fresh
        row = self._host_tables[slot]
        for i, page in enumerate(self._pages_of[slot]):
            row[i] = page
        self._host_lengths[slot] = prompt_len
        self._sync()

    def grow(self, slot: int) -> bool:
        """Ensure the slot can hold one more token, allocating a page at a
        page boundary. Returns True iff a page was allocated — the caller
        (:meth:`step`) must :meth:`_sync` before the next device step when
        any table changed; stale device tables would scatter the new token
        into another sequence's page."""
        return self.grow_to(slot, 1)

    def grow_to(self, slot: int, n: int) -> bool:
        """Ensure the slot can hold ``n`` more tokens (the device-side
        decode window's scatters land at positions length..length+n-1),
        allocating pages as needed. Early allocation is safe by the
        serving layer's admission discipline: every request's worst-case
        page budget is reserved up front, so pages pulled here were
        already accounted for. Returns True iff any page was allocated
        (caller must :meth:`_sync`)."""
        if slot not in self._pages_of:
            raise PagedCacheError(f"slot {slot} is not admitted")
        length = self._host_lengths[slot]
        pages = self._pages_of[slot]
        grew = False
        while length + n > len(pages) * self.page_size:
            if len(pages) == self.max_pages_per_seq:
                raise PagedCacheError(f"slot {slot} hit max_pages_per_seq")
            if not self._free and not (
                self.pressure_relief and self.pressure_relief(1)
            ):
                raise PagedCacheError("pool exhausted mid-decode")
            page = self._free.pop()
            self._refs[page] += 1
            pages.append(page)
            self._host_tables[slot][len(pages) - 1] = page
            grew = True
        return grew

    def release(self, slot: int) -> None:
        """Finish a sequence: drop its references (pages free at 0)."""
        if slot not in self._pages_of:
            raise PagedCacheError(f"slot {slot} is not admitted")
        for page in self._pages_of.pop(slot):
            self._unref(page)
        self._host_tables[slot] = [0] * self.max_pages_per_seq
        self._host_lengths[slot] = 0
        # A released slot's device length must drop to 0 even while
        # other slots' spec windows are in flight (the merge in _sync
        # keeps only UNHARVESTED slots' device lengths).
        self._spec_unharvested[slot] = 0
        self._sync()

    def _sync(self) -> None:
        import numpy as _np

        b = self.bucket
        lengths = jnp.asarray(self._host_lengths[:b], jnp.int32)
        if any(self._spec_unharvested):
            # Spec windows in flight advance their slots' DEVICE
            # lengths by data-dependent acceptance counts the host
            # learns only at harvest — a sync triggered by an unrelated
            # admit/grow/release must keep those slots' device lengths,
            # not clobber them with the stale host mirror.
            mask = jnp.asarray(
                _np.asarray(self._spec_unharvested[:b]) > 0
            )
            lengths = jnp.where(mask, self.state.lengths, lengths)
        self.state = dataclasses.replace(
            self.state,
            tables=jnp.asarray(self._host_tables[:b], jnp.int32),
            lengths=lengths,
        )

    # ---- data plane (device) --------------------------------------------

    def snapshot_pages(self, ids: list[int]):
        """DEVICE copies of the K/V data in ``ids``: two fresh arrays
        ``[L, n, page, K, Dh]`` (one gather per pool). The split that
        lets the periodic dump hold the serving lock only for the
        gather dispatch: the fresh arrays are immune to the decode
        step's buffer donation, so the (much slower) device->host
        transfer happens OUTSIDE the lock without racing a step that
        would invalidate the pool buffers.

        An int8 pool snapshots AS STORED (int8 values + fp32 scales —
        a 2-or-4 tuple): dequantizing on device would make the
        device->host transfer ~4x the bytes the pool actually holds,
        on exactly the configs int8 exists to relieve.
        :meth:`snapshot_to_host` dequantizes host-side, so the
        persistence FILE format stays kv_dtype-agnostic — a dump taken
        from an int8 server loads into a bf16 one and vice versa
        (write_pages re-quantizes on the way in), at the cost of one
        extra quantization round trip whose error is bounded by one
        int8 step of the row's amax."""
        idx = jnp.asarray(ids, jnp.int32)
        out = [self.state.pool_k[:, idx], self.state.pool_v[:, idx]]
        if self.kv_quantized:
            out += [self.state.scale_k[:, idx],
                    self.state.scale_v[:, idx]]
        return tuple(out)

    @staticmethod
    def snapshot_to_host(snapshot):
        """Host fp32 ``(k, v)`` from a :meth:`snapshot_pages` tuple —
        the transfer (compact, as-stored) then the dequant (host-side,
        cheap numpy)."""
        import numpy as np

        if len(snapshot) == 2:
            k, v = (np.asarray(x, np.float32) for x in snapshot)
            return k, v
        k, v, sk, sv = (np.asarray(x) for x in snapshot)
        return (k.astype(np.float32) * sk[..., None].astype(np.float32),
                v.astype(np.float32) * sv[..., None].astype(np.float32))

    def read_pages(self, ids: list[int]):
        """Host fp32 copies of the K/V data in ``ids``: two arrays
        ``[L, n, page, K, Dh]`` (dequantized for int8 pools). One
        gather + transfer per array — the prefix-persistence dump path
        (models/serving.py)."""
        return self.snapshot_to_host(self.snapshot_pages(ids))

    def write_pages(self, ids: list[int], k_vals, v_vals) -> None:
        """Scatter K/V data ([L, n, page, K, Dh]) into pages ``ids`` —
        ONE batched device update per pool (a per-page loop would copy
        the whole pool once per page). The persistence load path; the
        caller owns allocation/refcounts for these pages. Values arrive
        unquantized (see snapshot_pages); an int8 pool re-quantizes
        them per row here."""
        idx = jnp.asarray(ids, jnp.int32)
        if self.kv_quantized:
            k_q, k_s = _kv_quantize(jnp.asarray(k_vals, jnp.float32))
            v_q, v_s = _kv_quantize(jnp.asarray(v_vals, jnp.float32))
            self.state = dataclasses.replace(
                self.state,
                pool_k=self.state.pool_k.at[:, idx].set(k_q),
                pool_v=self.state.pool_v.at[:, idx].set(v_q),
                scale_k=self.state.scale_k.at[:, idx].set(k_s),
                scale_v=self.state.scale_v.at[:, idx].set(v_s),
            )
            return
        dtype = self.state.pool_k.dtype
        self.state = dataclasses.replace(
            self.state,
            pool_k=self.state.pool_k.at[:, idx].set(
                jnp.asarray(k_vals, dtype)
            ),
            pool_v=self.state.pool_v.at[:, idx].set(
                jnp.asarray(v_vals, dtype)
            ),
        )

    # ---- preemptive swap (scheduler layer, SERVING.md rung 17) ----------

    def _device_swapout(self, ids: list[int]):
        """Device seam: gather pages ``ids`` AS STORED (fresh arrays,
        immune to the decode steps' buffer donation). The slice cache
        overrides this to broadcast an OP_SWAPOUT so followers replay
        the gather in the totally-ordered op stream."""
        return _gather_pages_impl(self.state, jnp.asarray(ids, jnp.int32))

    def swapout_pages(self, ids: list[int]) -> tuple:
        """Host copies of pages ``ids`` EXACTLY as the pool stores them
        (2-tuple ``(k, v)`` for a bf16 pool, 4-tuple with the fp32
        scale slabs for int8) — the preemption snapshot. Unlike
        :meth:`read_pages`/:meth:`write_pages` (the persistence pair,
        which dequantize/re-quantize and accept one int8 step of
        error), a swap round trip must be BIT-identical: a preempted
        request's resumed token stream is pinned equal to the
        never-preempted one, so the pool bytes go to host verbatim and
        come back verbatim via :meth:`swapin_pages`."""
        import numpy as np

        return tuple(np.asarray(x) for x in self._device_swapout(ids))

    def _device_swapin(self, ids: list[int], arrays: tuple) -> None:
        """Device seam: scatter as-stored ``arrays`` into pages ``ids``
        (one batched update per pool). Slice cache broadcasts."""
        self.state = _scatter_pages_impl(
            self.state, jnp.asarray(ids, jnp.int32),
            tuple(jnp.asarray(a) for a in arrays),
        )

    def swapin_pages(self, ids: list[int], arrays: tuple) -> None:
        """Write a :meth:`swapout_pages` snapshot back into pages
        ``ids`` (freshly allocated by the resume path's re-admission —
        the caller owns allocation/refcounts). Verbatim: no dtype
        conversion happens in either direction."""
        if len(arrays) != (4 if self.kv_quantized else 2):
            raise PagedCacheError(
                f"swap snapshot carries {len(arrays)} arrays; this "
                f"pool needs {4 if self.kv_quantized else 2} "
                "(kv_dtype mismatch between swap-out and swap-in?)"
            )
        self._device_swapin(ids, arrays)

    def cow_page(self, slot: int, index: int) -> int | None:
        """Copy-on-write divergence for table position ``index`` of
        ``slot``: when the page there is SHARED (refcount > 1 — a
        cached-prefix page other holders still read), copy its K/V into
        a fresh page on device and repoint only this slot's table at
        the copy, so the slot's upcoming writes (the partial last page
        of a shared prefix fills in during prefill/decode) cannot
        corrupt co-holders. Returns the new page id, or None when the
        slot already owns the page exclusively (no copy, no cost).

        The copy is a single device-side page copy (``_device_cow`` —
        the slice cache overrides it to broadcast an OP_COWP so
        followers replay the same copy in the totally-ordered op
        stream); no bytes cross the host. The source keeps the
        remaining holders' references; the copy starts at refcount 1
        owned by the slot. Allocation may invoke pressure relief —
        safe at the admission call site because the matched registry
        entry's pages are also held by this slot's table, so evicting
        the entry cannot free the source mid-copy."""
        if slot not in self._pages_of:
            raise PagedCacheError(f"slot {slot} is not admitted")
        pages = self._pages_of[slot]
        if not 0 <= index < len(pages):
            raise PagedCacheError(
                f"slot {slot} holds {len(pages)} pages — no index {index}"
            )
        src = pages[index]
        if self._refs[src] <= 1:
            return None
        if not self._free and not (
            self.pressure_relief and self.pressure_relief(1)
        ):
            raise PagedCacheError("pool exhausted: no page for COW copy")
        dst = self._free.pop()
        self._refs[dst] += 1
        self._device_cow(src, dst)
        pages[index] = dst
        self._host_tables[slot][index] = dst
        self._unref(src)
        self._sync()
        return dst

    def _device_cow(self, src: int, dst: int) -> None:
        """Device seam: copy page ``src``'s slabs into ``dst`` (K, V,
        and int8 scale slabs when quantized). Slice cache broadcasts."""
        self.state = _cow_page_impl(
            self.state,
            jnp.asarray(src, jnp.int32), jnp.asarray(dst, jnp.int32),
        )

    def allocate_pinned_page(self) -> int:
        """Take one page off the free list with refcount 1, owned by the
        caller (the persistence loader's registry pins — there is no
        slot whose reservation covers them). Raises when the pool is
        exhausted; the loader checks ``free_pages`` first and never
        invokes pressure relief (loading cache must not evict cache)."""
        if not self._free:
            raise PagedCacheError("pool exhausted: no page to pin")
        page = self._free.pop()
        self._refs[page] += 1
        return page

    def prefill(self, params: dict, slot: int, prompt) -> jax.Array:
        """Feed a 1D prompt into ``slot`` (after :meth:`admit`).

        Prefill is per-sequence (prompts arrive one request at a time in
        serving); the batched hot path is :meth:`step`. Returns the
        last-position logits [V].
        """
        (prompt_len,) = prompt.shape
        if prompt_len != self._host_lengths[slot]:
            raise PagedCacheError(
                f"admit({slot}) reserved {self._host_lengths[slot]} positions, "
                f"prefill got {prompt_len}"
            )
        return self.prefill_chunk(params, slot, prompt, 0)

    def prefill_chunk(self, params: dict, slot: int, tokens,
                      offset: int) -> jax.Array:
        """Feed ``tokens`` into ``slot`` at absolute position ``offset``.

        The chunked-prefill granule (models/serving.py): a long prompt
        lands in fixed-size chunks so (a) XLA compiles one program per
        CHUNK length, not per prompt length — a bounded compile surface
        under arbitrary operator traffic — and (b) the serving loop can
        run batched decode steps for in-flight requests between chunks
        instead of blocking every co-tenant for one admission's whole
        prefill. Causality across chunks is free: earlier chunks'
        K/V are already scattered into the slot's pages, and the gather
        masks on absolute positions. Returns the chunk's last-position
        logits [V] (only the final chunk's matter to the caller).
        """
        (n,) = tokens.shape
        if offset + n > self._host_lengths[slot]:
            raise PagedCacheError(
                f"chunk [{offset}, {offset + n}) exceeds slot {slot}'s "
                f"admitted length {self._host_lengths[slot]}"
            )
        return self._device_prefill(params, tokens, slot, offset)

    def _device_prefill(self, params, tokens, slot: int, offset: int):
        """Device seam: run the prefill kernel and advance state."""
        logits, self.state = _paged_prefill(
            params, self.state, tokens, slot, self.cfg, offset
        )
        return logits

    def _step_slots(self, active) -> list[int]:
        """Admitted slots this step advances. ``active`` (bool [slots])
        restricts to the caller's in-flight set — the serving loop
        passes it so a HALF-PREFILLED co-tenant (admitted, tables live,
        chunks still landing) is neither grown, scattered into, nor
        length-advanced by interleaved decode steps. None = every
        admitted slot (the pre-chunking behavior)."""
        if active is None:
            return list(self._pages_of)
        return [s for s in self._pages_of if active[s]]

    @staticmethod
    def _active_array(state: PagedState, active):
        import numpy as _np

        if active is None:
            return state.lengths > 0
        return jnp.asarray(_np.asarray(active, bool))

    def step(self, params: dict, tokens, active=None) -> jax.Array:
        """One batched decode step over every active slot.

        ``tokens`` is [slots] int32; inactive slots' outputs are garbage
        (masked sequences) and their lengths do not advance. Returns
        logits [slots, V].
        """
        slots = self._step_slots(active)
        grew = False
        for slot in slots:
            grew |= self.grow(slot)
        if grew:
            # Device tables are stale only when a page was allocated; the
            # steady-state token step pays no host->device re-upload.
            self._sync()
        logits = self._device_step(params, tokens, active)
        # The device state already advanced active slots' lengths (the
        # active mask in _paged_decode_step); just mirror on the host —
        # tables only change in admit/grow/release, which sync themselves.
        for slot in slots:
            self._host_lengths[slot] += 1
        return logits

    def _device_step(self, params, tokens, active):
        """Device seam: one batched decode step over current state."""
        logits, self.state = _paged_decode_step(
            params, self.state, tokens, self.cfg,
            self._active_array(self.state, active),
        )
        return logits

    def step_tokens(self, params, tokens, active=None) -> jax.Array:
        """One batched GREEDY decode step with the token pick fused
        into the dispatched program: same growth/length discipline as
        :meth:`step`, but returns next tokens [slots] int32 instead of
        [slots, V] logits — the per-step host read shrinks to one int
        per slot and the argmax stops costing its own dispatch (the
        bulk of the per-step "hostloop" tax the windowed path was
        measured against). Sampled slots need the logits and stay on
        :meth:`step`."""
        slots = self._step_slots(active)
        grew = False
        for slot in slots:
            grew |= self.grow(slot)
        if grew:
            self._sync()
        toks = self._device_step_tokens(params, tokens, active)
        for slot in slots:
            self._host_lengths[slot] += 1
        return toks

    def _device_step_tokens(self, params, tokens, active):
        """Device seam: fused step+argmax (see :meth:`step_tokens`)."""
        toks, self.state = _paged_decode_step_tokens(
            params, self.state, tokens, self.cfg,
            self._active_array(self.state, active),
        )
        return toks

    def step_window(self, params, tokens, n_steps: int, active=None):
        """``n_steps`` greedy decode steps in ONE dispatched program.

        The per-token host round trip is the paged path's tax: page
        tables only change at page boundaries, so between boundaries the
        decode loop is a pure device-side recurrence — scan it. Pages
        for the whole window are allocated up front (legal because the
        serving layer reserves each request's worst-case budget at
        admission), the greedy argmax feeds back inside the scan, and
        the host pays one dispatch + one transfer for ``n_steps`` tokens
        instead of ``n_steps`` of each.

        ``tokens`` is [slots] int32 (each active slot's pending token).
        Returns generated tokens [n_steps, slots]; row ``i`` is the
        token produced by feeding row ``i-1`` (row 0 fed ``tokens``).
        Greedy only — mixed batches with sampled slots use
        :meth:`step_window_sampled`, whose scan carries the sampled
        rows' key schedule on device (base indices are host-known at
        dispatch).
        """
        slots = self._step_slots(active)
        grew = False
        for slot in slots:
            grew |= self.grow_to(slot, n_steps)
        if grew:
            self._sync()
        toks = self._device_window(params, tokens, n_steps, active)
        for slot in slots:
            self._host_lengths[slot] += n_steps
        return toks

    def _device_window(self, params, tokens, n_steps: int, active):
        """Device seam: ``n_steps`` greedy steps in one program."""
        toks, self.state = _paged_decode_window(
            params, self.state, tokens, self.cfg, n_steps,
            self._active_array(self.state, active),
        )
        return toks

    def step_window_sampled(self, params, tokens, n_steps: int, active,
                            key_data, base_steps, temps, top_ps,
                            sampled_mask):
        """``n_steps`` mixed greedy/sampled decode steps in ONE
        dispatched program (see :func:`_paged_decode_window_sampled_impl`
        for the key-schedule argument). Same growth/length discipline
        as :meth:`step_window`; all per-row sampling inputs are host
        arrays ([B]-shaped; ``key_data`` [B, 2] uint32)."""
        slots = self._step_slots(active)
        grew = False
        for slot in slots:
            grew |= self.grow_to(slot, n_steps)
        if grew:
            self._sync()
        toks = self._device_window_sampled(
            params, tokens, n_steps, active, key_data, base_steps,
            temps, top_ps, sampled_mask,
        )
        for slot in slots:
            self._host_lengths[slot] += n_steps
        return toks

    def _device_window_sampled(self, params, tokens, n_steps: int,
                               active, key_data, base_steps, temps,
                               top_ps, sampled_mask):
        """Device seam: mixed window (overridden by the slice cache)."""
        import numpy as _np

        toks, self.state = _paged_decode_window_sampled(
            params, self.state, jnp.asarray(tokens, jnp.int32),
            self.cfg, n_steps, self._active_array(self.state, active),
            jnp.asarray(_np.asarray(key_data, _np.uint32)),
            jnp.asarray(_np.asarray(base_steps, _np.int32)),
            jnp.asarray(_np.asarray(temps, _np.float32)),
            jnp.asarray(_np.asarray(top_ps, _np.float32)),
            jnp.asarray(_np.asarray(sampled_mask, bool)),
        )
        return toks

    # ---- overlapped (double-buffered) windows ---------------------------

    def _window_caps(self, n_steps: int, steps_left) -> "np.ndarray":
        import numpy as _np

        if steps_left is None:
            return _np.full((self.bucket,), n_steps, _np.int32)
        caps = _np.minimum(
            _np.asarray(steps_left, _np.int64), n_steps
        )
        return _np.maximum(caps, 0).astype(_np.int32)

    def dispatch_window(self, params, tokens, n_steps: int, active=None,
                        steps_left=None, stop_tokens=None):
        """Enqueue a greedy decode window WITHOUT forcing its result.

        The pipelined twin of :meth:`step_window`: returns the produced
        tokens as an unforced device value (JAX async dispatch — the
        program is queued, the host keeps running) to be forced later
        with :meth:`harvest_window`. Because the device stream executes
        in order, a second dispatch may be enqueued before the first is
        harvested; ``tokens=None`` feeds the previous dispatch's final
        token row (the device-resident carry), so no host round trip
        separates back-to-back windows.

        ``steps_left`` [slots] int32 is each row's remaining decode
        budget (None = no cap): row b advances ``min(n_steps,
        steps_left[b])`` steps and then freezes on device (see
        :func:`_paged_decode_window_capped_impl`), which is what makes
        a speculatively dispatched window safe. Pages and host lengths
        advance by each row's TRUE advance, never the full window.

        ``stop_tokens`` [slots] int32 (None = no stops) rides the scan
        as per-row stop-token detection; the harvested result carries
        the packed ``[fin, stop_at]`` bookkeeping rows (rung 23).
        """
        import numpy as _np

        slots = self._step_slots(active)
        caps = self._window_caps(n_steps, steps_left)
        if stop_tokens is None:
            stop_tokens = _np.full(self.bucket, -1, _np.int32)
        grew = False
        for slot in slots:
            if caps[slot] > 0:
                grew |= self.grow_to(slot, int(caps[slot]))
        if grew:
            self._sync()
        toks = self._device_window_dispatch(
            params, tokens, n_steps, active, caps, stop_tokens
        )
        for slot in slots:
            self._host_lengths[slot] += int(caps[slot])
        return toks

    def dispatch_window_sampled(self, params, tokens, n_steps: int,
                                active, key_data, base_steps, temps,
                                top_ps, sampled_mask, steps_left=None,
                                stop_tokens=None):
        """Mixed greedy/sampled :meth:`dispatch_window` (same carry,
        cap, growth, and stop-token discipline; sampling inputs as in
        :meth:`step_window_sampled`)."""
        import numpy as _np

        slots = self._step_slots(active)
        caps = self._window_caps(n_steps, steps_left)
        if stop_tokens is None:
            stop_tokens = _np.full(self.bucket, -1, _np.int32)
        grew = False
        for slot in slots:
            if caps[slot] > 0:
                grew |= self.grow_to(slot, int(caps[slot]))
        if grew:
            self._sync()
        toks = self._device_window_sampled_dispatch(
            params, tokens, n_steps, active, key_data, base_steps,
            temps, top_ps, sampled_mask, caps, stop_tokens,
        )
        for slot in slots:
            self._host_lengths[slot] += int(caps[slot])
        return toks

    def harvest_window(self, handle):
        """Force a dispatched window's tokens to the host
        ([n_steps + 2, slots] int32: the produced tokens plus the
        packed ``[fin, stop_at]`` finish-bookkeeping rows). Blocks
        until the device finishes that window — ideally while a later
        window is already queued behind it (the overlap)."""
        import numpy as _np

        return _np.asarray(handle)

    def _carry_tokens(self):
        if self._carry is None:
            raise PagedCacheError(
                "no window in flight to carry tokens from — the first "
                "window of a pipeline must pass explicit tokens"
            )
        toks, n = self._carry
        return toks[n - 1]

    def drop_carry(self) -> None:
        """Forget the device-resident carries (recovery: a revived pool
        restarts its pipelines from host tokens — greedy carry AND the
        windowed-spec drafting context), and forget any unharvested
        spec advance (the slots it covered are being torn down; their
        host lengths are authoritative again)."""
        self._carry = None
        self._spec_carry = None
        self._spec_unharvested = [0] * self.slots
        # The operand memo holds device arrays from the same stream
        # the carries rode — a revived pool must re-upload.
        self._dev_memo.clear()

    def _device_window_dispatch(self, params, tokens, n_steps: int,
                                active, steps_left, stop_tokens):
        """Device seam: enqueue a capped greedy window (no read)."""
        import numpy as _np

        toks_in = (self._carry_tokens() if tokens is None
                   else jnp.asarray(_np.asarray(tokens, _np.int32)))
        # Steady-state pipelining redispatches with identical mask/
        # caps/stops rows — the memo turns three device_puts per
        # window into zero (host-path elimination, rung 26).
        act = (self._active_array(self.state, active)
               if active is None else
               self._dev_const("w_act", _np.asarray(active, bool)))
        toks, self.state = _paged_decode_window_capped(
            params, self.state, toks_in, self.cfg, n_steps,
            act,
            self._dev_const("w_caps",
                            _np.asarray(steps_left, _np.int32)),
            self._dev_const("w_stops",
                            _np.asarray(stop_tokens, _np.int32)),
        )
        self._carry = (toks, n_steps)
        return toks

    def _device_window_sampled_dispatch(self, params, tokens,
                                        n_steps: int, active, key_data,
                                        base_steps, temps, top_ps,
                                        sampled_mask, steps_left,
                                        stop_tokens):
        """Device seam: enqueue a capped mixed window (no read)."""
        import numpy as _np

        toks_in = (self._carry_tokens() if tokens is None
                   else jnp.asarray(_np.asarray(tokens, _np.int32)))
        # key_data/base_steps change every window (positions advance);
        # the mask/sampling-constant/cap rows repeat in steady state
        # and ride the memo like the greedy dispatch's.
        act = (self._active_array(self.state, active)
               if active is None else
               self._dev_const("ws_act", _np.asarray(active, bool)))
        toks, self.state = _paged_decode_window_sampled_capped(
            params, self.state, toks_in, self.cfg, n_steps,
            act,
            jnp.asarray(_np.asarray(key_data, _np.uint32)),
            jnp.asarray(_np.asarray(base_steps, _np.int32)),
            self._dev_const("ws_temps",
                            _np.asarray(temps, _np.float32)),
            self._dev_const("ws_topps",
                            _np.asarray(top_ps, _np.float32)),
            self._dev_const("ws_smask",
                            _np.asarray(sampled_mask, bool)),
            self._dev_const("ws_caps",
                            _np.asarray(steps_left, _np.int32)),
            self._dev_const("ws_stops",
                            _np.asarray(stop_tokens, _np.int32)),
        )
        self._carry = (toks, n_steps)
        return toks

    def step_spec(self, params, tokens, active, spec_mask):
        """One speculative verify pass (see :func:`_spec_verify_core`).

        ``tokens`` [slots, 1+K] int32; ``spec_mask`` [slots] bool marks
        rows whose drafts may accept (greedy rows — sampled rows ride
        with acceptance 0 and their draft scatters dropped). Greedy
        rows grow pages for the worst case (all K drafts accepted) up
        front — legal because the serving layer reserves each
        SPECULATIVE request's slack budget at admission; sampled rows
        grow one position only, exactly like a plain step, so they
        carry no slack reservation. Returns ``(emitted [slots, K+1],
        accepted [slots] np.int64, logits0 [slots, V])``.
        """
        import numpy as _np

        slots = self._step_slots(active)
        spec_np = _np.asarray(spec_mask, bool)
        k_len = tokens.shape[1] - 1
        grew = False
        for slot in slots:
            grew |= self.grow_to(
                slot, (k_len + 1) if spec_np[slot] else 1
            )
        if grew:
            self._sync()
        emitted, accepted, logits0 = self._device_spec(
            params, tokens, active, spec_mask
        )
        accepted_np = _np.asarray(accepted)
        for slot in slots:
            self._host_lengths[slot] += 1 + int(accepted_np[slot])
        return emitted, accepted_np, logits0

    def _device_spec(self, params, tokens, active, spec_mask):
        """Device seam: one batched verify pass over current state."""
        import numpy as _np

        emitted, accepted, logits0, self.state = _paged_spec_verify(
            params, self.state, jnp.asarray(tokens, jnp.int32), self.cfg,
            self._active_array(self.state, active),
            jnp.asarray(_np.asarray(spec_mask, bool)),
        )
        return emitted, accepted, logits0

    # ---- windowed speculative decode (device-resident passes) -----------

    def spec_window_caps(self, n_passes: int, k_len: int,
                         budgets, sampled_mask=None) -> "np.ndarray":
        """Worst-case token advance per slot for ONE dispatched spec
        window: a row runs verify passes while its remaining budget is
        positive, each advancing 1 + accepted <= 1 + K, so the last
        pass may overshoot the budget by up to K (the host truncates
        the stream at harvest, exactly like the legacy per-pass path).
        Pages, host inflight accounting, and ``_spec_unharvested`` all
        reserve THIS bound; the true advance (the sum of the window's
        acceptance counts) is only known at harvest.

        A SAMPLED row (``sampled_mask``) advances exactly one token per
        live pass — acceptance is forced to 0 — so its cap is EXACT,
        not a bound: ``min(budget, n_passes)``. Exactness matters
        beyond page thrift: the serving layer prices ``base_steps`` for
        the next pipelined window off inflight (= this cap), and the
        sampler key schedule is only bit-identical to the per-pass path
        when inflight equals the true advance.
        """
        import numpy as _np

        budgets_np = _np.maximum(
            _np.asarray(budgets, _np.int64), 0
        ).astype(_np.int32)
        caps = _np.minimum(budgets_np + k_len, n_passes * (k_len + 1))
        if sampled_mask is not None:
            caps = _np.where(
                _np.asarray(sampled_mask, bool),
                _np.minimum(budgets_np, n_passes), caps,
            )
        return _np.where(budgets_np > 0, caps, 0).astype(_np.int32)

    def dispatch_spec_window(self, params, tokens, n_passes: int,
                             k_len: int, budgets, active=None,
                             ctx=None, ctx_len=None, sampling=None):
        """Enqueue ``n_passes`` speculative draft+verify passes in ONE
        device program, WITHOUT forcing the result.

        The windowed twin of :meth:`step_spec`: drafting (the n-gram
        proposer over a device-resident context), verification, KV
        commits for accepted drafts, acceptance-capped freezing, and
        the pending-token chain all run inside the scan — the host pays
        one dispatch + one harvest for up to ``n_passes * (1 + K)``
        tokens instead of one round trip per pass. Greedy rows only
        (``budgets[b] > 0`` marks participants); sampled co-tenants
        keep the legacy per-pass path.

        First window of a pipeline: ``tokens`` [slots] int32 is each
        row's pending token and ``ctx``/``ctx_len`` its drafting
        context (prompt + generated + pending; [slots, S_ctx] /
        [slots]). Subsequent windows pass ``tokens=None`` to ride the
        device-resident spec carry — pending, context, and context
        lengths never visit the host between back-to-back windows.

        Returns an UNFORCED handle for :meth:`harvest_spec_window`.
        Page growth and ``_spec_unharvested`` reserve the worst case
        (:meth:`spec_window_caps`); host lengths advance only at
        harvest, by the true acceptance counts.

        ``sampling`` (rung 23) carries a mixed batch's sampled
        co-tenants through the SAME window: a ``(key_data, base_steps,
        temps, top_ps, sampled_mask)`` tuple (the capped mixed
        window's inputs) routes the dispatch through
        :func:`_paged_spec_window_sampled_impl` — sampled rows ride
        verify passes with acceptance 0 and draw their next token on
        device; None keeps the greedy-only program.
        """
        import numpy as _np

        slots = self._step_slots(active)
        sampled_mask = sampling[4] if sampling is not None else None
        caps = self.spec_window_caps(n_passes, k_len, budgets,
                                     sampled_mask)
        budgets_np = _np.maximum(
            _np.asarray(budgets, _np.int64), 0
        ).astype(_np.int32)
        grew = False
        for slot in slots:
            if caps[slot] > 0:
                grew |= self.grow_to(
                    slot, self._spec_unharvested[slot] + int(caps[slot])
                )
        if grew:
            self._sync()
        if tokens is None:
            if self._spec_carry is None:
                raise PagedCacheError(
                    "no spec window in flight to carry from — the "
                    "first spec window of a pipeline must pass "
                    "explicit tokens and drafting context"
                )
        elif ctx is None or ctx_len is None:
            raise PagedCacheError(
                "a spec window dispatched from host tokens needs "
                "its drafting context (ctx, ctx_len)"
            )
        emitted, counts, pend_out = self._device_spec_window(
            params, tokens, n_passes, k_len, active, budgets_np,
            ctx, ctx_len, sampling,
        )
        for slot in slots:
            if caps[slot] > 0:
                self._spec_unharvested[slot] += int(caps[slot])
        return {
            "emitted": emitted,      # [n_passes, slots, K+1], unforced
            "counts": counts,        # [n_passes, slots], unforced
            "pending": pend_out,     # [slots], unforced
            "caps": caps,            # host worst-case reservation
        }

    def _device_spec_window(self, params, tokens, n_passes: int,
                            k_len: int, active, budgets, ctx, ctx_len,
                            sampling=None):
        """Device seam: enqueue a windowed spec program (no read).
        ``tokens=None`` rides the device-resident spec carry; the seam
        owns the carry resolution AND the carry update, so a slice
        override can broadcast the host inputs and keep a per-process
        carry (runtime/sliceserve.py) with the base host bookkeeping
        unchanged. The greedy and mixed programs share one carry triple
        (pending, ctx, ctx_len), so a pipeline may hand a carry between
        them when the batch's sampled population drains."""
        import numpy as _np

        if tokens is None:
            pending, ctx_dev, ctx_len_dev = self._spec_carry
        else:
            pending = jnp.asarray(_np.asarray(tokens, _np.int32))
            ctx_dev = jnp.asarray(_np.asarray(ctx, _np.int32))
            ctx_len_dev = jnp.asarray(_np.asarray(ctx_len, _np.int32))
        if sampling is None:
            (emitted, counts, pend_out, ctx_out, ctx_len_out,
             self.state) = _paged_spec_window(
                params, self.state, pending, self.cfg, n_passes, k_len,
                self._active_array(self.state, active),
                jnp.asarray(_np.asarray(budgets, _np.int32)), ctx_dev,
                ctx_len_dev,
            )
        else:
            key_data, base_steps, temps, top_ps, sampled_mask = sampling
            (emitted, counts, pend_out, ctx_out, ctx_len_out,
             self.state) = _paged_spec_window_sampled(
                params, self.state, pending, self.cfg, n_passes, k_len,
                self._active_array(self.state, active),
                jnp.asarray(_np.asarray(budgets, _np.int32)), ctx_dev,
                ctx_len_dev,
                jnp.asarray(_np.asarray(key_data, _np.uint32)),
                jnp.asarray(_np.asarray(base_steps, _np.int32)),
                jnp.asarray(_np.asarray(temps, _np.float32)),
                jnp.asarray(_np.asarray(top_ps, _np.float32)),
                jnp.asarray(_np.asarray(sampled_mask, bool)),
            )
        self._spec_carry = (pend_out, ctx_out, ctx_len_out)
        return emitted, counts, pend_out

    def _force_spec_window(self, handle):
        """Read a dispatched spec window's results to the host — the
        blocking seam (a slice cache deadline-bounds it and reads its
        local replicated shard)."""
        import numpy as _np

        return (_np.asarray(handle["emitted"]),
                _np.asarray(handle["counts"]),
                _np.asarray(handle["pending"]))

    def harvest_spec_window(self, handle):
        """Force a dispatched spec window to the host and settle the
        bookkeeping its dispatch could only bound: host lengths advance
        by each slot's TRUE acceptance-counted advance (the sum of its
        per-pass counts), and the worst-case ``_spec_unharvested``
        reservation is returned. Returns ``(emitted [n_passes, slots,
        K+1], counts [n_passes, slots], pending [slots])`` as numpy."""
        emitted, counts, pending = self._force_spec_window(handle)
        caps = handle["caps"]
        for slot in range(len(caps)):
            # A slot released (or released and re-admitted) while its
            # window was in flight already had its bookkeeping zeroed —
            # release()/drop_carry() are authoritative; settling here
            # would resurrect a dead reservation.
            if (caps[slot] > 0 and slot in self._pages_of
                    and self._spec_unharvested[slot] >= int(caps[slot])):
                self._host_lengths[slot] += int(counts[:, slot].sum())
                self._spec_unharvested[slot] -= int(caps[slot])
        return emitted, counts, pending


# ---- jitted kernels ------------------------------------------------------

# Retrace telemetry: each impl body notes a trace event when Python
# actually runs it — which under jit happens ONLY at trace time (a jit
# cache hit replays the compiled program without touching the Python
# body). The capacity tests pin "admissions within a bucket cause zero
# recompiles" on the delta of this counter, and it covers the slice
# path too (runtime/sliceserve.py re-jits these same impl functions).
_TRACE_EVENTS: dict = {"total": 0}


def trace_count() -> int:
    """Total paged-program trace events since import (monotonic)."""
    return _TRACE_EVENTS["total"]


def _note_trace(name: str) -> None:
    _TRACE_EVENTS["total"] += 1
    _TRACE_EVENTS[name] = _TRACE_EVENTS.get(name, 0) + 1


def _gather_pages_impl(state: PagedState, idx):
    """Pages ``idx`` of every pool slab, as stored: a 2-or-4 tuple of
    fresh ``[L, n, page, K, Dh]`` / ``[L, n, page, K]`` arrays. Shared
    by the single-host swap-out seam (plain dispatch) and the slice
    cache's jitted replicated gather (runtime/sliceserve.py jits it
    with ``out_shardings`` replicated, so the leader can read the swap
    snapshot host-side while followers hold the same bytes)."""
    out = [state.pool_k[:, idx], state.pool_v[:, idx]]
    if state.scale_k is not None:
        out += [state.scale_k[:, idx], state.scale_v[:, idx]]
    return tuple(out)


def _scatter_pages_impl(state: PagedState, idx, arrays) -> PagedState:
    """Scatter as-stored ``arrays`` (a :func:`_gather_pages_impl`
    tuple) into pages ``idx`` — ONE batched update per slab, no dtype
    conversion (the swap-in path's bit-exactness contract). Shared by
    the single-host seam and the slice cache's jitted donating
    scatter."""
    fields = dict(
        pool_k=state.pool_k.at[:, idx].set(arrays[0]),
        pool_v=state.pool_v.at[:, idx].set(arrays[1]),
    )
    if state.scale_k is not None:
        fields.update(
            scale_k=state.scale_k.at[:, idx].set(arrays[2]),
            scale_v=state.scale_v.at[:, idx].set(arrays[3]),
        )
    return dataclasses.replace(state, **fields)


def _cow_page_impl(state: PagedState, src, dst) -> PagedState:
    """Copy page ``src`` into page ``dst`` across every pool slab — the
    COW divergence primitive. Bytes move device-to-device as stored
    (no dequantization; int8 scale slabs ride along), so a diverged
    copy is bit-identical to its source. ``src``/``dst`` arrive as
    traced int32 scalars: the slice cache jits this impl once and
    every (src, dst) pair replays the same compiled program."""
    fields = dict(
        pool_k=state.pool_k.at[:, dst].set(state.pool_k[:, src]),
        pool_v=state.pool_v.at[:, dst].set(state.pool_v[:, src]),
    )
    if state.scale_k is not None:
        fields.update(
            scale_k=state.scale_k.at[:, dst].set(state.scale_k[:, src]),
            scale_v=state.scale_v.at[:, dst].set(state.scale_v[:, src]),
        )
    return dataclasses.replace(state, **fields)


def _gathered(state: PagedState, layer_slabs, dtype):
    """pool[L] pages -> per-sequence contiguous [B, S_max, K, Dh] views
    (dequantized to ``dtype`` when the pool is int8)."""
    pool_k_l, pool_v_l, scale_k_l, scale_v_l = layer_slabs
    batch, max_pages = state.tables.shape
    page, kv, dh = pool_k_l.shape[1:]
    k = pool_k_l[state.tables]  # [B, max_pages, page, K, Dh]
    v = pool_v_l[state.tables]
    if scale_k_l is not None:
        k = _kv_dequantize(k, scale_k_l[state.tables], dtype)
        v = _kv_dequantize(v, scale_v_l[state.tables], dtype)
    return (
        k.reshape(batch, max_pages * page, kv, dh),
        v.reshape(batch, max_pages * page, kv, dh),
    )


def _scatter_token(pool, scales, tables, lengths, kv_new, active):
    """Write one [B, K, Dh] token row into each sequence's current page.

    pool [P, page, K, Dh]; the target of row b is
    page ``tables[b, lengths[b] // page]``, offset ``lengths[b] % page``.
    Inactive slots (empty table rows would alias page 0) are routed
    out-of-bounds and dropped. ``scales`` non-None = int8 pool: the row
    quantizes per (b, head) and its scale scatters alongside. Returns
    ``(pool, scales)``.
    """
    pages, page = pool.shape[:2]
    page_idx = jnp.take_along_axis(
        tables, (lengths // page)[:, None], axis=1
    )[:, 0]                                   # [B] page ids
    page_idx = jnp.where(active, page_idx, pages)  # OOB => dropped
    offset = lengths % page                    # [B]
    if scales is not None:
        kv_new, row_scale = _kv_quantize(kv_new)
        scales = scales.at[page_idx, offset].set(row_scale, mode="drop")
    return pool.at[page_idx, offset].set(kv_new, mode="drop"), scales


def _paged_attend_layer(cfg: TransformerConfig, state: PagedState, x,
                        layer_params, layer_slabs, q_positions, slot=None,
                        write_mask=None):
    """Shared block body. x: [B, Q, D]; q_positions: [B, Q] absolute
    positions of the new tokens. ``slot`` non-None = single-sequence
    prefill (B == 1 view of that slot). ``write_mask`` [B, Q] bool
    (batched paths only) gates which query offsets persist K/V — the
    speculative verify pass drops sampled rows' draft-position writes so
    those rows need no slack pages; None = every offset writes."""
    if cfg.n_experts:
        w_qkv, w_out, router, w_up, w_down, ln_attn, ln_mlp = layer_params
    else:
        w_qkv, w_out, w_up, w_down, ln_attn, ln_mlp = layer_params
    batch, q_len, _ = x.shape
    h, kv, dh = cfg.n_heads, cfg.kv_heads, cfg.d_head
    group = h // kv
    dtype = x.dtype
    pool_k_l, pool_v_l, scale_k_l, scale_v_l = layer_slabs
    quantized = scale_k_l is not None

    normed = _rmsnorm(x, ln_attn)
    q, k, v = split_qkv(cfg, normed @ w_qkv.astype(dtype))
    # rotary wants [T]-shaped positions; rows share a position vector only
    # in prefill (B=1). Decode/verify rows each carry their own
    # positions: apply per-row via vmap (q_len 1 for plain decode,
    # 1 + draft_len for a speculative verify pass).
    if slot is None:
        rot = jax.vmap(lambda t, p: _rotary(t[None], p)[0])
        q = rot(q, q_positions)
        k = rot(k, q_positions)
    else:
        q = _rotary(q, q_positions[0])
        k = _rotary(k, q_positions[0])

    if slot is None:
        tables, lengths = state.tables, state.lengths
        active = lengths > 0
        # One scatter per query offset (static q_len): row b's token i
        # lands at position lengths[b] + i — multi-offset writes are how
        # a verify pass persists the drafts' K/V in the same program
        # that scores them (intra-pass causality is free: writes land
        # before the gather, and the mask is on absolute positions).
        new_pool_k, new_pool_v = pool_k_l, pool_v_l
        new_scale_k, new_scale_v = scale_k_l, scale_v_l
        for i in range(q_len):
            w_active = (active if write_mask is None
                        else active & write_mask[:, i])
            new_pool_k, new_scale_k = _scatter_token(
                new_pool_k, new_scale_k, tables, lengths + i, k[:, i],
                w_active,
            )
            new_pool_v, new_scale_v = _scatter_token(
                new_pool_v, new_scale_v, tables, lengths + i, v[:, i],
                w_active,
            )
    else:
        # Prefill: scatter q_len rows of one slot at their ABSOLUTE
        # positions (chunked prefill passes an offset, so a chunk's
        # positions are offset..offset+q_len-1; the first/whole-prompt
        # chunk starts at zero).
        tables = state.tables[slot][None]
        page = pool_k_l.shape[1]
        positions = q_positions[0]
        page_idx = tables[0][positions // page]
        offset = positions % page
        k_rows, v_rows = k[0], v[0]
        new_scale_k, new_scale_v = scale_k_l, scale_v_l
        if quantized:
            k_rows, sk = _kv_quantize(k_rows)
            v_rows, sv = _kv_quantize(v_rows)
            new_scale_k = scale_k_l.at[page_idx, offset].set(sk)
            new_scale_v = scale_v_l.at[page_idx, offset].set(sv)
        new_pool_k = pool_k_l.at[page_idx, offset].set(k_rows)
        new_pool_v = pool_v_l.at[page_idx, offset].set(v_rows)

    # int8 pools use the kernel too (pages stream AS STORED — half the
    # DMA bytes — with scales folded in post-dot), as long as both
    # whole scale arrays fit the kernel's VMEM budget. "auto" routes
    # oversized pools to the gather; a FORCED kernel that cannot run
    # refuses loudly (PagedKVCache.__init__ rejects it up front; this
    # trace-time raise is the defense for direct kernel callers). Only
    # traces the kernel could actually take refuse: prefill and spec-
    # verify (slot set / q_len > 1) always run the gather, so raising
    # there would kill legitimate programs a forced-kernel pool still
    # needs.
    kernel_eligible = slot is None and q_len == 1
    if quantized:
        from kvedge_tpu.ops.paged_attention import scales_fit_vmem

        scales_fit = scales_fit_vmem(new_scale_k.size)
        if (kernel_eligible and cfg.paged_attention == "kernel"
                and not scales_fit):
            raise ValueError(
                "paged_attention='kernel' forced but the int8 scale "
                f"arrays ({new_scale_k.size} fp32 elements x2) exceed "
                "the kernel's VMEM budget — shrink the pool/page "
                "geometry or use 'auto'/'gather'"
            )
    else:
        scales_fit = True
    if (kernel_eligible and scales_fit
            and _use_paged_kernel(cfg, pool_k_l.shape[1], kv * dh,
                                  max_pages=tables.shape[1])):
        # Single-query decode (steps and windows): attention directly
        # over the block table — K/V pages stream up to each row's LIVE
        # length through the Pallas kernel; the padded pool view is
        # never materialized (ops/paged_attention.py).
        from kvedge_tpu.ops.paged_attention import paged_decode_attention

        att = paged_decode_attention(
            q[:, 0], new_pool_k, new_pool_v, tables, q_positions[:, 0],
            scale_k=new_scale_k, scale_v=new_scale_v,
            interpret=jax.default_backend() != "tpu",
        )  # [B, H, Dh], kv-major head layout — same as the einsum's
        x = x + att.reshape(batch, 1, h * dh) @ w_out.astype(dtype)
    else:
        gk, gv = _gathered(
            dataclasses.replace(state, tables=tables),
            (new_pool_k, new_pool_v, new_scale_k, new_scale_v),
            dtype,
        )
        qg = q.reshape(batch, q_len, kv, group, dh)
        scores = jnp.einsum("bqkgd,bskd->bkgqs", qg, gk) / (dh ** 0.5)
        key_pos = jnp.arange(gk.shape[1])
        allowed = (key_pos[None, None, :]
                   <= q_positions[:, :, None])  # [B, Q, S]
        scores = jnp.where(
            allowed[:, None, None], scores, jnp.finfo(dtype).min
        )
        weights = jax.nn.softmax(
            scores.astype(jnp.float32), axis=-1
        ).astype(dtype)
        attended = jnp.einsum("bkgqs,bskd->bqkgd", weights, gv)
        x = x + attended.reshape(batch, q_len, h * dh) @ w_out.astype(dtype)

    normed = _rmsnorm(x, ln_mlp)
    if cfg.n_experts:
        from kvedge_tpu.models.moe import routed_ffn_block

        x = x + routed_ffn_block(
            normed, router, w_up, w_down, top_k=cfg.expert_top_k
        )
    else:
        x = x + jax.nn.gelu(normed @ w_up.astype(dtype)) @ w_down.astype(dtype)
    return x, (new_pool_k, new_pool_v, new_scale_k, new_scale_v)


def _run_paged(cfg, params, state, x, q_positions, slot=None,
               all_positions: bool = False, write_mask=None):
    def body(carry, xs):
        layer_params, slabs = xs
        out, slabs = _paged_attend_layer(
            cfg, state, carry, layer_params, slabs,
            q_positions, slot, write_mask,
        )
        return out, slabs

    x, new_slabs = jax.lax.scan(
        body, x,
        (stacked_layer_params(params, cfg),
         (state.pool_k, state.pool_v, state.scale_k, state.scale_v)),
    )
    x = _rmsnorm(x, params["ln_final"])
    logits = tied_readout(
        x if all_positions else x[:, -1], params["embedding"]
    )
    return logits, new_slabs


def _with_slabs(state: PagedState, slabs, **extra) -> PagedState:
    """A state whose pools/scales are replaced by ``slabs`` (the
    4-tuple every paged kernel returns), plus any other field."""
    new_k, new_v, new_sk, new_sv = slabs
    return dataclasses.replace(
        state, pool_k=new_k, pool_v=new_v, scale_k=new_sk,
        scale_v=new_sv, **extra,
    )


def _paged_prefill_impl(params: dict, state: PagedState, prompt, slot,
                        cfg: TransformerConfig, offset=0):
    # ``slot`` and ``offset`` are traced (they are only ever indices),
    # so XLA compiles one program per CHUNK length, not one per
    # (slot, offset, length) triple.
    _note_trace("prefill")
    dtype = jnp.dtype(cfg.dtype)
    x = params["embedding"][prompt][None].astype(dtype)  # [1, T, D]
    q_positions = (offset + jnp.arange(prompt.shape[0]))[None]
    logits, slabs = _run_paged(
        cfg, params, state, x, q_positions, slot
    )
    return logits[0], _with_slabs(state, slabs)


_paged_prefill = functools.partial(
    jax.jit, static_argnames=("cfg",), donate_argnums=(1,)
)(_paged_prefill_impl)


def _decode_step_core(params: dict, state: PagedState, tokens,
                      cfg: TransformerConfig, active):
    """One batched decode step (traceable body shared by the jitted
    single step and the windowed scan — the two must stay the same
    program so windowed and per-step decode agree token for token).
    ``active`` [B] bool gates the scatter and the length advance —
    lengths>0 is NOT sufficient once chunked prefill exists (a
    half-prefilled slot is admitted with its final length but must not
    be touched by decode)."""
    _note_trace("decode_step")
    dtype = jnp.dtype(cfg.dtype)
    x = params["embedding"][tokens][:, None].astype(dtype)  # [B, 1, D]
    q_positions = state.lengths[:, None]  # [B, 1]
    masked = dataclasses.replace(
        state, lengths=jnp.where(active, state.lengths, 0)
    )
    logits, slabs = _run_paged(cfg, params, masked, x, q_positions)
    return logits, _with_slabs(
        state, slabs,
        lengths=state.lengths + active.astype(jnp.int32),
    )


_paged_decode_step = functools.partial(
    jax.jit, static_argnames=("cfg",), donate_argnums=(1,)
)(_decode_step_core)


def _decode_step_tokens_core(params: dict, state: PagedState, tokens,
                             cfg: TransformerConfig, active):
    """Fused greedy pick: :func:`_decode_step_core` plus the argmax in
    ONE compiled program, so a per-step loop pays one dispatch and a
    [B]-int read instead of a dispatch, a second argmax dispatch, and
    a [B, V] logits transfer. The argmax is the same jnp op the host
    path ran on the same logits — token-identical by construction
    (and pinned transitively by the window-vs-step exactness tests,
    whose scan feeds back this very pick)."""
    logits, state = _decode_step_core(params, state, tokens, cfg,
                                      active)
    return jnp.argmax(logits, axis=-1).astype(jnp.int32), state


_paged_decode_step_tokens = functools.partial(
    jax.jit, static_argnames=("cfg",), donate_argnums=(1,)
)(_decode_step_tokens_core)


def _spec_verify_core(params: dict, state: PagedState, tokens,
                      cfg: TransformerConfig, active, spec_mask):
    """One batched speculative verify pass over the paged cache.

    ``tokens`` is [B, 1+K]: each active row's pending token followed by
    K drafted tokens. One forward with 1+K query positions per row
    scores every draft (y[b, i] = the model's greedy token after row
    b's prefix extended by tokens[b, :i+1]) and writes all 1+K tokens'
    K/V; acceptance is the leading-agreement count, exactly the
    contiguous speculative decoder's rule (models/speculative.py), so
    emitted tokens are token-for-token the plain greedy decode.

    ``spec_mask`` [B] bool: rows whose drafts may accept. A sampled row
    rides the same pass with acceptance forced to 0 — it advances by
    exactly its pending token (position ``length``), and its draft
    offsets' K/V scatters are DROPPED (``write_mask``): a row that can
    never accept a draft must not consume pages past its real length,
    so sampled requests reserve no speculative slack
    (models/serving.py ``_pages_needed``). Its draft-position *scores*
    read whatever stale data sits past ``length`` in the pool — finite
    garbage whose outputs (y[:, 1:]) are discarded for that row, since
    acceptance is 0 and only the pending position's logits are used.

    Returns ``(emitted [B, K+1], accepted [B], logits0 [B, V], state)``:
    row b's first ``accepted[b]`` emitted entries are its accepted
    drafts, entry ``accepted[b]`` is the bonus token (the model's own
    argmax after them); ``logits0`` is the pending-token position's
    logits for host-side sampling. Lengths advance by
    ``1 + accepted`` per active row — the pending token's K/V plus the
    accepted drafts'; the bonus token's K/V is the next pass's pending
    write, exactly like plain decode.
    """
    _note_trace("spec_verify")
    dtype = jnp.dtype(cfg.dtype)
    k_len = tokens.shape[1] - 1
    x = params["embedding"][tokens].astype(dtype)  # [B, 1+K, D]
    q_positions = (state.lengths[:, None]
                   + jnp.arange(1 + k_len)[None])  # [B, 1+K]
    masked = dataclasses.replace(
        state, lengths=jnp.where(active, state.lengths, 0)
    )
    # Offset 0 (the pending token) always writes; draft offsets write
    # only for rows that can accept them.
    write_mask = (spec_mask[:, None]
                  | (jnp.arange(1 + k_len) == 0)[None, :])
    logits, slabs = _run_paged(
        cfg, params, masked, x, q_positions, all_positions=True,
        write_mask=write_mask,
    )  # [B, 1+K, V]
    y = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, 1+K]
    draft = tokens[:, 1:]
    agree = jnp.cumprod(
        (draft == y[:, :k_len]).astype(jnp.int32), axis=1
    )
    accepted = jnp.sum(agree, axis=1) * spec_mask.astype(jnp.int32)
    idx = jnp.arange(k_len + 1)[None]
    emitted = jnp.where(
        idx < accepted[:, None],
        jnp.concatenate([draft, y[:, -1:]], axis=1),
        jnp.take_along_axis(y, accepted[:, None], axis=1),
    ).astype(jnp.int32)
    state = _with_slabs(
        state, slabs,
        lengths=state.lengths + active.astype(jnp.int32) * (1 + accepted),
    )
    return emitted, accepted, logits[:, 0], state


_paged_spec_verify = functools.partial(
    jax.jit, static_argnames=("cfg",), donate_argnums=(1,)
)(_spec_verify_core)


def _paged_spec_window_impl(params: dict, state: PagedState, tokens,
                            cfg: TransformerConfig, n_passes: int,
                            k_len: int, active, budgets, ctx, ctx_len):
    """``n_passes`` speculative draft+verify passes in ONE program —
    the windowed twin of :func:`_spec_verify_core`, with the host
    removed from the loop entirely.

    The legacy path pays a full host round trip per verify pass: read
    back the emitted tokens, re-draft on the host, re-dispatch. Here
    the scan carries everything that loop needed the host for:

    * ``pending`` [B] — the pending-token chain (each pass's bonus
      token feeds the next pass, exactly the legacy
      ``req.next_token`` hand-off);
    * ``ctx`` [B, S_ctx] / ``ctx_len`` [B] — the drafting context
      (prompt + generated + pending). Each pass drafts K tokens with
      the SAME n-gram proposer the host drafter mirrors
      (models/speculative.py ``_propose_ngram``), appends its accepted
      tokens + bonus, and drafts the next pass from the updated
      context — so the windowed drafts equal the legacy host drafts
      token for token, and (since greedy verify makes the emitted
      stream independent of draft quality anyway) the emitted stream
      is bit-identical to both the legacy spec path and plain greedy;
    * ``rem`` [B] — each row's remaining emission budget. A pass runs
      a row only while ``rem > 0``; a frozen row's scatters drop, its
      length holds, and its pending/context freeze (the same
      discipline as :func:`_paged_decode_window_capped_impl`), so a
      speculatively dispatched window can never scribble past a stop
      the host hasn't seen. The LAST live pass may overshoot the
      budget by up to K accepted drafts — the host truncates at
      harvest, exactly like the legacy per-pass path's ``room`` cap.

    Each pass verifies through :func:`_spec_verify_core` (the single
    jitted-pass body — windowed and per-pass spec stay the same
    program, the invariant the windowed/per-step greedy pair already
    keeps). Returns ``(emitted [n_passes, B, K+1], counts
    [n_passes, B], pending [B], ctx, ctx_len, state)`` where
    ``counts[p, b] = 1 + accepted`` for rows pass p advanced (0 for
    frozen rows): row b's pass-p emissions are its pending token
    followed by ``emitted[p, b, :counts[p, b] - 1]``, and
    ``emitted[p, b, counts[p, b] - 1]`` is the next pending.
    """
    from kvedge_tpu.models.speculative import _propose_ngram

    _note_trace("spec_window")
    s_ctx = ctx.shape[1]

    def body(carry, _):
        state, pending, rem, ctx, ctx_len = carry
        live = active & (rem > 0)
        draft = jax.vmap(
            lambda c, n: _propose_ngram(c, n, k_len)
        )(ctx, ctx_len)
        toks = jnp.concatenate([pending[:, None], draft], axis=1)
        emitted, accepted, _logits0, state = _spec_verify_core(
            params, state, toks, cfg, live, live
        )
        count = live.astype(jnp.int32) * (1 + accepted)
        bonus = jnp.take_along_axis(
            emitted, accepted[:, None], axis=1
        )[:, 0]
        pending = jnp.where(live, bonus, pending)
        # Append this pass's a+1 new tokens (accepted drafts + bonus)
        # to the drafting context; frozen rows' writes drop out of
        # bounds. emitted[b, i] for i > accepted[b] repeats the bonus,
        # so masking by offset <= accepted writes exactly the stream.
        idx = jnp.arange(k_len + 1)[None, :]
        pos = ctx_len[:, None] + idx
        ok = live[:, None] & (idx <= accepted[:, None])
        pos = jnp.where(ok, pos, s_ctx)
        ctx = jax.vmap(
            lambda c, p, e: c.at[p].set(e, mode="drop")
        )(ctx, pos, emitted)
        ctx_len = ctx_len + count
        rem = rem - count
        return (state, pending, rem, ctx, ctx_len), (emitted, count)

    carry0 = (state, tokens, budgets, ctx, ctx_len)
    (state, pending, _rem, ctx, ctx_len), (emitted, counts) = (
        jax.lax.scan(body, carry0, length=n_passes)
    )
    return emitted, counts, pending, ctx, ctx_len, state


_paged_spec_window = functools.partial(
    jax.jit, static_argnames=("cfg", "n_passes", "k_len"),
    donate_argnums=(1,),
)(_paged_spec_window_impl)


def _paged_spec_window_sampled_impl(params: dict, state: PagedState,
                                    tokens, cfg: TransformerConfig,
                                    n_passes: int, k_len: int, active,
                                    budgets, ctx, ctx_len, key_data,
                                    base_steps, temps, top_ps,
                                    sampled_mask):
    """Mixed greedy/sampled :func:`_paged_spec_window_impl` — the
    device-resident endgame for the sampled co-tenant (SERVING.md
    rung 23): one sampled row no longer collapses the whole batch to
    the legacy per-pass path.

    Speculative sampling degenerates for this repo's greedy-verify
    scheme: a sampled row's acceptance is forced to 0 (it rejects at
    the first draft position), so "residual resampling on first
    rejection" reduces to drawing the replacement token from the
    nucleus-filtered target distribution at the PENDING position —
    exactly what the legacy per-pass path does with
    ``_sample_slots(logits0, ...)`` on the host. Here that draw moves
    into the scan carry: ``spec_live = live & ~sampled_mask`` rides
    :func:`_spec_verify_core` as the spec mask (acceptance 0, draft
    K/V scatters dropped, length +1 per pass — the documented
    sampled-row contract of the verify core), and the pending chain
    for sampled rows feeds ``sample_token(logits0, fold_in(seed,
    base + i), temp, top_p)`` instead of the bonus argmax.

    The key schedule is bit-identical to the legacy path because a
    live sampled row advances by EXACTLY one token per pass (counts
    1 + accepted = 1), liveness is a prefix of the window (``rem``
    only decreases), and the serving layer dispatches ``base_steps =
    len(generated) + inflight + 1`` — so scan index ``i`` IS the
    row's emitted offset, the same ``fold_in(seed, len(generated)+1)``
    the per-pass path folds. ``emitted[p, b, 0]`` is patched to the
    sampled draw so the harvest path reads sampled and greedy rows
    through one code path (row b's pass-p count is 1: pending emits,
    the sampled token is the next pending).
    """
    from kvedge_tpu.models.decode import sample_token
    from kvedge_tpu.models.speculative import _propose_ngram

    _note_trace("spec_window_sampled")
    s_ctx = ctx.shape[1]
    keys = jax.random.wrap_key_data(key_data)

    def body(carry, i):
        state, pending, rem, ctx, ctx_len = carry
        live = active & (rem > 0)
        spec_live = live & ~sampled_mask
        draft = jax.vmap(
            lambda c, n: _propose_ngram(c, n, k_len)
        )(ctx, ctx_len)
        toks = jnp.concatenate([pending[:, None], draft], axis=1)
        emitted, accepted, logits0, state = _spec_verify_core(
            params, state, toks, cfg, live, spec_live
        )
        step_keys = jax.vmap(jax.random.fold_in)(keys, base_steps + i)
        sampled = sample_token(
            logits0, step_keys, temps[:, None], top_ps[:, None]
        )
        count = live.astype(jnp.int32) * (1 + accepted)
        bonus = jnp.take_along_axis(
            emitted, accepted[:, None], axis=1
        )[:, 0]
        bonus = jnp.where(sampled_mask, sampled, bonus).astype(jnp.int32)
        emitted = jnp.where(sampled_mask[:, None], bonus[:, None],
                            emitted)
        pending = jnp.where(live, bonus, pending)
        idx = jnp.arange(k_len + 1)[None, :]
        pos = ctx_len[:, None] + idx
        ok = live[:, None] & (idx <= accepted[:, None])
        pos = jnp.where(ok, pos, s_ctx)
        ctx = jax.vmap(
            lambda c, p, e: c.at[p].set(e, mode="drop")
        )(ctx, pos, emitted)
        ctx_len = ctx_len + count
        rem = rem - count
        return (state, pending, rem, ctx, ctx_len), (emitted, count)

    carry0 = (state, tokens, budgets, ctx, ctx_len)
    (state, pending, _rem, ctx, ctx_len), (emitted, counts) = (
        jax.lax.scan(body, carry0, jnp.arange(n_passes))
    )
    return emitted, counts, pending, ctx, ctx_len, state


_paged_spec_window_sampled = functools.partial(
    jax.jit, static_argnames=("cfg", "n_passes", "k_len"),
    donate_argnums=(1,),
)(_paged_spec_window_sampled_impl)


def _paged_decode_window_impl(params: dict, state: PagedState, tokens,
                              cfg: TransformerConfig, n_steps: int,
                              active):
    """``n_steps`` decode steps with greedy feedback, one program.

    The scan carries (state, pending token); each step feeds the pending
    token and emits its greedy successor. Inactive slots produce garbage
    tokens that are never read (their scatters drop, their lengths hold).
    """
    _note_trace("window")

    def body(carry, _):
        state, toks = carry
        logits, state = _decode_step_core(params, state, toks, cfg, active)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return (state, nxt), nxt

    (state, _), produced = jax.lax.scan(
        body, (state, tokens), length=n_steps
    )
    return produced, state


_paged_decode_window = functools.partial(
    jax.jit, static_argnames=("cfg", "n_steps"), donate_argnums=(1,)
)(_paged_decode_window_impl)


def _paged_decode_window_capped_impl(params: dict, state: PagedState,
                                     tokens, cfg: TransformerConfig,
                                     n_steps: int, active, steps_left,
                                     stop_tokens):
    """Greedy window with PER-SLOT stop detection in the scan carry.

    The overlap pipeline (serving.py) dispatches window N+1 before the
    host has harvested window N, so the host can no longer shrink the
    window to the tightest slot's remaining budget the way the serial
    path does (_window_steps). Instead each row carries its own budget
    cap: ``steps_left`` [B] int32 is how many steps row b may still
    decode, and the per-step done flag ``i >= steps_left[b]`` freezes a
    finished row — its length holds and its K/V scatters drop (the
    same ``active`` gate chunked prefill relies on), so a speculatively
    dispatched window can never scribble past a stop the host hasn't
    seen yet. A frozen row keeps re-emitting its final token; the host
    truncates its stream at the true stop when it harvests
    (row b's real tokens are produced[:steps_left[b]]).

    Finish bookkeeping rides the carry (SERVING.md rung 23):
    ``stop_tokens`` [B] int32 is each row's stop token (-1 = none;
    argmax can never produce -1, so stop-free traffic is bit-identical
    by construction). The window tracks ``stop_at`` [B] — the first
    1-based live step whose produced token equals the row's stop
    (0 = no hit) — and the result packs TWO extra rows onto the
    produced tokens: ``produced[n_steps] = fin`` (0 = window-capped,
    1 = froze in-window on its per-slot cap, 2 = stop token hit) and
    ``produced[n_steps + 1] = stop_at``. One device->host transfer
    hands the host every finish decision, so the boundary sweep does
    O(finishes) work instead of scanning the bucket. A stop hit does
    NOT freeze the row on device — its remaining in-window steps decode
    garbage within its already-granted cap (writes stay inside reserved
    pages, lengths advance exactly as the host pre-booked) and the host
    truncates the emission at ``stop_at``; the row's slot releases at
    harvest, which zeroes the length either way.
    """
    _note_trace("window_capped")

    def body(carry, i):
        state, toks, stop_at = carry
        live = active & (i < steps_left)
        logits, state = _decode_step_core(params, state, toks, cfg, live)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        nxt = jnp.where(live, nxt, toks)
        stop_at = jnp.where(
            live & (stop_at == 0) & (nxt == stop_tokens), i + 1, stop_at
        )
        return (state, nxt, stop_at), nxt

    stop0 = jnp.zeros(tokens.shape[0], jnp.int32)
    (state, _, stop_at), produced = jax.lax.scan(
        body, (state, tokens, stop0), jnp.arange(n_steps)
    )
    fin = jnp.where(
        stop_at > 0, 2,
        jnp.where(active & (steps_left <= n_steps), 1, 0),
    ).astype(jnp.int32)
    produced = jnp.concatenate(
        [produced, fin[None], stop_at[None]], axis=0
    )
    return produced, state


_paged_decode_window_capped = functools.partial(
    jax.jit, static_argnames=("cfg", "n_steps"), donate_argnums=(1,)
)(_paged_decode_window_capped_impl)


def _paged_decode_window_sampled_capped_impl(
        params: dict, state: PagedState, tokens,
        cfg: TransformerConfig, n_steps: int, active, key_data,
        base_steps, temps, top_ps, sampled_mask, steps_left,
        stop_tokens):
    """Mixed greedy/sampled window with the per-slot done flag of
    :func:`_paged_decode_window_capped_impl`. Live rows run the exact
    key schedule of the serial sampled window (``fold_in(seed,
    base + i)``), so pipelined and serial sampled decode emit identical
    tokens; frozen rows' draws are computed and discarded (their
    outputs are never read and their state never advances). Packs the
    same ``[fin, stop_at]`` finish-bookkeeping rows onto the produced
    tokens as the greedy capped window."""
    _note_trace("window_sampled_capped")
    keys = jax.random.wrap_key_data(key_data)

    def body(carry, i):
        state, toks, stop_at = carry
        live = active & (i < steps_left)
        logits, state = _decode_step_core(params, state, toks, cfg,
                                          live)
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        from kvedge_tpu.models.decode import sample_token

        step_keys = jax.vmap(jax.random.fold_in)(keys, base_steps + i)
        sampled = sample_token(
            logits, step_keys, temps[:, None], top_ps[:, None]
        )
        nxt = jnp.where(sampled_mask, sampled, greedy).astype(jnp.int32)
        nxt = jnp.where(live, nxt, toks)
        stop_at = jnp.where(
            live & (stop_at == 0) & (nxt == stop_tokens), i + 1, stop_at
        )
        return (state, nxt, stop_at), nxt

    stop0 = jnp.zeros(tokens.shape[0], jnp.int32)
    (state, _, stop_at), produced = jax.lax.scan(
        body, (state, tokens, stop0), jnp.arange(n_steps)
    )
    fin = jnp.where(
        stop_at > 0, 2,
        jnp.where(active & (steps_left <= n_steps), 1, 0),
    ).astype(jnp.int32)
    produced = jnp.concatenate(
        [produced, fin[None], stop_at[None]], axis=0
    )
    return produced, state


_paged_decode_window_sampled_capped = functools.partial(
    jax.jit, static_argnames=("cfg", "n_steps"), donate_argnums=(1,)
)(_paged_decode_window_sampled_capped_impl)


def _paged_decode_window_sampled_impl(params: dict, state: PagedState,
                                      tokens, cfg: TransformerConfig,
                                      n_steps: int, active, key_data,
                                      base_steps, temps, top_ps,
                                      sampled_mask):
    """``n_steps`` decode steps with mixed greedy/sampled feedback.

    The round-5 fix for the sampled-RTT tax (VERDICT r4 #3): the
    per-token sampling key is ``fold_in(row_seed, t)`` with ``t`` a
    pure function of the request's emitted count — host-known at
    dispatch — so the whole key schedule rides the scan carry as
    ``base_steps + i``. Each step applies the SAME nucleus filter and
    categorical draw as the host path (decode.sample_token), then
    selects sampled vs greedy per row by ``sampled_mask``; one host
    round trip serves a window of sampled tokens exactly as it does
    greedy ones, and one sampled co-tenant no longer drags the whole
    batch onto per-step dispatch.

    ``key_data`` is raw uint32 key data ([B, 2] for threefry), wrapped
    on device — raw data crosses process boundaries (the slice
    op-stream) where typed key arrays cannot.
    """
    _note_trace("window_sampled")
    keys = jax.random.wrap_key_data(key_data)

    def body(carry, i):
        state, toks = carry
        logits, state = _decode_step_core(params, state, toks, cfg,
                                          active)
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        from kvedge_tpu.models.decode import sample_token

        step_keys = jax.vmap(jax.random.fold_in)(keys, base_steps + i)
        sampled = sample_token(
            logits, step_keys, temps[:, None], top_ps[:, None]
        )
        nxt = jnp.where(sampled_mask, sampled, greedy).astype(jnp.int32)
        return (state, nxt), nxt

    (state, _), produced = jax.lax.scan(
        body, (state, tokens), jnp.arange(n_steps)
    )
    return produced, state


_paged_decode_window_sampled = functools.partial(
    jax.jit, static_argnames=("cfg", "n_steps"), donate_argnums=(1,)
)(_paged_decode_window_sampled_impl)
