"""Model payloads hosted by the runtime.

The reference hosts an opaque external payload (the Azure IoT Edge daemon);
kvedge-tpu's payload slot is JAX-native, and the flagship occupant is a
compact decoder-only transformer LM designed TPU-first: bf16 compute onto
the MXU, ``lax.scan`` over layers (one compiled layer body regardless of
depth), static shapes, and Megatron-style dp×tp sharding via the rules in
:mod:`kvedge_tpu.parallel.sharding`.
"""

from kvedge_tpu.models.transformer import (
    PRESETS,
    TransformerConfig,
    init_params,
    forward,
    forward_hidden,
    forward_with_aux,
    loss_fn,
    make_train_step,
)
from kvedge_tpu.models.decode import (
    KVCache,
    init_cache,
    prefill,
    decode_step,
    generate,
)
from kvedge_tpu.models.kvcache import PagedKVCache, PagedCacheError
from kvedge_tpu.models.speculative import generate_speculative

__all__ = [
    "generate_speculative",
    "PRESETS",
    "TransformerConfig",
    "init_params",
    "forward",
    "forward_hidden",
    "forward_with_aux",
    "loss_fn",
    "make_train_step",
    "KVCache",
    "init_cache",
    "prefill",
    "decode_step",
    "generate",
    "PagedKVCache",
    "PagedCacheError",
]
