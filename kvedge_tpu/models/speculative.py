"""Prompt-lookup speculative decoding: K drafted tokens, one verify pass.

The serving latency lever the README's future-work list called for
(greedy decode emits one token per model forward; speculation emits up
to ``draft_len + 1``). The draft model is the CONTEXT itself — n-gram
("prompt lookup") drafting: find the most recent earlier occurrence of
the current bigram and propose the tokens that followed it. Free (no
second model), and strong exactly where autoregressive serving is slow:
summarization, code edits, retrieval-augmented generation — anything
whose output re-uses spans of its input.

Greedy speculation is EXACT: a draft is accepted only where it equals
the model's own greedy argmax, so output is token-for-token identical to
:func:`~kvedge_tpu.models.decode.generate` (pinned by
tests/test_speculative.py) — speculation changes the schedule, never the
text. Bad drafts only cost speed.

TPU-first shape discipline, same as decode.py:

* The ENTIRE generation is one compiled program: prefill, then a
  ``lax.while_loop`` of draft -> verify -> accept steps. All shapes are
  static (the draft length is a compile-time constant; acceptance moves
  a scalar length, never a shape); the loop is data-dependent only in
  its trip count, which ``while_loop`` exists for.
* Verification reuses the decode cache machinery: one
  ``_attend_layer`` pass over ``1 + K`` query positions against the
  donated KV slabs. Rejected drafts leave garbage K/V beyond the
  accepted length — harmless by construction: causal masking never
  attends past the query positions, and the next verify step's write
  window provably covers every garbage position before it can be read.
* Drafting is pure ``jnp`` (vectorized bigram match + one
  ``dynamic_slice``), fused into the same program — no host round trip
  per token group.

Reference parity: the reference has no inference path at all
(SURVEY.md §0); this extends the serving capability lane
(decode -> paged continuous batching -> streaming -> speculation).
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax import lax

from kvedge_tpu.models.decode import (
    KVCache,
    _run_layers,
    init_cache,
    prefill,
)
from kvedge_tpu.models.transformer import TransformerConfig


def _propose_ngram(ctx, length, k: int):
    """Draft ``k`` tokens from the context's own history (one row).

    ctx: [S] int32 (prompt + accepted tokens, junk beyond ``length``).
    Finds the most recent position ``p < length - 2`` where
    ``ctx[p:p+2]`` equals the current final bigram and proposes
    ``ctx[p+2 : p+2+k]``; with no match, repeats the last token (any
    guess is legal — verification makes correctness draft-independent).
    """
    s = ctx.shape[0]
    idx = jnp.arange(s)
    g0 = jnp.take(ctx, length - 2)
    g1 = jnp.take(ctx, length - 1)
    match = (ctx == g0) & (jnp.roll(ctx, -1) == g1) & (idx < length - 2)
    p = jnp.max(jnp.where(match, idx, -1))
    start = jnp.clip(p + 2, 0, s - k)
    draft = lax.dynamic_slice(ctx, (start,), (k,))
    return jnp.where(p >= 0, draft, jnp.full((k,), g1, ctx.dtype))


def _verify(params, cache: KVCache, tokens, cfg: TransformerConfig):
    """One forward over ``[1, 1+K]`` positions against the cache.

    ``tokens`` = [last accepted token, draft_0 .. draft_{K-1}]. Returns
    (greedy argmax at EVERY position [1, 1+K], cache advanced by 1+K) —
    the caller rewinds ``length`` to the accepted prefix; the garbage
    K/V beyond it is overwritten by the next step's window (see module
    docstring). Runs decode.py's own layer pipeline
    (``_run_layers(all_positions=True)``) so the numerics are the same
    code path as plain decode.
    """
    dtype = jnp.dtype(cfg.dtype)
    x = params["embedding"][tokens].astype(dtype)  # [1, 1+K, D]
    logits, new_cache = _run_layers(
        cfg, params, x, cache, cache.length, all_positions=True
    )  # [1, 1+K, V]
    return jnp.argmax(logits, axis=-1).astype(jnp.int32), new_cache


@functools.partial(jax.jit, static_argnames=("cfg", "n_new", "draft_len"))
def generate_speculative(params: dict, prompt, cfg: TransformerConfig,
                         n_new: int, draft_len: int = 4):
    """Greedy-decode ``n_new`` tokens with prompt-lookup speculation.

    prompt: [1, T] int32 — speculation is a single-sequence latency
    optimization (ragged per-row acceptance does not batch; throughput
    workloads want the paged server instead). Returns
    ``([1, T + n_new] int32, accepted_per_step fp32)`` where the second
    value is the mean tokens emitted per VERIFY pass (the prefill's
    first token is excluded; 1.0 = speculation never paid,
    ``draft_len + 1`` = every draft accepted, 0.0 = no verify pass ran
    i.e. ``n_new == 1``) — the observability hook for whether
    speculation pays on a workload.

    Token-for-token identical to ``generate(...)`` greedy output, with
    one precisely-scoped caveat: verification computes its logits with
    ``1+K``-query matmuls where plain decode uses single-query ones, so
    a vocab pair whose fp32-accumulated logits tie EXACTLY could break
    the argmax differently. Tests pin exactness in fp32 and bf16; for
    trained models an exact tie is measure-zero, and a tie-break
    difference selects an equally-ranked token, never a worse one.
    """
    if prompt.shape[0] != 1:
        raise ValueError(
            "speculative decoding is single-sequence (got batch "
            f"{prompt.shape[0]}); use generate()/the paged server for "
            "batched throughput"
        )
    k = draft_len
    prompt_len = prompt.shape[1]
    # Slack beyond n_new: a verify window may extend past the final
    # needed token; clamped writes must never shift onto real tokens.
    cache = init_cache(cfg, 1, max_seq=prompt_len + n_new + k + 1)
    logits, cache = prefill(params, prompt, cache, cfg)
    first = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [1]

    ctx0 = jnp.zeros((prompt_len + n_new + k + 1,), jnp.int32)
    ctx0 = lax.dynamic_update_slice(ctx0, prompt[0], (0,))
    ctx0 = ctx0.at[prompt_len].set(first[0])
    out0 = jnp.zeros((n_new + k + 1,), jnp.int32)
    out0 = out0.at[0].set(first[0])

    def cond(state):
        produced, *_ = state
        return produced < n_new

    def step(state):
        produced, steps, ctx, out, cache = state
        length = prompt_len + produced
        draft = _propose_ngram(ctx, length, k)  # [K]
        last = jnp.take(ctx, length - 1)
        tokens = jnp.concatenate([last[None], draft])[None]  # [1, 1+K]
        y, cache = _verify(params, cache, tokens, cfg)
        y = y[0]  # [1+K]: y[i] = greedy token after position i
        accepted = jnp.sum(
            jnp.cumprod((draft == y[:k]).astype(jnp.int32))
        )  # leading agreement, in [0, K]
        # Emitted this step: the accepted drafts then the bonus token
        # (the model's own argmax after them) — junk beyond that is
        # provably overwritten by the next step's window.
        emitted = jnp.where(
            jnp.arange(k + 1) < accepted, jnp.concatenate([draft, y[-1:]]),
            jnp.take(y, accepted),
        ).astype(jnp.int32)
        out = lax.dynamic_update_slice(out, emitted, (produced,))
        ctx = lax.dynamic_update_slice(ctx, emitted, (length,))
        # Valid K/V now covers [0, length + accepted): the verify pass
        # wrote `last` + the drafts; the accepted prefix is last + a
        # drafts. The BONUS token's K/V is not written yet — it is the
        # next step's `last`, exactly like plain decode's final token.
        cache = dataclasses.replace(cache, length=length + accepted)
        return produced + accepted + 1, steps + 1, ctx, out, cache

    produced, steps, _, out, _ = lax.while_loop(
        cond, step, (jnp.int32(1), jnp.int32(0), ctx0, out0, cache)
    )
    tokens = jnp.concatenate([prompt[0], out[:n_new]])[None]
    # Verify passes only: the prefill's first token is not a pass, so
    # the draft_len + 1 ceiling is actually reachable. Clamped at n_new:
    # the final pass may overshoot the budget, and tokens the client
    # never received must not inflate the acceleration metric.
    delivered = jnp.minimum(produced, n_new)
    rate = jnp.where(
        steps > 0,
        (delivered - 1).astype(jnp.float32)
        / jnp.maximum(steps, 1).astype(jnp.float32),
        0.0,
    )
    return tokens, rate
