"""Autoregressive inference: prefill + decode with a static-shape KV cache.

The reference has no inference path (its payload is an opaque external
daemon, SURVEY.md §0); this module is the serving half of kvedge-tpu's
flagship payload, designed TPU-first:

* **Static shapes.** The cache is allocated once at ``[L, B, S, K, Dh]``
  and written in place with ``lax.dynamic_update_slice``; the decode loop
  is a ``lax.scan`` over steps — one compiled step regardless of length,
  no retracing as the sequence grows.
* **Donated cache.** ``decode_step`` donates the cache buffers, so XLA
  performs the slice-update in place instead of copying HBM every token.
* **GQA-aware.** K/V are cached at ``cfg.kv_heads`` — with grouped-query
  attention the cache (the HBM-bandwidth bill of decoding) shrinks by
  ``n_heads / n_kv_heads``. Attention against the cache uses a grouped
  einsum; the KV repeat is never materialized.
* **fp32 softmax, bf16 everything else** — same numerics policy as
  training (transformer.py).

The per-step layer loop is the same ``lax.scan``-over-stacked-params scheme
as the forward pass: each layer's cache slab rides the scan's xs/ys, so XLA
compiles ONE layer body and, with donation, updates slabs in place.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax import lax

from kvedge_tpu.models.transformer import (
    TransformerConfig,
    _rmsnorm,
    _rotary,
    split_qkv,
    stacked_layer_params,
    tied_readout,
)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class KVCache:
    """Contiguous KV cache: one [L, B, S, K, Dh] slab per projection.

    ``length`` is the number of valid positions (traced; uniform across the
    batch — ragged batches are the paged cache's job, models/kvcache.py).
    """

    k: jax.Array
    v: jax.Array
    length: jax.Array  # scalar int32

    @property
    def max_seq(self) -> int:
        return self.k.shape[2]


def init_cache(cfg: TransformerConfig, batch: int,
               max_seq: int | None = None) -> KVCache:
    from kvedge_tpu.models.moe import warn_if_train_serve_divergence

    cfg.validate()
    warn_if_train_serve_divergence(cfg)
    shape = (
        cfg.n_layers, batch, max_seq or cfg.max_seq, cfg.kv_heads, cfg.d_head,
    )
    dtype = jnp.dtype(cfg.dtype)
    return KVCache(
        k=jnp.zeros(shape, dtype),
        v=jnp.zeros(shape, dtype),
        length=jnp.zeros((), jnp.int32),
    )


def _attend_layer(cfg: TransformerConfig, x, layer_params, k_slab, v_slab,
                  pos):
    """One decoder block against the cache.

    x: [B, Q, D] new positions starting at ``pos``; k_slab/v_slab:
    [B, S, K, Dh] this layer's cache. Returns (x, k_slab, v_slab) with the
    new positions written in. Works for prefill (Q = prompt len, pos = 0)
    and decode (Q = 1) alike.
    """
    if cfg.n_experts:
        w_qkv, w_out, router, w_up, w_down, ln_attn, ln_mlp = layer_params
    else:
        w_qkv, w_out, w_up, w_down, ln_attn, ln_mlp = layer_params
    batch, q_len, _ = x.shape
    h, kv, dh = cfg.n_heads, cfg.kv_heads, cfg.d_head
    group = h // kv
    max_seq = k_slab.shape[1]
    dtype = x.dtype

    normed = _rmsnorm(x, ln_attn)
    q, k, v = split_qkv(cfg, normed @ w_qkv.astype(dtype))
    positions = pos + jnp.arange(q_len)
    q = _rotary(q, positions)
    k = _rotary(k, positions)

    k_slab = lax.dynamic_update_slice(k_slab, k, (0, pos, 0, 0))
    v_slab = lax.dynamic_update_slice(v_slab, v, (0, pos, 0, 0))

    # Grouped attention against the whole slab; invalid tail positions are
    # masked out. q grouped as [B, Q, K, G, Dh] so each KV head serves its
    # G query heads without materializing a repeat.
    qg = q.reshape(batch, q_len, kv, group, dh)
    scores = jnp.einsum("bqkgd,bskd->bkgqs", qg, k_slab) / (dh ** 0.5)
    key_pos = jnp.arange(max_seq)
    allowed = key_pos[None, :] <= positions[:, None]  # [Q, S] causal+valid
    scores = jnp.where(
        allowed[None, None, None], scores, jnp.finfo(dtype).min
    )
    weights = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(dtype)
    attended = jnp.einsum("bkgqs,bskd->bqkgd", weights, v_slab)
    x = x + attended.reshape(batch, q_len, h * dh) @ w_out.astype(dtype)

    normed = _rmsnorm(x, ln_mlp)
    if cfg.n_experts:
        from kvedge_tpu.models.moe import routed_ffn_block

        x = x + routed_ffn_block(
            normed, router, w_up, w_down, top_k=cfg.expert_top_k
        )
    else:
        x = x + jax.nn.gelu(normed @ w_up.astype(dtype)) @ w_down.astype(dtype)
    return x, k_slab, v_slab


def _run_layers(cfg: TransformerConfig, params: dict, x, cache: KVCache,
                pos, all_positions: bool = False):
    """Scan the layer stack, threading each layer's cache slab through xs/ys.

    ``all_positions=True`` reads out logits at EVERY query position
    (speculative verification needs the argmax after each drafted
    token); the default reads only the last (prefill/decode). One
    definition of the layer pipeline for both, so the speculative
    path's numerics can never drift from plain decode's.
    """

    def body(carry, xs):
        layer_params, k_slab, v_slab = xs
        out, k_slab, v_slab = _attend_layer(
            cfg, carry, layer_params, k_slab, v_slab, pos
        )
        return out, (k_slab, v_slab)

    x, (new_k, new_v) = lax.scan(
        body, x, (stacked_layer_params(params, cfg), cache.k, cache.v)
    )
    new_cache = KVCache(k=new_k, v=new_v, length=pos + x.shape[1])
    x = _rmsnorm(x, params["ln_final"])
    logits = tied_readout(
        x if all_positions else x[:, -1], params["embedding"]
    )
    return logits, new_cache


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnums=(2,))
def prefill(params: dict, tokens, cache: KVCache, cfg: TransformerConfig):
    """Feed a [B, T] prompt into an empty cache.

    Returns (last-position logits [B, V] fp32, filled cache).
    """
    dtype = jnp.dtype(cfg.dtype)
    x = params["embedding"][tokens].astype(dtype)
    return _run_layers(cfg, params, x, cache, jnp.zeros((), jnp.int32))


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnums=(1,))
def decode_step(params: dict, cache: KVCache, tokens, cfg: TransformerConfig):
    """One decode step: [B] tokens at position ``cache.length``.

    Returns (logits [B, V] fp32, cache advanced by one).
    """
    dtype = jnp.dtype(cfg.dtype)
    x = params["embedding"][tokens][:, None].astype(dtype)  # [B, 1, D]
    return _run_layers(cfg, params, x, cache, cache.length)


def nucleus_filter(logits, temperature, top_p):
    """Temperature-scale + top-p (nucleus) filter. logits [..., V] fp32.

    Tokens outside the smallest probability mass >= ``top_p`` get -inf;
    the highest-probability token always survives (top_p -> 0 degrades
    to greedy). ONE definition shared by the contiguous scan and the
    continuous-batching server, so the two backends sample identically
    from identical logits — the cross-backend parity contract
    (tests/test_sampling.py). ``temperature``/``top_p`` are traced
    scalars: new values never recompile the serving loop.
    """
    scaled = logits / jnp.maximum(temperature, 1e-6)
    sorted_logits = jnp.sort(scaled, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cumulative = jnp.cumsum(probs, axis=-1)
    # Keep ranks whose PRECEDING mass is < top_p (the first rank always
    # qualifies); map the rank cutoff back through a logit threshold.
    keep = (cumulative - probs) < top_p
    threshold = jnp.min(
        jnp.where(keep, sorted_logits, jnp.inf), axis=-1, keepdims=True
    )
    return jnp.where(scaled >= threshold, scaled, -jnp.inf)


def sample_token(logits, keys, temperature, top_p):
    """One sampled token id per row. logits [B, V] fp32, ``keys`` one
    PRNG key per row (each row owns its stream — batch composition must
    not change any row's tokens)."""
    filtered = nucleus_filter(logits, temperature, top_p)
    return jax.vmap(jax.random.categorical)(keys, filtered).astype(
        jnp.int32
    )


def row_sample_keys(seed_keys, step):
    """The shared key schedule: token ``step`` of a row samples with
    ``fold_in(row_seed, step)`` — a pure function of (row seed, token
    index), independent of batch composition or backend."""
    return jax.vmap(lambda k: jax.random.fold_in(k, step))(seed_keys)


@functools.partial(jax.jit, static_argnames=("cfg", "n_new", "sampled"))
def generate(params: dict, prompt, cfg: TransformerConfig, n_new: int,
             sampling=None, sampled: bool = False):
    """Decode ``n_new`` tokens after a [B, T] prompt.

    Greedy by default. With ``sampled=True``, ``sampling`` is a traced
    ``(seed_keys [B], temperature scalar, top_p scalar)`` triple: token
    ``t`` of row ``r`` samples from the nucleus-filtered logits with key
    ``fold_in(seed_keys[r], t)``. Temperature/top_p/keys are traced, so
    only the greedy/sampled CHOICE recompiles — not every request's
    parameters.

    Returns [B, T + n_new] int32. The whole loop is one compiled program:
    prefill, then a ``lax.scan`` of donated decode steps.
    """
    batch, prompt_len = prompt.shape
    cache = init_cache(cfg, batch, max_seq=prompt_len + n_new)
    logits, cache = prefill(params, prompt, cache, cfg)

    def pick(logits, step):
        if not sampled:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        seed_keys, temperature, top_p = sampling
        keys = row_sample_keys(seed_keys, step)
        return sample_token(logits, keys, temperature, top_p)

    def step_fn(carry, step):
        cache, logits = carry
        token = pick(logits, step)
        logits, cache = decode_step(params, cache, token, cfg)
        return (cache, logits), token

    # n_new - 1 cached steps; the final token falls out of the last carried
    # logits without paying for a decode step whose logits nobody reads.
    (_, logits), tokens = lax.scan(
        step_fn, (cache, logits), jnp.arange(n_new - 1)
    )
    last = pick(logits, n_new - 1)
    return jnp.concatenate([prompt, tokens.T, last[:, None]], axis=1)
