"""On-demand profiler trace capture, persisted through the state volume.

The reference has no tracing or profiling subsystem of any kind
(SURVEY.md §5, "Tracing / profiling: absent") — this is an addition, in
the same spirit as the status/metrics endpoints: the runtime's one
externally reachable surface should also be able to answer "what is the
device actually doing?". A capture runs ``jax.profiler`` for a bounded
window and writes the trace (xplane + trace.json.gz, loadable in
XProf/TensorBoard or Perfetto) under ``<state_dir>/traces/``, so traces
survive pod rescheduling exactly like heartbeats and checkpoints do.
"""

from __future__ import annotations

import os
import shutil
import threading
import time
from typing import Callable


class CaptureBusy(RuntimeError):
    """A trace capture is already in progress (only one at a time)."""


class CaptureUnavailable(RuntimeError):
    """The runtime cannot profile right now (e.g. still booting)."""


_jitted_matmul = None


def default_activity() -> None:
    """A small device workload so a capture is never empty.

    The profiler records whatever the devices do during the window; on a
    runtime whose payload is idle between heartbeats, that could be
    nothing. One jitted matmul guarantees at least one device program in
    every trace. The jitted callable is cached at module level — a fresh
    ``jax.jit(lambda ...)`` per call would retrace every loop iteration
    and fill the trace with compile events instead of device work.
    """
    global _jitted_matmul
    import jax
    import jax.numpy as jnp

    if _jitted_matmul is None:
        _jitted_matmul = jax.jit(lambda a: a @ a)
    x = jnp.ones((512, 512), jnp.bfloat16)
    _jitted_matmul(x).block_until_ready()


class TraceCapture:
    """Bounded, serialized ``jax.profiler`` captures into the state dir.

    Trace directories are numbered past any that already exist on the
    state volume (the volume outlives the pod), and only the newest
    ``keep`` traces are retained — the traces dir shares its PVC with
    heartbeats and checkpoints, and the capture endpoint is reachable
    through the LoadBalancer, so unbounded growth would let repeated
    captures fill the volume and degrade the runtime.
    """

    def __init__(self, state_dir: str, *, max_seconds: float = 60.0,
                 keep: int = 8,
                 activity: Callable[[], None] | None = default_activity):
        self._traces_dir = os.path.join(state_dir, "traces")
        self._max_seconds = max_seconds
        self._keep = max(1, keep)
        self._activity = activity
        self._lock = threading.Lock()

    @staticmethod
    def _trace_seq(name: str) -> int:
        try:
            return int(name.split("-", 1)[1])
        except (IndexError, ValueError):
            return -1

    def _existing_traces(self) -> list[str]:
        """Trace dir names, oldest first by *numeric* sequence.

        Lexicographic order would break past trace-9999 (``trace-10000``
        sorts before ``trace-1001``), making retention delete the capture
        it just wrote.
        """
        try:
            names = os.listdir(self._traces_dir)
        except FileNotFoundError:
            return []
        return sorted(
            (n for n in names if n.startswith("trace-")), key=self._trace_seq
        )

    def _next_trace_dir(self) -> str:
        seq = max(
            (self._trace_seq(n) for n in self._existing_traces()), default=0
        )
        return os.path.join(self._traces_dir, f"trace-{max(seq, 0) + 1:04d}")

    def _sweep_retention(self) -> None:
        for name in self._existing_traces()[:-self._keep]:
            shutil.rmtree(os.path.join(self._traces_dir, name),
                          ignore_errors=True)

    def list(self) -> list[dict]:
        """The on-disk captures, oldest first — ``GET /profile/traces``.

        Pure filesystem walk (no profiler, no lock): safe to call from
        the status server's handler threads at any time, including
        while a capture is running (the in-progress dir just shows its
        bytes-so-far).
        """
        out = []
        for name in self._existing_traces():
            trace_dir = os.path.join(self._traces_dir, name)
            files = [
                os.path.join(root, f)
                for root, _, fs in os.walk(trace_dir) for f in fs
            ]
            try:
                size = sum(os.path.getsize(f) for f in files)
                mtime = os.path.getmtime(trace_dir)
            except OSError:
                continue  # swept by retention mid-walk
            out.append({
                "name": name,
                "seq": self._trace_seq(name),
                "age_s": round(max(0.0, time.time() - mtime), 3),
                "files": len(files),
                "bytes": size,
            })
        return out

    def capture(self, seconds: float = 3.0) -> dict:
        """Trace device activity for ``seconds``; return a summary doc."""
        seconds = min(max(float(seconds), 0.1), self._max_seconds)
        if not self._lock.acquire(blocking=False):
            raise CaptureBusy("a trace capture is already running")
        try:
            import jax

            trace_dir = self._next_trace_dir()
            os.makedirs(trace_dir, exist_ok=True)
            started = time.time()
            jax.profiler.start_trace(trace_dir)
            try:
                # The activity only needs to guarantee the trace is never
                # empty — run it at a slow cadence and sleep the rest of
                # the window, so a long capture records the *payload's*
                # device work instead of drowning it in synthetic matmuls
                # (and doesn't peg a host thread for the whole window).
                deadline = started + seconds
                activity_cadence = 0.5
                next_activity = started
                while True:
                    now = time.time()
                    if now >= deadline:
                        break
                    if self._activity is not None and now >= next_activity:
                        self._activity()
                        next_activity = time.time() + activity_cadence
                    wakeup = deadline if self._activity is None else min(
                        deadline, next_activity
                    )
                    time.sleep(max(0.0, min(0.1, wakeup - time.time())))
            finally:
                jax.profiler.stop_trace()
            self._sweep_retention()
            files = [
                os.path.join(root, f)
                for root, _, fs in os.walk(trace_dir) for f in fs
            ]
            return {
                "trace_dir": trace_dir,
                "duration_s": round(time.time() - started, 3),
                "files": len(files),
                "bytes": sum(os.path.getsize(f) for f in files),
            }
        finally:
            self._lock.release()
