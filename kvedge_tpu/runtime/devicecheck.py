"""Device-visibility check and sharded matmul probe.

The end-to-end "it works" signal for the provisioned runtime (SURVEY.md §7
step 4): the analogue of the reference's post-install smoke test — the VM
boots and `kubectl get vmi` shows Running (`NOTES.txt:9`) — is that the pod
sees its TPU chips and can execute one compiled, mesh-sharded computation
across all of them.

TPU-first details: the probe is a bf16 matmul (MXU-shaped work, not a toy
scalar op), laid out over the configured `jax.sharding.Mesh` with the batch
dim sharded across every mesh axis, so a wrong sharding or a missing chip
fails loudly here rather than in a real workload later.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Sequence

from kvedge_tpu.config.runtime_config import RuntimeConfig

PROBE_ROWS_PER_DEVICE = 16
PROBE_DIM = 128


@dataclasses.dataclass(frozen=True)
class DeviceCheckResult:
    ok: bool
    platform: str
    device_count: int
    device_kinds: tuple[str, ...]
    mesh_axes: tuple[str, ...]
    mesh_shape: tuple[int, ...]
    probe_ms: float
    probe_checksum: float
    error: str = ""

    def to_dict(self) -> dict:
        return dataclasses.asdict(self) | {
            "device_kinds": list(self.device_kinds),
            "mesh_axes": list(self.mesh_axes),
            "mesh_shape": list(self.mesh_shape),
        }


def _failure(platform: str, count: int, kinds: Sequence[str], error: str
             ) -> DeviceCheckResult:
    return DeviceCheckResult(
        ok=False, platform=platform, device_count=count,
        device_kinds=tuple(kinds), mesh_axes=(), mesh_shape=(),
        probe_ms=0.0, probe_checksum=0.0, error=error,
    )


def run_device_check(cfg: RuntimeConfig) -> DeviceCheckResult:
    """Probe device visibility, then run one pjit'd matmul over the mesh."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from kvedge_tpu.parallel.mesh import build_mesh

    devices = jax.devices()
    platform = devices[0].platform if devices else "none"
    kinds = tuple(sorted({d.device_kind for d in devices}))
    count = len(devices)

    if cfg.expected_platform and platform != cfg.expected_platform:
        return _failure(
            platform, count, kinds,
            f"expected platform {cfg.expected_platform!r}, got {platform!r}",
        )
    if cfg.expected_chips and count != cfg.expected_chips:
        return _failure(
            platform, count, kinds,
            f"expected {cfg.expected_chips} chips, {count} visible",
        )

    try:
        mesh = build_mesh(cfg.mesh, devices=devices)
    except Exception as e:
        return _failure(platform, count, kinds, f"mesh resolution failed: {e}")

    axis_names = cfg.mesh.axis_names()
    shape = mesh.devices.shape

    rows = PROBE_ROWS_PER_DEVICE * count
    x_sharding = NamedSharding(mesh, P(axis_names))  # batch over all axes
    w_sharding = NamedSharding(mesh, P())            # replicated weights

    @jax.jit
    def probe(x, w):
        return jnp.sum(x @ w)

    try:
        x = jax.device_put(
            jnp.ones((rows, PROBE_DIM), dtype=jnp.bfloat16), x_sharding
        )
        w = jax.device_put(
            jnp.full((PROBE_DIM, PROBE_DIM), 0.5, dtype=jnp.bfloat16),
            w_sharding,
        )
        start = time.perf_counter()
        checksum = float(probe(x, w).block_until_ready())
        elapsed_ms = (time.perf_counter() - start) * 1000.0
    except Exception as e:  # XLA failures surface as runtime errors
        return _failure(platform, count, kinds, f"matmul probe failed: {e}")

    expected = rows * PROBE_DIM * PROBE_DIM * 0.5
    if abs(checksum - expected) > expected * 1e-2:
        return _failure(
            platform, count, kinds,
            f"probe checksum {checksum} != expected {expected}",
        )

    return DeviceCheckResult(
        ok=True, platform=platform, device_count=count, device_kinds=kinds,
        mesh_axes=axis_names, mesh_shape=shape,
        probe_ms=elapsed_ms, probe_checksum=checksum,
    )
