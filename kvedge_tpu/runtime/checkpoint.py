"""Checkpoint/resume through the PVC-backed state directory.

The reference's whole checkpoint story is "the PVC is the checkpoint":
EdgeHub message state survives rescheduling because the boot disk is
PVC-backed (SURVEY.md §5, reference ``README.md:77,88``) — there is no
application-level checkpoint code at all. kvedge-tpu keeps that property
for the runtime's own state (heartbeats) and adds what a *JAX* payload
actually needs: an orbax-backed layout under ``<state_dir>/checkpoints``
so training state (params, optimizer, step) written through the PVC is
restorable by the next pod generation (SURVEY.md §7 capability 3 calls
for exactly this orbax-compatible layout).
"""

from __future__ import annotations

import os
from typing import Any

CHECKPOINT_SUBDIR = "checkpoints"


def resolve_checkpoint_dir(state_dir: str, checkpoint_dir: str = "") -> str:
    """Where checkpoints live for a given state volume + optional override.

    Default (empty override): ``<state_dir>/checkpoints`` on the PVC —
    the single-host layout, where checkpoint durability IS pod-restart
    durability. A multi-host slice needs storage every host can reach
    (per-host PVCs cannot hold a slice-wide sharded checkpoint), so the
    override accepts a shared filesystem path or a remote URI
    (``gs://bucket/prefix`` — orbax resolves URI schemes through
    ``etils.epath``). URIs are passed through untouched; local paths are
    absolutized exactly like the default. Heartbeats and train-progress
    stay on the per-host PVC either way — they are per-pod liveness
    state, not slice state.
    """
    if not checkpoint_dir:
        return os.path.abspath(os.path.join(state_dir, CHECKPOINT_SUBDIR))
    if "://" in checkpoint_dir:
        return checkpoint_dir
    return os.path.abspath(checkpoint_dir)


class StateCheckpointer:
    """Thin orbax CheckpointManager over the state volume.

    Synchronous by design: the runtime's value proposition is that state
    is on the PVC when the pod dies, so every save waits for durability.
    ``checkpoint_dir`` overrides the on-PVC default for shared-storage
    deployments (see :func:`resolve_checkpoint_dir`).
    """

    def __init__(self, state_dir: str, keep: int = 3,
                 checkpoint_dir: str = ""):
        import orbax.checkpoint as ocp

        self._dir = resolve_checkpoint_dir(state_dir, checkpoint_dir)
        self._manager = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(max_to_keep=keep, create=True),
        )
        self._ocp = ocp

    @property
    def directory(self) -> str:
        return self._dir

    def save(self, step: int, tree: Any) -> None:
        self._manager.save(step, args=self._ocp.args.StandardSave(tree))
        self._manager.wait_until_finished()

    def latest_step(self) -> int | None:
        return self._manager.latest_step()

    def restore_latest(self, abstract_tree: Any = None, *,
                       partial: bool = False) -> tuple[int, Any] | None:
        """(step, tree) of the newest checkpoint, or None on a fresh volume.

        ``abstract_tree`` (e.g. ``jax.eval_shape`` output or a concrete
        template) restores with the correct dtypes/shardings; omitting it
        falls back to orbax's topology inference. With ``partial=True``,
        subtrees of ``abstract_tree`` replaced by ``orbax.checkpoint
        .PLACEHOLDER`` are skipped entirely — never read, never allocated
        (how ``serve``/``eval`` restore params without materializing the
        optimizer moments). Partial restore is only valid on a manager
        that has not saved in this process (orbax binds the handler to
        the first args type it sees).
        """
        step = self._manager.latest_step()
        if step is None:
            return None
        if abstract_tree is not None:
            args = (self._ocp.args.PyTreeRestore(abstract_tree) if partial
                    else self._ocp.args.StandardRestore(abstract_tree))
            tree = self._manager.restore(step, args=args)
        else:
            tree = self._manager.restore(step)
        return step, tree

    def close(self) -> None:
        self._manager.close()

    def __enter__(self) -> "StateCheckpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
