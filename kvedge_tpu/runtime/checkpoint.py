"""Checkpoint/resume through the PVC-backed state directory.

The reference's whole checkpoint story is "the PVC is the checkpoint":
EdgeHub message state survives rescheduling because the boot disk is
PVC-backed (SURVEY.md §5, reference ``README.md:77,88``) — there is no
application-level checkpoint code at all. kvedge-tpu keeps that property
for the runtime's own state (heartbeats) and adds what a *JAX* payload
actually needs: an orbax-backed layout under ``<state_dir>/checkpoints``
so training state (params, optimizer, step) written through the PVC is
restorable by the next pod generation (SURVEY.md §7 capability 3 calls
for exactly this orbax-compatible layout).
"""

from __future__ import annotations

import os
from typing import Any

CHECKPOINT_SUBDIR = "checkpoints"


def _shape_index(tree: Any) -> dict[str, tuple]:
    """``{"params/embedding": (512, 128), ...}`` for every leaf with a
    shape. Key-path strings normalize container differences — orbax
    metadata renders optax's namedtuples/tuples as dicts of stringified
    indices, so treedef equality is the wrong comparator across that
    boundary; names are stable."""
    import jax

    idx: dict[str, tuple] = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        shape = getattr(leaf, "shape", None)
        if shape is None:
            continue
        parts = []
        for p in path:
            part = getattr(p, "key", None)
            if part is None:
                part = getattr(p, "name", None)
            if part is None:
                part = getattr(p, "idx", None)
            parts.append(str(part))
        idx["/".join(parts)] = tuple(shape)
    return idx


def _verify_template(abstract_tree: Any, saved_tree: Any, source: str,
                     *, structure_must_match: bool = True) -> None:
    """Raise loudly when the restore template doesn't match the
    checkpoint.

    The mismatch-fails-loudly contract (a serve pod whose [model]
    disagrees with the checkpoint must error, never silently decode a
    different architecture) must not depend on the orbax version doing
    the checking: some releases fulfil a mismatched template from
    whatever the file holds without erroring. ``saved_tree`` is the
    checkpoint's own metadata (pre-restore) or the restored tree
    (post-restore net). Shape checks skip template leaves without a
    ``.shape`` (e.g. PLACEHOLDER markers on partial restores).
    """
    import jax

    want, want_def = jax.tree_util.tree_flatten(abstract_tree)
    got, got_def = jax.tree_util.tree_flatten(saved_tree)
    if want_def != got_def:
        if not structure_must_match:
            # Metadata pre-check: container types differ legitimately
            # (orbax metadata renders tuples as dicts), so match leaves
            # by key path instead of treedef.
            want_idx = _shape_index(abstract_tree)
            got_idx = _shape_index(saved_tree)
            for key in want_idx.keys() & got_idx.keys():
                if want_idx[key] != got_idx[key]:
                    raise ValueError(
                        f"checkpoint shape mismatch against the "
                        f"{source} at {key!r}: template expects "
                        f"{want_idx[key]}, checkpoint holds "
                        f"{got_idx[key]} — the configured model does "
                        "not match the checkpointed one"
                    )
            return
        raise ValueError(
            "checkpoint tree structure mismatch against the "
            f"{source}: the restore template has {want_def}, the "
            f"checkpoint holds {got_def} — the configured model does "
            "not match the checkpointed one"
        )
    for w, g in zip(want, got):
        ws, gs = getattr(w, "shape", None), getattr(g, "shape", None)
        if ws is not None and gs is not None and tuple(ws) != tuple(gs):
            raise ValueError(
                f"checkpoint shape mismatch against the {source}: "
                f"template expects {tuple(ws)}, checkpoint holds "
                f"{tuple(gs)} — the configured model does not match "
                "the checkpointed one"
            )


def resolve_checkpoint_dir(state_dir: str, checkpoint_dir: str = "") -> str:
    """Where checkpoints live for a given state volume + optional override.

    Default (empty override): ``<state_dir>/checkpoints`` on the PVC —
    the single-host layout, where checkpoint durability IS pod-restart
    durability. A multi-host slice needs storage every host can reach
    (per-host PVCs cannot hold a slice-wide sharded checkpoint), so the
    override accepts a shared filesystem path or a remote URI
    (``gs://bucket/prefix`` — orbax resolves URI schemes through
    ``etils.epath``). URIs are passed through untouched; local paths are
    absolutized exactly like the default. Heartbeats and train-progress
    stay on the per-host PVC either way — they are per-pod liveness
    state, not slice state.
    """
    if not checkpoint_dir:
        return os.path.abspath(os.path.join(state_dir, CHECKPOINT_SUBDIR))
    if "://" in checkpoint_dir:
        return checkpoint_dir
    return os.path.abspath(checkpoint_dir)


class StateCheckpointer:
    """Thin orbax CheckpointManager over the state volume.

    Synchronous by design: the runtime's value proposition is that state
    is on the PVC when the pod dies, so every save waits for durability.
    ``checkpoint_dir`` overrides the on-PVC default for shared-storage
    deployments (see :func:`resolve_checkpoint_dir`).
    """

    def __init__(self, state_dir: str, keep: int = 3,
                 checkpoint_dir: str = ""):
        import orbax.checkpoint as ocp

        self._dir = resolve_checkpoint_dir(state_dir, checkpoint_dir)
        self._manager = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(max_to_keep=keep, create=True),
        )
        self._ocp = ocp

    @property
    def directory(self) -> str:
        return self._dir

    def save(self, step: int, tree: Any) -> None:
        self._manager.save(step, args=self._ocp.args.StandardSave(tree))
        self._manager.wait_until_finished()

    def latest_step(self) -> int | None:
        return self._manager.latest_step()

    def _saved_metadata(self, step: int) -> Any | None:
        """Shape metadata of the saved tree, or None when unreadable.

        ``item_metadata`` resolves through the manager's handler
        registry, which a manager that never saved may not have bound
        yet (it then yields an empty tree); the handler-level
        ``metadata()`` reads the step directory directly. Best-effort:
        any failure returns None and the post-restore net still runs.
        """
        import jax

        try:
            meta = self._manager.item_metadata(step)
            if meta is not None and jax.tree_util.tree_leaves(meta):
                return meta
        except Exception:
            pass
        try:
            from etils import epath

            path = epath.Path(self._dir) / str(step) / "default"
            if path.exists():
                return self._ocp.StandardCheckpointHandler().metadata(path)
        except Exception:
            pass
        return None

    def restore_latest(self, abstract_tree: Any = None, *,
                       partial: bool = False) -> tuple[int, Any] | None:
        """(step, tree) of the newest checkpoint, or None on a fresh volume.

        ``abstract_tree`` (e.g. ``jax.eval_shape`` output or a concrete
        template) restores with the correct dtypes/shardings; omitting it
        falls back to orbax's topology inference. With ``partial=True``,
        subtrees of ``abstract_tree`` replaced by ``orbax.checkpoint
        .PLACEHOLDER`` are skipped entirely — never read, never allocated
        (how ``serve``/``eval`` restore params without materializing the
        optimizer moments). Partial restore is only valid on a manager
        that has not saved in this process (orbax binds the handler to
        the first args type it sees).
        """
        step = self._manager.latest_step()
        if step is None:
            return None
        if abstract_tree is not None:
            saved = self._saved_metadata(step)
            if saved is not None:
                _verify_template(abstract_tree, saved,
                                 "checkpoint metadata",
                                 structure_must_match=False)
        if abstract_tree is not None:
            args = (self._ocp.args.PyTreeRestore(abstract_tree) if partial
                    else self._ocp.args.StandardRestore(abstract_tree))
            tree = self._manager.restore(step, args=args)
        else:
            try:
                tree = self._manager.restore(step)
            except KeyError:
                # Some orbax versions refuse an argless restore on a
                # manager that never saved (no handler bound for the
                # item yet); StandardRestore with topology inference is
                # the same operation spelled explicitly.
                tree = self._manager.restore(
                    step, args=self._ocp.args.StandardRestore()
                )
        if abstract_tree is not None:
            _verify_template(abstract_tree, tree, "restored tree")
        return step, tree

    def close(self) -> None:
        self._manager.close()

    def __enter__(self) -> "StateCheckpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
