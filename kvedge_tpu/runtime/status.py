"""HTTP status endpoint — the runtime's externally reachable smoke surface.

The reference's post-install verification is human: ``kubectl get vmi``
shows Running, then ssh in (``NOTES.txt:8-12``); it has no observability
subsystem at all (SURVEY.md §5). kvedge-tpu adds a machine surface behind
the same LoadBalancer: ``/healthz`` for external monitors, ``/status`` for
the full runtime picture (devices, mesh, heartbeat age, boot count),
``/metrics`` in Prometheus text format, ``/version`` for kubelet probes,
``POST /profile?seconds=N`` for an on-demand profiler trace capture
(``kvedge_tpu/runtime/profiling.py``), and — when the runtime booted the
``serve`` payload — ``POST /generate`` for greedy decode against the
checkpointed model (``kvedge_tpu/runtime/workload.py``).

Auth model: the GET surface is read-only by design and stays open (the
reference's only public surface, SSH, is key-gated; the pod-world /status
is the ``kubectl get vmi`` analogue and leaks no secrets). The *mutating*
routes, ``POST /profile`` and ``POST /generate``, trigger device work, so
when the runtime config carries ``[status] token`` (delivered through the
same boot-config Secret as the rest of the TOML) every POST requires
``Authorization: Bearer <token>`` and answers 401 otherwise.
"""

from __future__ import annotations

import hmac
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable
from urllib.parse import parse_qs, urlsplit

from kvedge_tpu.runtime.profiling import CaptureBusy, CaptureUnavailable
from kvedge_tpu.version import __version__


class GenerateUnavailable(RuntimeError):
    """No generation backend is serving (payload is not ``serve``, or the
    runtime is still booting)."""


# Request-body ceiling for POST /generate: a [batch, prompt] token grid at
# int size is tiny, so 1 MiB is generous — anything bigger is a mistake or
# abuse of an internet-reachable port, rejected before json.loads.
_MAX_GENERATE_BODY = 1 << 20

_METRIC_FIELDS = (
    # (snapshot key, metric suffix, help text)
    ("ok", "up", "1 if the runtime payload check passed"),
    ("boot_count", "boot_count", "boots observed on this state volume"),
    ("uptime_s", "uptime_seconds", "seconds since this runtime booted"),
    ("heartbeat_seq", "heartbeat_seq", "monotonic heartbeat sequence"),
    ("heartbeat_age_s", "heartbeat_age_seconds", "age of the last heartbeat"),
)

# Serving observability (the ``serving`` sub-document of /status, fed by
# the serve payload's request accounting + the paged server's pool
# stats). Counter buckets mirror the HTTP classes POST /generate answers
# with: rejected = 400, unavailable = 503, errors = 500.
_SERVE_METRIC_FIELDS = (
    # (serving key, metric suffix, TYPE, help text)
    ("requests_total", "serve_requests_total", "counter",
     "generate requests reaching the serving backend (transport-level "
     "400s — bad framing/JSON — are rejected before it)"),
    ("completed_total", "serve_completed_total", "counter",
     "generate requests completed"),
    ("rejected_total", "serve_rejected_total", "counter",
     "invalid generate requests (HTTP 400)"),
    ("unavailable_total", "serve_unavailable_total", "counter",
     "capacity-refused generate requests (HTTP 503)"),
    ("errors_total", "serve_errors_total", "counter",
     "failed generate requests (HTTP 500)"),
    ("tokens_generated_total", "serve_tokens_generated_total", "counter",
     "tokens generated for clients"),
    ("last_latency_ms", "serve_last_latency_ms", "gauge",
     "latency of the most recently completed request"),
    # _total, not _sum: Prometheus counters end in _total, and a bare
    # _sum suffix collides with the histogram exposition grammar (the
    # /metrics conformance test pins both rules).
    ("latency_ms_sum", "serve_latency_ms_total", "counter",
     "summed latency of completed requests in ms (divide by "
     "kvedge_serve_completed_total for the mean)"),
    # Paged backend only: live pool occupancy.
    ("in_flight", "serve_in_flight", "gauge",
     "requests currently decoding (paged backend)"),
    ("free_slots", "serve_free_slots", "gauge",
     "free decode slots (paged backend)"),
    ("free_pages", "serve_free_pages", "gauge",
     "unreferenced KV pages in the pool (paged backend)"),
    ("reserved_pages", "serve_reserved_pages", "gauge",
     "worst-case pages reserved by in-flight requests (paged backend)"),
    # Capacity semantics (SERVING.md rung 21): total pool size, the
    # compile bucket the device batch dim currently runs at, and the
    # free-page watermarks the scheduler's shed/resume decisions key on.
    ("pages_total", "serve_pages_total", "gauge",
     "total KV pages in the pool (paged backend; HBM-budget- or "
     "serving_pages-sized)"),
    ("slots_total", "serve_slots_total", "gauge",
     "configured decode slots — the bucket ladder's ceiling (paged "
     "backend)"),
    ("bucket", "serve_bucket", "gauge",
     "device batch rows currently compiled for — the active compile "
     "bucket (paged backend; equals slots when bucketing is off)"),
    ("bucket_min", "serve_bucket_min", "gauge",
     "smallest compile bucket (serving_min_bucket; 0 = bucketing off, "
     "batch dim pinned to slots)"),
    ("page_low_watermark", "serve_page_low_watermark", "gauge",
     "free-page fraction below which non-top-priority admissions shed "
     "(0 = off)"),
    ("page_high_watermark", "serve_page_high_watermark", "gauge",
     "free-page fraction swapped requests wait for before resuming "
     "(0 = off)"),
    ("prefix_entries", "serve_prefix_entries", "gauge",
     "registered prefix-cache entries (paged backend)"),
    ("prefix_hits", "serve_prefix_hits_total", "counter",
     "admissions that reused a cached prompt prefix (paged backend)"),
    ("prefix_tokens_saved", "serve_prefix_tokens_saved_total", "counter",
     "prompt tokens whose prefill was skipped via prefix sharing "
     "(paged backend)"),
    # Copy-on-write radix prefix cache (SERVING.md rung 24): hit rate
    # (hits / lookups), HBM bytes the sharing avoided recomputing, COW
    # divergence copies, and the tiered host residency gauges.
    ("prefix_lookups", "serve_prefix_lookups_total", "counter",
     "admission-time prefix-cache lookups — hit rate is "
     "serve_prefix_hits_total / this (paged backend)"),
    ("prefix_bytes_saved", "serve_prefix_bytes_saved_total", "counter",
     "KV-pool bytes the shared prefix pages avoided re-prefilling "
     "(tokens_saved x per-token page bytes; paged backend)"),
    ("prefix_cow_copies", "serve_prefix_cow_copies_total", "counter",
     "device-side copy-on-write page copies taken when an admission "
     "shared a partially-matching last page (paged backend)"),
    ("prefix_host_entries", "serve_prefix_host_entries", "gauge",
     "prefix entries resident in the host RAM tier "
     "(serving_prefix_host_mb; paged backend)"),
    ("prefix_host_bytes", "serve_prefix_host_bytes", "gauge",
     "host RAM bytes held by demoted prefix entries, counted against "
     "serving_prefix_host_mb (paged backend)"),
    ("prefix_demotions", "serve_prefix_demotions_total", "counter",
     "prefix entries demoted HBM -> host tier on eviction "
     "(paged backend)"),
    ("prefix_promotions", "serve_prefix_promotions_total", "counter",
     "host-resident prefix entries swapped back into HBM at an "
     "admission hit (paged backend)"),
    # Journal refcounts (rung 24c): shadow snapshots of shared prefix
    # bytes cited by (not duplicated into) checkpoint entries.
    ("journal_shadow_nodes", "serve_journal_shadow_nodes", "gauge",
     "shared-prefix shadow snapshots the journal holds — each backs "
     "one or more checkpoint entries by reference (paged backend)"),
    ("journal_shadow_bytes", "serve_journal_shadow_bytes", "gauge",
     "host RAM bytes held by shared-prefix shadow snapshots, counted "
     "ONCE against the journal budget however many entries cite them"),
    ("window", "serve_window", "gauge",
     "device decode window cap in steps (paged backend, "
     "serving_window)"),
    # Overlapped window pipeline (serving_overlap): whether the
    # double-buffered decode loop is active, how many windows it has
    # harvested, and whether one is in flight right now.
    ("overlap", "serve_overlap", "gauge",
     "1 if the overlapped (double-buffered) window pipeline is "
     "enabled (paged backend, serving_overlap)"),
    ("overlap_windows_total", "serve_overlap_windows_total", "counter",
     "decode windows harvested by the overlapped pipeline"),
    ("overlap_inflight_depth", "serve_overlap_inflight_depth", "gauge",
     "dispatched-but-unharvested windows right now (0 or 1 — the "
     "pipeline is double-buffered, never deeper)"),
    ("spec_passes", "serve_spec_passes_total", "counter",
     "speculative verify passes run (paged backend, "
     "serving_speculative > 0)"),
    ("spec_emitted_per_pass", "serve_spec_emitted_per_pass", "gauge",
     "mean greedy tokens emitted per verify pass — the realized "
     "speculative acceleration (paged backend)"),
    # Device-resident spec windows (SERVING.md rung 20): W draft+
    # verify passes per dispatch, so the host RTT amortizes over up to
    # W*(1+K) tokens instead of taxing every pass.
    ("spec_window", "serve_spec_window", "gauge",
     "speculative passes batched per device dispatch (paged backend, "
     "serving_spec_window > 0; absent = windows off)"),
    ("spec_windows_total", "serve_spec_windows_total", "counter",
     "device-resident speculative windows harvested (paged backend, "
     "serving_spec_window)"),
    # Device-resident endgame (SERVING.md rung 23): whether mixed
    # greedy+sampled batches stay on the windowed spec path, and how
    # many finishes the device-side stop detection completed.
    ("spec_window_sampled", "serve_spec_window_sampled", "gauge",
     "1 if sampled co-tenants ride the windowed spec path on device "
     "(serving_spec_sampled_window; 0 = mixed batches fall back to "
     "the legacy per-pass program)"),
    ("stop_finishes_total", "serve_stop_finishes_total", "counter",
     "requests finished by per-row stop-token detection inside the "
     "device scan (paged backend; stop_token set on the request)"),
    # Failure surface (runtime/failures.py): 1 once the pool has been
    # poisoned by a serving failure. With the recovery supervisor active
    # (runtime/recovery.py) this clears again after a successful heal —
    # alert on degraded AND NOT recovering for the reschedule signal.
    ("degraded", "serve_degraded", "gauge",
     "1 if the serving pool is poisoned/degraded (clears after an "
     "in-process recovery; without one, the pod should be rescheduled)"),
    # Recovery machine (runtime/recovery.py): attempt/outcome counters
    # plus the in-flight gauge /healthz keys its non-terminal 503 off.
    ("recovering", "serve_recovering", "gauge",
     "1 while the recovery supervisor is actively healing the pool "
     "(degrade is not terminal yet)"),
    ("recovery_attempts_total", "serve_recovery_attempts_total",
     "counter",
     "individual heal attempts (teardown + reformation + warm restart) "
     "the recovery supervisor has made"),
    ("recoveries_total", "serve_recoveries_total", "counter",
     "successful in-process recoveries (pool returned to healthy)"),
    ("recovery_failures_total", "serve_recovery_failures_total",
     "counter",
     "recoveries that escalated to the terminal path (attempt budget "
     "exhausted or crash-loop breaker tripped)"),
    ("last_recovery_s", "serve_last_recovery_seconds", "gauge",
     "wall-clock seconds the most recent successful recovery took "
     "(also the basis of the degraded-refusal retry-after hint)"),
    # SLO-aware admission scheduler (models/scheduler.py, SERVING.md
    # rung 17): per-class queue depth, the preemptive-swap ledger, and
    # the shed counter the overload watermarks drive.
    ("sched_queue_depth_interactive", "serve_sched_queue_depth_interactive",
     "gauge",
     "interactive-class requests parked in the admission queue "
     "(paged backend)"),
    ("sched_queue_depth_batch", "serve_sched_queue_depth_batch", "gauge",
     "batch-class requests parked in the admission queue "
     "(paged backend)"),
    ("sched_swapped_out", "serve_sched_swapped_out", "gauge",
     "preempted requests whose KV pages currently live in host RAM "
     "awaiting resume (paged backend)"),
    ("sched_swap_bytes_host", "serve_sched_swap_bytes_host", "gauge",
     "host RAM bytes held by swapped-out KV snapshots, counted "
     "against serving_sched_swap_budget_mb (paged backend)"),
    ("sched_preemptions_total", "serve_sched_preemptions_total",
     "counter",
     "requests preempted (KV swapped to host) to admit a "
     "higher-class request (paged backend)"),
    ("sched_resumes_total", "serve_sched_resumes_total", "counter",
     "preempted requests swapped back in and resumed — matches "
     "preemptions at idle unless a failure dropped the swap set "
     "(paged backend)"),
    ("sched_shed_total", "serve_sched_shed_total", "counter",
     "requests rejected early by the overload watermarks "
     "(serving_sched_max_queue_depth / _wait_s) with a measured "
     "retry-after hint (paged backend)"),
    # Durability (models/serving.py + runtime/journal.py, SERVING.md
    # rung 22): boundary-checkpoint journal occupancy and the restores
    # revive()/reformation performed — the coverage story for
    # in-flight requests (paged backend, serving_checkpoint_every).
    ("checkpoint_every", "serve_checkpoint_every", "gauge",
     "configured checkpoint cadence in quiescent boundaries "
     "(0 = durability off; paged backend)"),
    ("journal_entries", "serve_journal_entries", "gauge",
     "live requests with a resumable checkpoint in the host-side "
     "journal (paged backend, serving_checkpoint_every)"),
    ("journal_bytes", "serve_journal_bytes", "gauge",
     "host RAM bytes held by journaled checkpoints (KV snapshots + "
     "token logs), counted against the journal budget"),
    ("checkpoints_total", "serve_checkpoints_total", "counter",
     "per-request boundary checkpoints taken since boot"),
    ("checkpoint_skipped_total", "serve_checkpoint_skipped_total",
     "counter",
     "checkpoints refused by the journal byte budget — those "
     "requests degrade to fail-and-retry on the next outage"),
    ("checkpoint_unchanged_total", "serve_checkpoint_unchanged_total",
     "counter",
     "checkpoints delta-skipped at a boundary because the request's "
     "standing journal entry already matched (gen_len, next_token) — "
     "zero device work spent re-serializing identical state "
     "(SERVING.md rung 26)"),
    ("journal_restores_total", "serve_journal_restores_total",
     "counter",
     "journaled in-flight requests re-admitted by revive()/"
     "reformation (direct slot restores + swap-set re-queues)"),
    # Online window controller (runtime/autotune.py, SERVING.md rung
    # 26, serving_window=auto): the per-boundary pick and its EWMA
    # inputs. Present only when the controller is on.
    ("autotune_window", "serve_autotune_window", "gauge",
     "decode window the online controller currently picks — the "
     "smallest power of two with window*t >= R (paged backend, "
     "serving_window=auto)"),
    ("autotune_r_ms", "serve_autotune_r_ms", "gauge",
     "EWMA host turnaround per window (dispatch+harvest bookkeeping "
     "the device window must hide), the controller's R input"),
    ("autotune_t_ms", "serve_autotune_t_ms", "gauge",
     "EWMA per-step device time, the controller's t input"),
    ("autotune_updates", "serve_autotune_updates_total", "counter",
     "harvested windows the controller has learned from"),
    # Request-scoped tracing (runtime/tracing.py, [payload]
    # serving_trace): flight-recorder occupancy and loss. Present only
    # while tracing is enabled.
    ("trace_events", "serve_trace_events", "gauge",
     "trace events currently held in the flight-recorder ring "
     "(paged backend, serving_trace)"),
    ("trace_events_total", "serve_trace_events_total", "counter",
     "trace events recorded since boot (paged backend, "
     "serving_trace)"),
    ("trace_dropped_total", "serve_trace_dropped_total", "counter",
     "trace events that fell off the bounded flight-recorder ring "
     "(paged backend, serving_trace)"),
    ("trace_sample", "serve_trace_sample", "gauge",
     "per-request trace sampling rate in (0, 1] (paged backend, "
     "serving_trace)"),
    # Completion counters (SERVING.md rung 25): normal finishes and
    # the tokens they realized — the goodput numerator.
    ("requests_done_total", "serve_requests_done_total", "counter",
     "requests that finished normally (cancels and failures "
     "excluded; paged backend)"),
    ("tokens_done_total", "serve_tokens_done_total", "counter",
     "generated tokens realized by normally-finished requests "
     "(paged backend)"),
    # SLO engine (runtime/slo.py, [payload] serving_slo): rolling
    # fast-window SLIs and the fast/slow error-budget burn rates.
    # Present only while the engine is on; 0.0 = window not yet
    # filled (the series must exist for recording rules).
    ("slo_ttft_p99_ms", "serve_slo_ttft_p99_ms", "gauge",
     "rolling fast-window TTFT p99 in ms (serving_slo)"),
    ("slo_itl_p99_ms", "serve_slo_itl_p99_ms", "gauge",
     "rolling fast-window per-request mean inter-token gap p99 in ms "
     "(serving_slo)"),
    ("slo_queue_p99_ms", "serve_slo_queue_p99_ms", "gauge",
     "rolling fast-window admission queue-wait p99 in ms "
     "(serving_slo)"),
    ("slo_goodput_tps", "serve_slo_goodput_tps", "gauge",
     "rolling fast-window goodput in generated tokens/s from "
     "normally-finished requests (serving_slo)"),
    ("slo_shed_rate", "serve_slo_shed_rate", "gauge",
     "rolling fast-window shed fraction: shed / (shed + done) "
     "(serving_slo)"),
    ("slo_burn_fast", "serve_slo_burn_fast", "gauge",
     "fast-window error-budget burn rate: worst bad-event fraction "
     "/ (1 - serving_slo_target); 1.0 = budget spent at exactly "
     "sustainable pace"),
    ("slo_burn_slow", "serve_slo_burn_slow", "gauge",
     "slow-window error-budget burn rate (the multi-window alert's "
     "is-it-real half)"),
    ("slo_alert", "serve_slo_alert", "gauge",
     "1 while BOTH burn windows exceed the alert thresholds "
     "(14x fast / 6x slow — the page condition, and the burn-gated "
     "shed input when serving_slo_shed is on)"),
    ("slo_snapshots_total", "serve_slo_snapshots_total", "counter",
     "boundary snapshots accepted into the SLO ring (serving_slo)"),
    ("slo_resets_total", "serve_slo_resets_total", "counter",
     "SLO ring rebases after a counter reset (pool replaced — plain "
     "revive() preserves counters and does not reset)"),
    # Occupancy timeline ring (runtime/slo.py OccupancyRing,
    # [payload] serving_occupancy_ring): the LATEST quiescent-boundary
    # sample, flattened; the full timeline exports as Chrome counter
    # tracks in GET /trace and the flight bundle's tail.
    ("occupancy_samples_total", "serve_occupancy_samples_total",
     "counter",
     "occupancy samples taken at quiescent boundaries "
     "(serving_occupancy_ring)"),
    ("occupancy_pages_total", "serve_occupancy_pages_total", "gauge",
     "pool pages at the last occupancy sample"),
    ("occupancy_pages_live", "serve_occupancy_pages_live", "gauge",
     "referenced (live) pool pages at the last occupancy sample"),
    ("occupancy_pages_free", "serve_occupancy_pages_free", "gauge",
     "free-list pages at the last occupancy sample"),
    ("occupancy_hbm_bytes_used", "serve_occupancy_hbm_bytes_used",
     "gauge",
     "HBM bytes held by live KV pages at the last occupancy sample "
     "(live pages x per-page pool bytes incl. int8 scales)"),
    ("occupancy_bucket", "serve_occupancy_bucket", "gauge",
     "active compile bucket at the last occupancy sample"),
    ("occupancy_slots_admitted", "serve_occupancy_slots_admitted",
     "gauge",
     "slots with admitted page tables at the last occupancy sample"),
    ("occupancy_slots_active", "serve_occupancy_slots_active", "gauge",
     "slots actively decoding at the last occupancy sample"),
    ("occupancy_reserved_pages", "serve_occupancy_reserved_pages",
     "gauge",
     "worst-case reserved pages at the last occupancy sample"),
    ("occupancy_prefix_entries", "serve_occupancy_prefix_entries",
     "gauge",
     "HBM-resident prefix-cache entries at the last occupancy sample"),
    ("occupancy_prefix_host_bytes",
     "serve_occupancy_prefix_host_bytes", "gauge",
     "host-tier prefix bytes at the last occupancy sample"),
    ("occupancy_journal_bytes", "serve_occupancy_journal_bytes",
     "gauge",
     "journal bytes at the last occupancy sample"),
    ("occupancy_queue_depth", "serve_occupancy_queue_depth", "gauge",
     "parked admission tickets at the last occupancy sample"),
)

# Latency histograms from the serving path (models/scheduler.py _Hist
# snapshots: {"edges", "counts", "sum", "count"} with per-bucket
# counts — cumulated into Prometheus ``le`` buckets here, at render
# time). The window_* series come from the overlapped decode loop, the
# sched_* series from the admission scheduler's per-class queue-wait
# tracking.
_SERVE_HISTOGRAM_FIELDS = (
    # (serving key, metric suffix, help text)
    ("window_dispatch_harvest_ms", "serve_window_dispatch_harvest_ms",
     "per-window dispatch-to-harvest wall time in ms (the device "
     "execution + host-device RTT leg the pipeline overlaps)"),
    ("window_host_ms", "serve_window_host_ms",
     "per-window host processing time in ms (emission, stops, "
     "bookkeeping — the work hidden under the next window)"),
    ("window_inflight_depth", "serve_window_inflight_depth",
     "pipeline depth observed at each window dispatch (0 = boundary "
     "dispatch, 1 = overlapped dispatch)"),
    ("spec_window_emitted_tokens", "serve_spec_window_emitted_tokens",
     "tokens a request realized from one device-resident speculative "
     "window (serving_spec_window; low buckets mean drafts are not "
     "landing and the window is mostly frozen passes)"),
    ("sched_queue_wait_ms_interactive",
     "serve_sched_queue_wait_ms_interactive",
     "admission queue wait in ms for interactive-class requests "
     "(enqueue to admit; swap residency is tracked separately)"),
    ("sched_queue_wait_ms_batch", "serve_sched_queue_wait_ms_batch",
     "admission queue wait in ms for batch-class requests "
     "(enqueue to admit; swap residency is tracked separately)"),
    ("sched_swap_residency_ms_interactive",
     "serve_sched_swap_residency_ms_interactive",
     "time preempted interactive-class requests spent swapped out to "
     "host RAM in ms (swap-out to resume)"),
    ("sched_swap_residency_ms_batch",
     "serve_sched_swap_residency_ms_batch",
     "time preempted batch-class requests spent swapped out to "
     "host RAM in ms (swap-out to resume)"),
    # Per-stage request latency split (models/serving.py, SERVING.md
    # rung 18): submit->first-token, the queue leg, and the decode leg.
    # Always on — fed from the same span boundaries tracing uses, but
    # independent of the serving_trace knob.
    ("ttft_ms", "serve_ttft_ms",
     "time to first token in ms (submit to the first emitted token, "
     "queue wait + prefill included)"),
    ("queue_ms", "serve_queue_ms",
     "admission queue wait in ms (submit to slot admission — the "
     "queue leg of the TTFT split)"),
    ("decode_ms", "serve_decode_ms",
     "admission-to-completion time in ms (the prefill + decode leg "
     "of the latency split)"),
    # Device-time attribution (SERVING.md rung 25): the device-side
    # slice of the dispatch->harvest window, timed around the forcing
    # read at each sync point. serve_window_host_ms is its host
    # complement; together they split serve_window_dispatch_harvest_ms.
    ("window_device_ms", "serve_device_ms_window",
     "device-side time per window in ms (dispatch to the forcing "
     "harvest read; the host bookkeeping half is "
     "serve_window_host_ms)"),
    ("itl_ms", "serve_itl_ms",
     "per-request mean inter-token gap in ms (first token to finish "
     "over generated tokens - 1; observed once per normal finish)"),
)


def _render_histogram(lines: list, name: str, help_text: str,
                      hist: dict) -> None:
    edges = hist.get("edges") or []
    counts = hist.get("counts") or []
    if len(counts) != len(edges) + 1:
        return  # malformed snapshot; skip rather than lie
    lines.append(f"# HELP {name} {help_text}")
    lines.append(f"# TYPE {name} histogram")
    cum = 0
    for edge, count in zip(edges, counts):
        cum += count
        lines.append(f'{name}_bucket{{le="{edge:g}"}} {cum}')
    cum += counts[-1]
    lines.append(f'{name}_bucket{{le="+Inf"}} {cum}')
    lines.append(f"{name}_sum {hist.get('sum', 0)}")
    lines.append(f"{name}_count {hist.get('count', 0)}")


def render_metrics(snapshot: dict) -> str:
    """Render a /status snapshot as Prometheus text exposition format."""
    lines = []
    for key, suffix, help_text in _METRIC_FIELDS:
        value = snapshot.get(key)
        if isinstance(value, bool):
            value = int(value)
        if value is None:
            continue
        name = f"kvedge_{suffix}"
        lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {value}")
    check = snapshot.get("check", {})
    if check.get("probe_ms") is not None:
        lines.append("# HELP kvedge_probe_ms payload probe duration")
        lines.append("# TYPE kvedge_probe_ms gauge")
        lines.append(f"kvedge_probe_ms {check['probe_ms']}")
    if check.get("device_count") is not None:
        lines.append("# HELP kvedge_devices visible accelerator devices")
        lines.append("# TYPE kvedge_devices gauge")
        lines.append(f"kvedge_devices {check['device_count']}")
    progress = snapshot.get("train_progress") or {}
    if progress.get("step") is not None:
        lines.append("# HELP kvedge_train_step last completed training step")
        lines.append("# TYPE kvedge_train_step gauge")
        lines.append(f"kvedge_train_step {progress['step']}")
    if progress.get("target_steps") is not None:
        lines.append("# HELP kvedge_train_target_steps training step target")
        lines.append("# TYPE kvedge_train_target_steps gauge")
        lines.append(f"kvedge_train_target_steps {progress['target_steps']}")
    if progress.get("loss") is not None:
        lines.append("# HELP kvedge_train_loss last training loss")
        lines.append("# TYPE kvedge_train_loss gauge")
        lines.append(f"kvedge_train_loss {progress['loss']}")
    if progress.get("ts") is not None:
        # Staleness signal: the progress file persists across pod
        # generations by design, so consumers need the write time to
        # tell a live run from one that finished long ago.
        lines.append("# HELP kvedge_train_progress_ts unix time of the "
                     "last training-progress write")
        lines.append("# TYPE kvedge_train_progress_ts gauge")
        lines.append(f"kvedge_train_progress_ts {progress['ts']}")
    serving = snapshot.get("serving") or {}
    for key, suffix, mtype, help_text in _SERVE_METRIC_FIELDS:
        value = serving.get(key)
        if value is None:
            continue
        name = f"kvedge_{suffix}"
        lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {mtype}")
        lines.append(f"{name} {value}")
    # Labelled counter (the one non-scalar serving metric): spec-window
    # fallbacks by cause. "sampled" must pin to 0 in mixed steady state
    # once serving_spec_sampled_window is on — that is rung 23's
    # acceptance gate, so the cause label is load-bearing, not garnish.
    fallbacks = serving.get("spec_window_fallbacks")
    if isinstance(fallbacks, dict) and fallbacks:
        name = "kvedge_serve_spec_window_fallbacks_total"
        lines.append(
            f"# HELP {name} decode rounds that fell off the windowed "
            "spec path, by cause (sampled = mixed batch with "
            "serving_spec_sampled_window off; spec_off = speculation "
            "disabled mid-flight; overlap_off = serial loop with "
            "spec windows configured)")
        lines.append(f"# TYPE {name} counter")
        for cause in sorted(fallbacks):
            lines.append(
                f'{name}{{cause="{cause}"}} {fallbacks[cause]}')
    # Prefix-cache evictions by cause (rung 24): admission = LRU sweep
    # to fit an arrival; pressure = mid-decode pool-relief callback;
    # revive = post-poison scrub (device bytes untrusted, never
    # demoted); host_lru / host_over = host-tier budget evictions.
    evictions = serving.get("prefix_evictions")
    if isinstance(evictions, dict) and evictions:
        name = "kvedge_serve_prefix_evictions_total"
        lines.append(
            f"# HELP {name} prefix-cache entries evicted from their "
            "tier, by cause (admission/pressure/revive = HBM "
            "entries; host_lru/host_over = host-tier records)")
        lines.append(f"# TYPE {name} counter")
        for cause in sorted(evictions):
            lines.append(
                f'{name}{{cause="{cause}"}} {evictions[cause]}')
    # Per-op broadcast attribution (rung 25): the slice transport's
    # cumulative frame count and milliseconds by op kind ({op:
    # [frames, ms]}). OP_MULTI frames show up under their own label,
    # so coalescing wins read directly as fewer frames per step.
    op_ms = serving.get("slice_op_ms")
    if isinstance(op_ms, dict) and op_ms:
        frames_name = "kvedge_serve_device_broadcast_frames_total"
        ms_name = "kvedge_serve_device_ms_broadcast_total"
        lines.append(
            f"# HELP {frames_name} control-plane broadcast frames "
            "sent to the slice pool, by op kind (multi = coalesced "
            "OP_MULTI envelopes)")
        lines.append(f"# TYPE {frames_name} counter")
        for op in sorted(op_ms):
            cell = op_ms[op]
            lines.append(f'{frames_name}{{op="{op}"}} {cell[0]}')
        lines.append(
            f"# HELP {ms_name} cumulative milliseconds spent inside "
            "slice broadcasts (send + per-shard run + gather), by op "
            "kind")
        lines.append(f"# TYPE {ms_name} counter")
        for op in sorted(op_ms):
            cell = op_ms[op]
            lines.append(f'{ms_name}{{op="{op}"}} {cell[1]:.3f}')
    for key, suffix, help_text in _SERVE_HISTOGRAM_FIELDS:
        hist = serving.get(key)
        if isinstance(hist, dict):
            _render_histogram(lines, f"kvedge_{suffix}", help_text, hist)
    return "\n".join(lines) + "\n"


class StatusServer:
    """Threaded HTTP server.

    ``snapshot`` supplies the /status document; ``healthy`` is a cheap
    in-memory check for /healthz (liveness probes hit it every few seconds,
    so it must not touch the state volume). ``health_detail``, also cheap
    and in-memory, enriches an unhealthy /healthz body — a degraded
    serving pool adds its failure reason and ``"terminal": true`` so
    probes (runtime/healthcheck.py) can stop polling a pod that will
    never recover in place. A non-empty ``token`` gates every mutating
    (POST) route behind ``Authorization: Bearer <token>``; the read-only
    GET surface is never gated.
    """

    def __init__(self, bind: str, port: int, snapshot: Callable[[], dict],
                 healthy: Callable[[], bool] | None = None,
                 profiler: Callable[[float], dict] | None = None,
                 token: str = "",
                 generator: Callable[[dict], dict] | None = None,
                 health_detail: Callable[[], dict | None] | None = None,
                 trace_doc: Callable[[], dict | None] | None = None,
                 profile_traces: Callable[[], list] | None = None,
                 slo_doc: Callable[[], dict | None] | None = None,
                 bundle_doc: Callable[[], dict | None] | None = None):
        outer = self
        self._healthy = healthy or (
            lambda: bool(snapshot().get("ok", False))
        )
        self._health_detail = health_detail
        self._profiler = profiler
        self._token = token
        self._generator = generator
        # GET /trace: the serving flight recorder as Chrome trace-event
        # JSON (runtime/tracing.py export_chrome). Returning None means
        # tracing is off -> 404 with a pointer at the knob.
        self._trace_doc = trace_doc
        # GET /profile/traces: the on-disk profiler captures under
        # <state_dir>/traces/ (runtime/profiling.py TraceCapture.list).
        self._profile_traces = profile_traces
        # GET /slo: the rolling SLI/burn-rate document (runtime/slo.py
        # SloEngine.doc). GET /debug/bundle: the flight-recorder bundle
        # assembled on demand (models/serving.py flight_bundle). Either
        # returning None means its knob is off -> 404 with a pointer.
        self._slo_doc = slo_doc
        self._bundle_doc = bundle_doc

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet by default
                pass

            def _send(self, code: int, doc: dict,
                      extra_headers: dict | None = None) -> None:
                body = json.dumps(doc, indent=2, sort_keys=True).encode()
                self._send_raw(code, body, "application/json", extra_headers)

            def _send_raw(self, code: int, body: bytes, ctype: str,
                          extra_headers: dict | None = None) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for name, value in (extra_headers or {}).items():
                    self.send_header(name, value)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/metrics":
                    self._send_raw(
                        200,
                        render_metrics(outer._snapshot()).encode(),
                        "text/plain; version=0.0.4",
                    )
                elif self.path == "/healthz":
                    healthy = outer._healthy()
                    doc = {"status": "ok" if healthy else "degraded"}
                    if not healthy and outer._health_detail is not None:
                        try:
                            doc.update(outer._health_detail() or {})
                        except Exception:
                            pass  # detail is best-effort; 503 already says it
                    self._send(200 if healthy else 503, doc)
                elif self.path == "/status":
                    self._send(200, outer._snapshot())
                elif self.path == "/version":
                    self._send(200, {"version": __version__})
                elif self.path == "/trace":
                    doc = (outer._trace_doc()
                           if outer._trace_doc is not None else None)
                    if doc is None:
                        self._send(404, {
                            "error": "tracing is off — enable [payload] "
                                     "serving_trace (on, or a sample "
                                     "rate in (0, 1])"
                        })
                    else:
                        self._send(200, doc)
                elif self.path == "/slo":
                    doc = (outer._slo_doc()
                           if outer._slo_doc is not None else None)
                    if doc is None:
                        self._send(404, {
                            "error": "SLO engine is off — enable "
                                     "[payload] serving_slo = true"
                        })
                    else:
                        self._send(200, doc)
                elif self.path == "/debug/bundle":
                    doc = (outer._bundle_doc()
                           if outer._bundle_doc is not None else None)
                    if doc is None:
                        self._send(404, {
                            "error": "flight recorder is off — enable "
                                     "[payload] serving_bundle = true"
                        })
                    else:
                        self._send(200, doc)
                elif urlsplit(self.path).path == "/profile/traces":
                    if outer._profile_traces is None:
                        self._send(503, {"error": "profiler not available"})
                    else:
                        self._send(200,
                                   {"traces": outer._profile_traces()})
                elif urlsplit(self.path).path == "/profile":
                    self._send(405, {
                        "error": "use POST /profile?seconds=N to capture"
                    })
                else:
                    self._send(404, {"error": f"no route {self.path}"})

            def _authorized(self) -> bool:
                """Bearer-token check for mutating routes.

                Constant-time comparison; an unset token leaves the POST
                surface open (dev/local use; any deployment that enables
                the LoadBalancer should set ``[status] token`` in the
                runtime config TOML — see config/runtime_config.py).
                """
                if not outer._token:
                    return True
                auth = self.headers.get("Authorization", "")
                scheme, _, presented = auth.partition(" ")
                # Compare as bytes: compare_digest on str raises TypeError
                # for non-ASCII input, and headers arrive latin-1-decoded,
                # so an attacker-supplied high byte would otherwise kill
                # the handler thread instead of getting a 401.
                return scheme.lower() == "bearer" and hmac.compare_digest(
                    presented.strip().encode("utf-8", "surrogateescape"),
                    outer._token.encode("utf-8"),
                )

            def do_POST(self):
                url = urlsplit(self.path)
                if url.path not in ("/profile", "/generate"):
                    self._send(404, {"error": f"no route {url.path}"})
                    return
                if not self._authorized():
                    self._send(
                        401,
                        {"error": f"POST {url.path} requires "
                                  "Authorization: Bearer <status token>"},
                        extra_headers={"WWW-Authenticate": "Bearer"},
                    )
                    return
                if url.path == "/generate":
                    self._handle_generate()
                    return
                if outer._profiler is None:
                    self._send(503, {"error": "profiler not available"})
                    return
                try:
                    seconds = float(
                        parse_qs(url.query).get("seconds", ["3"])[0]
                    )
                except ValueError:
                    self._send(400, {"error": "seconds must be a number"})
                    return
                try:
                    self._send(200, outer._profiler(seconds))
                except CaptureBusy as e:
                    self._send(409, {"error": str(e)})
                except CaptureUnavailable as e:
                    self._send(503, {"error": str(e)})
                except Exception as e:  # capture failed; stay serving
                    self._send(500, {"error": f"capture failed: {e!r}"})

            def _handle_generate(self):
                if outer._generator is None:
                    self._send(503, {
                        "error": "no generation backend (boot the 'serve' "
                                 "payload)"
                    })
                    return
                try:
                    length = int(self.headers.get("Content-Length", "0"))
                except ValueError:
                    length = 0
                if not 0 < length <= _MAX_GENERATE_BODY:
                    self._send(400, {
                        "error": "POST /generate needs a JSON body "
                                 f"(1..{_MAX_GENERATE_BODY} bytes)"
                    })
                    return
                try:
                    doc = json.loads(self.rfile.read(length))
                except (json.JSONDecodeError, UnicodeDecodeError) as e:
                    self._send(400, {"error": f"invalid JSON body: {e}"})
                    return
                # Caller-supplied request ID: ride it into the serving
                # layer as the reserved "_request_id" doc key (the
                # request parser ignores unknown keys; workload.py
                # sanitizes and echoes it, or mints one). The response
                # carries it both in the JSON body and as an
                # X-Request-Id header so clients correlate either way.
                rid_in = self.headers.get("X-Request-Id")
                if rid_in and isinstance(doc, dict):
                    doc.setdefault("_request_id", rid_in)
                try:
                    result = outer._generator(doc)
                except ValueError as e:  # malformed request semantics
                    self._send(400, {"error": str(e)})
                    return
                except GenerateUnavailable as e:
                    self._send(503, {"error": str(e)})
                    return
                except Exception as e:  # generation failed; stay serving
                    self._send(500, {"error": f"generate failed: {e!r}"})
                    return
                stream = (result or {}).get("_stream")
                rid_out = (result or {}).get("request_id")
                rid_headers = (
                    {"X-Request-Id": str(rid_out)} if rid_out else None
                )
                if stream is None:
                    self._send(200, result, extra_headers=rid_headers)
                    return
                # Streaming: newline-delimited JSON, one document per
                # token, end-of-body delimited by connection close
                # (HTTP/1.0 semantics — no Content-Length, no chunked
                # framing to desync on). Mid-stream failures can no
                # longer change the status code; they surface as a final
                # {"error": ...} line.
                self.send_response(200)
                self.send_header("Content-Type", "application/x-ndjson")
                for name, value in (rid_headers or {}).items():
                    self.send_header(name, value)
                self.end_headers()
                self.close_connection = True
                try:
                    for item in stream:
                        self.wfile.write(
                            (json.dumps(item) + "\n").encode()
                        )
                        self.wfile.flush()
                except BrokenPipeError:
                    # Client went away: close the stream so the serving
                    # layer cancels its rows at the next decode boundary
                    # (slots/pages free immediately instead of decoding
                    # out the reserved budgets — models/serving.py).
                    stream.close()
                except Exception as e:
                    doc = {"error": repr(e)}
                    # Multi-row streams attribute the failing row
                    # (workload.py tags it), so clients can tell a
                    # healthy row's truncation from its own failure.
                    row = getattr(e, "stream_row", None)
                    if row is not None:
                        doc["row"] = row
                    try:
                        self.wfile.write(
                            (json.dumps(doc) + "\n").encode()
                        )
                    except OSError:
                        pass

        self._snapshot = snapshot
        self._server = ThreadingHTTPServer((bind, port), Handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="kvedge-status",
            daemon=True,
        )

    @property
    def port(self) -> int:
        return self._server.server_port

    def start(self) -> None:
        self._thread.start()

    def shutdown(self) -> None:
        self._server.shutdown()
        self._server.server_close()
