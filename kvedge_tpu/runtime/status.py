"""HTTP status endpoint — the runtime's externally reachable smoke surface.

The reference's post-install verification is human: ``kubectl get vmi``
shows Running, then ssh in (``NOTES.txt:8-12``). kvedge-tpu adds a machine
surface behind the same LoadBalancer: ``/healthz`` for probes, ``/status``
for the full runtime picture (devices, mesh, heartbeat age, boot count).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable

from kvedge_tpu.version import __version__


class StatusServer:
    """Threaded HTTP server.

    ``snapshot`` supplies the /status document; ``healthy`` is a cheap
    in-memory check for /healthz (liveness probes hit it every few seconds,
    so it must not touch the state volume).
    """

    def __init__(self, bind: str, port: int, snapshot: Callable[[], dict],
                 healthy: Callable[[], bool] | None = None):
        outer = self
        self._healthy = healthy or (
            lambda: bool(snapshot().get("ok", False))
        )

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet by default
                pass

            def _send(self, code: int, doc: dict) -> None:
                body = json.dumps(doc, indent=2, sort_keys=True).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    healthy = outer._healthy()
                    self._send(200 if healthy else 503,
                               {"status": "ok" if healthy else "degraded"})
                elif self.path == "/status":
                    self._send(200, outer._snapshot())
                elif self.path == "/version":
                    self._send(200, {"version": __version__})
                else:
                    self._send(404, {"error": f"no route {self.path}"})

        self._snapshot = snapshot
        self._server = ThreadingHTTPServer((bind, port), Handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="kvedge-status",
            daemon=True,
        )

    @property
    def port(self) -> int:
        return self._server.server_port

    def start(self) -> None:
        self._thread.start()

    def shutdown(self) -> None:
        self._server.shutdown()
        self._server.server_close()
