"""Online window/spec-depth controller (SERVING.md rung 26).

The overlap pipeline's throughput law (rung 16) is

    steps/s = W / max(R, W * t)

where ``W`` is the dispatched window, ``t`` the per-step device time,
and ``R`` the per-boundary host turnaround (bookkeeping + dispatch +
harvest — everything the device window must hide). The device-resident
spec window (rung 20) obeys the same shape with the verify-pass time
``t_v`` and an emitted-tokens multiplier: ``E * W / max(R, W * t_v)``.
Both laws saturate once ``W * t >= R`` — beyond that point a larger
window buys no throughput and only adds boundary staleness (cancels,
newcomers, and checkpoints wait up to a full window). The optimal
window is therefore the SMALLEST power of two whose device time covers
the host turnaround.

This module closes the loop on those written-down models using the
rung-25 measurements the serving layer already takes at every harvest:

* ``device_ms``  — the forced device sync inside the harvest
  (``serve_device_ms_window``), giving ``t = device_ms / W``;
* ``rtt_ms``     — dispatch->harvest wall time, whose excess over
  ``device_ms`` is transport + dispatch bookkeeping;
* ``host_ms``    — post-harvest host processing
  (``serve_window_host_ms``).

``R`` is estimated as ``max(rtt_ms - device_ms, 0) + host_ms`` and
both ``R`` and ``t`` are EWMA'd so one slow boundary (a checkpoint, a
GC pause) does not whipsaw the window.

Correctness note: the window is pure SCHEDULING — the greedy argmax
and the positional ``fold_in(seed, t)`` key schedule make emitted
tokens identical for every window size (rung 16/20 exactness tests).
The controller can therefore never violate bit-identity; it only moves
work between host and device. That is also why the controller lives
OUTSIDE the lock discipline: it is plain-data, owned by the serving
loop, mutated only with the work lock held (like the journal — the
caller's lock, no locks here), and it survives ``revive()`` and slice
reformation because the server never recreates it.
"""

from __future__ import annotations

__all__ = ["pick_window", "WindowController"]


def _pow2_floor(w: int) -> int:
    return 1 if w <= 1 else 1 << (int(w).bit_length() - 1)


def pick_window(r_ms: float, t_ms: float, lo: int, hi: int) -> int:
    """Smallest power-of-two ``W`` in ``[lo, hi]`` with ``W*t >= R``.

    Pure function of the two EWMA'd measurements — the controller law,
    separated out so the convergence tests can drive it against a
    synthetic (R, t) schedule without a server. ``lo``/``hi`` are
    clamped to powers of two (floor), matching the serving layer's
    compiled-program set {1, 2, 4, ...}. Degenerate measurements
    (``t <= 0``: the device looks free) pin to ``hi`` — the largest
    window amortizes an unmeasurably-fast device best.
    """
    lo = _pow2_floor(max(1, int(lo)))
    hi = _pow2_floor(max(1, int(hi)))
    if hi < lo:
        hi = lo
    if t_ms <= 0.0:
        return hi
    w = lo
    while w < hi and w * t_ms < r_ms:
        w <<= 1
    return w


class WindowController:
    """EWMA state + the :func:`pick_window` law for one serving loop.

    One instance can drive several channels (the plain decode window
    and the spec-window depth) — each channel keeps its own (R, t)
    estimate because verify passes and decode steps have different
    per-step device costs. All methods are plain-data and called with
    the serving work lock held; the instance itself takes no locks.
    """

    __slots__ = ("lo", "hi", "alpha", "_r", "_t", "_updates")

    def __init__(self, lo: int = 1, hi: int = 256,
                 alpha: float = 0.2):
        if not (0.0 < alpha <= 1.0):
            raise ValueError("alpha must be in (0, 1]")
        self.lo = _pow2_floor(max(1, int(lo)))
        self.hi = _pow2_floor(max(1, int(hi)))
        if self.hi < self.lo:
            raise ValueError("window bounds inverted: "
                             f"[{lo}, {hi}]")
        self.alpha = float(alpha)
        self._r: dict[str, float] = {}
        self._t: dict[str, float] = {}
        self._updates: dict[str, int] = {}

    def observe(self, *, rtt_ms: float, device_ms: float,
                host_ms: float, window: int,
                channel: str = "decode") -> None:
        """Feed one harvested window's measurements (lock held).

        ``window`` is the size that was actually dispatched — the
        per-step device time is ``device_ms / window``. The first
        observation seeds the EWMAs directly (no warm-up bias toward
        zero)."""
        if window <= 0:
            return
        r = max(float(rtt_ms) - float(device_ms), 0.0) + float(host_ms)
        t = max(float(device_ms), 0.0) / float(window)
        a = self.alpha
        if channel in self._updates:
            self._r[channel] += a * (r - self._r[channel])
            self._t[channel] += a * (t - self._t[channel])
            self._updates[channel] += 1
        else:
            self._r[channel] = r
            self._t[channel] = t
            self._updates[channel] = 1

    def window(self, channel: str = "decode",
               default: int | None = None) -> int:
        """Current recommendation: :func:`pick_window` on the EWMAs.
        Before the first observation returns ``default`` (clamped) —
        the operator's static seed — or ``hi`` when none given."""
        if channel not in self._updates:
            if default is None:
                return self.hi
            return max(self.lo, min(self.hi,
                                    _pow2_floor(max(1, default))))
        return pick_window(self._r[channel], self._t[channel],
                           self.lo, self.hi)

    def snapshot(self, channel: str = "decode") -> dict:
        """Plain-dict state for /status + the flight recorder."""
        return {
            "window": self.window(channel),
            "r_ms": self._r.get(channel, 0.0),
            "t_ms": self._t.get(channel, 0.0),
            "updates": self._updates.get(channel, 0),
            "lo": self.lo,
            "hi": self.hi,
        }
