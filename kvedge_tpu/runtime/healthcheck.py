"""Polling health probe: the payload of the ``helm test`` hook pod.

The reference's post-install verification is entirely manual —
``kubectl get vmi`` then ssh in (reference ``NOTES.txt:8-12``; SURVEY.md
§4 "no helm test hooks"). kvedge-tpu's chart ships a test-hook Pod
(``helm test <release>``) that runs this module from inside the cluster:
poll the runtime's ``/healthz`` until it answers 200 (payload check
passed) or a deadline expires. Polling rather than a single probe
because ``helm test`` is typically run right after install, while the
runtime may still be compiling its first payload or waiting for
multi-host peers — the status server serves 503 until boot completes.

Usable standalone against any deployment:

    python -m kvedge_tpu.runtime.healthcheck http://<ip>:8476/healthz
"""

from __future__ import annotations

import argparse
import sys
import time
import urllib.error
import urllib.request


def wait_healthy(url: str, deadline_s: float = 240.0,
                 interval_s: float = 5.0) -> tuple[bool, str]:
    """Poll ``url`` until HTTP 200 or deadline. Returns (ok, last_detail)."""
    deadline = time.monotonic() + deadline_s
    detail = "no attempt made"
    while True:
        try:
            with urllib.request.urlopen(url, timeout=10) as resp:
                return True, f"HTTP {resp.status}"
        except urllib.error.HTTPError as e:
            # 503 = runtime up but degraded/booting; keep polling.
            detail = f"HTTP {e.code}: {e.read().decode(errors='replace')!r}"
        except Exception as e:  # DNS not yet registered, conn refused, ...
            detail = f"{type(e).__name__}: {e}"
        if time.monotonic() >= deadline:
            return False, detail
        time.sleep(min(interval_s, max(0.0, deadline - time.monotonic())))


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="kvedge-healthcheck",
        description="Poll a kvedge runtime /healthz until healthy.",
    )
    parser.add_argument("url")
    parser.add_argument("--deadline", type=float, default=240.0,
                        help="seconds to keep polling (default 240)")
    parser.add_argument("--interval", type=float, default=5.0,
                        help="seconds between attempts (default 5)")
    args = parser.parse_args(argv)
    ok, detail = wait_healthy(args.url, args.deadline, args.interval)
    print(f"[kvedge-healthcheck] {args.url}: "
          f"{'healthy' if ok else 'NOT healthy'} ({detail})", flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
