"""Polling health probe: the payload of the ``helm test`` hook pod.

The reference's post-install verification is entirely manual —
``kubectl get vmi`` then ssh in (reference ``NOTES.txt:8-12``; SURVEY.md
§4 "no helm test hooks"). kvedge-tpu's chart ships a test-hook Pod
(``helm test <release>``) that runs this module from inside the cluster:
poll the runtime's ``/healthz`` until it answers 200 (payload check
passed) or a deadline expires. Polling rather than a single probe
because ``helm test`` is typically run right after install, while the
runtime may still be compiling its first payload or waiting for
multi-host peers — the status server serves 503 until boot completes.

One 503 is *not* worth polling out: a poisoned serving pool that has
exhausted (or never had) in-process recovery marks its /healthz body
``"terminal": true`` because it only recovers by rescheduling — the
probe fails fast so the operator (or CI) learns in seconds, not after
the full deadline. A pool the recovery supervisor is actively healing
(runtime/recovery.py) answers 503 NON-terminal with ``"recovering":
true`` and a retry-after hint, and the probe rightly keeps polling:
healthy may be seconds away.

Usable standalone against any deployment:

    python -m kvedge_tpu.runtime.healthcheck http://<ip>:8476/healthz
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.error
import urllib.request


def wait_healthy(url: str, deadline_s: float = 240.0,
                 interval_s: float = 5.0) -> tuple[bool, str]:
    """Poll ``url`` until HTTP 200 or deadline. Returns (ok, last_detail).

    A 503 whose JSON body carries ``"terminal": true`` (a poisoned
    serving pool past recovery — boot.py's health_detail) returns
    failure immediately: that state never clears without a reschedule,
    so continuing to poll would only delay the verdict. A non-terminal
    503 — booting, or ``"recovering": true`` while the recovery
    supervisor heals the pool in place — keeps polling to the deadline.
    """
    deadline = time.monotonic() + deadline_s
    detail = "no attempt made"
    while True:
        try:
            with urllib.request.urlopen(url, timeout=10) as resp:
                return True, f"HTTP {resp.status}"
        except urllib.error.HTTPError as e:
            # 503 = runtime up but degraded/booting; keep polling unless
            # the body says the degradation is terminal.
            body = e.read().decode(errors="replace")
            detail = f"HTTP {e.code}: {body!r}"
            try:
                doc = json.loads(body)
            except ValueError:
                doc = {}
            if isinstance(doc, dict) and doc.get("terminal"):
                return False, detail
        except Exception as e:  # DNS not yet registered, conn refused, ...
            detail = f"{type(e).__name__}: {e}"
        if time.monotonic() >= deadline:
            return False, detail
        time.sleep(min(interval_s, max(0.0, deadline - time.monotonic())))


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="kvedge-healthcheck",
        description="Poll a kvedge runtime /healthz until healthy.",
    )
    parser.add_argument("url")
    parser.add_argument("--deadline", type=float, default=240.0,
                        help="seconds to keep polling (default 240)")
    parser.add_argument("--interval", type=float, default=5.0,
                        help="seconds between attempts (default 5)")
    args = parser.parse_args(argv)
    ok, detail = wait_healthy(args.url, args.deadline, args.interval)
    print(f"[kvedge-healthcheck] {args.url}: "
          f"{'healthy' if ok else 'NOT healthy'} ({detail})", flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
