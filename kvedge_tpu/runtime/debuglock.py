"""Ownership-asserting locks — the runtime half of lock discipline.

:mod:`kvedge_tpu.analysis.locklint` (SERVING.md rung 19) proves the
``*_locked`` contract statically; this module *executes* it. A
:class:`DebugLock` is a drop-in ``threading.Lock`` that remembers
which thread holds it, and :func:`instrument_locked_methods` wraps an
object's bound ``*_locked`` methods so every call asserts ownership at
runtime — the exact L1 rule, checked live under the tier-1 suite when
the ``serving_debug_locks`` knob is on.

Why a wrapper and not ``threading.RLock``: an RLock would *hide* the
bug locklint's L1 relock rule exists to catch (re-acquisition inside a
locked context), and its ownership is not introspectable. DebugLock
keeps plain-Lock semantics — a re-acquire by the owning thread
deadlocks in production and raises :class:`LockDisciplineError`
eagerly here — while exposing ``_is_owned()``.

``_is_owned`` is the load-bearing method: CPython's
``threading.Condition.__init__`` adopts ``acquire``/``release``/
``_is_owned`` from the lock it wraps (a documented duck-typing seam),
so a ``Condition(DebugLock())`` — the server's ``_work`` condition and
every per-ticket condition the scheduler makes — gets thread-accurate
``wait()``/``notify()`` ownership checks for free. A plain Lock's
Condition can only probe "is it locked at all"; ours answers "does
*this thread* hold it", which is the actual contract.

Zero cost when off: the knob default constructs ``threading.Lock``;
nothing here imports jax.
"""

from __future__ import annotations

import functools
import threading


class LockDisciplineError(AssertionError):
    """A ``*_locked`` contract violation caught at runtime.

    Subclasses AssertionError deliberately: this is an invariant
    breach in the calling code, never an operational condition to
    retry, so it must not be swallowed by handlers catching the
    runtime's typed :class:`ServingFailure` hierarchy.
    """


class DebugLock:
    """``threading.Lock`` semantics plus an introspectable owner.

    Non-reentrant like the real thing — but an owner re-acquiring
    raises :class:`LockDisciplineError` immediately instead of
    deadlocking silently (the dynamic twin of locklint's L1 relock
    finding).
    """

    def __init__(self) -> None:
        self._inner = threading.Lock()
        self._owner: int | None = None

    def acquire(self, blocking: bool = True,
                timeout: float = -1) -> bool:
        me = threading.get_ident()
        if self._owner == me:
            raise LockDisciplineError(
                "re-acquiring a non-reentrant lock already held by "
                "this thread: guaranteed self-deadlock (locklint L1 "
                "relock)"
            )
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._owner = me
        return got

    def release(self) -> None:
        if self._owner != threading.get_ident():
            raise LockDisciplineError(
                "releasing a lock this thread does not hold"
            )
        self._owner = None
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def _is_owned(self) -> bool:
        """Condition protocol: does the CURRENT thread hold the lock?"""
        return self._owner == threading.get_ident()

    def assert_held(self, what: str = "") -> None:
        if not self._is_owned():
            label = f" `{what}`" if what else ""
            raise LockDisciplineError(
                f"lock-discipline violation{label}: caller does not "
                f"hold the lock (the *_locked contract — see "
                f"SERVING.md rung 19)"
            )

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        state = (f"held by {self._owner}" if self._owner is not None
                 else "unlocked")
        return f"<DebugLock {state}>"


class DebugCondition(threading.Condition):
    """A Condition that insists on an ownership-introspectable lock.

    Plain ``threading.Condition(DebugLock())`` already inherits the
    thread-accurate checks (see module docstring); this subclass only
    exists to fail fast when handed a lock that cannot report
    ownership, and to carry ``assert_held`` through to the lock.
    """

    def __init__(self, lock: DebugLock | None = None) -> None:
        if lock is None:
            lock = DebugLock()
        if not hasattr(lock, "_is_owned"):
            raise TypeError(
                "DebugCondition requires an ownership-introspectable "
                "lock (DebugLock or RLock-like)"
            )
        super().__init__(lock)

    def assert_held(self, what: str = "") -> None:
        assert_held(self._lock, what)


def make_lock(debug: bool = False):
    """The knob seam: a DebugLock when asserting, a real Lock when not."""
    return DebugLock() if debug else threading.Lock()


def make_condition(lock) -> threading.Condition:
    return threading.Condition(lock)


def assert_held(lock, what: str = "") -> None:
    """Assert ownership on any lock that can answer; no-op otherwise.

    Call sites stay unconditional — against a plain ``threading.Lock``
    (no ``_is_owned``, no owner concept) this degrades to nothing, so
    production pays zero and debug mode pays one attribute probe.
    """
    probe = getattr(lock, "assert_held", None)
    if probe is not None:
        probe(what)
        return
    owned = getattr(lock, "_is_owned", None)
    if owned is not None and not owned():
        label = f" `{what}`" if what else ""
        raise LockDisciplineError(
            f"lock-discipline violation{label}: caller does not hold "
            f"the lock"
        )


def instrument_locked_methods(obj, lock) -> int:
    """Wrap ``obj``'s bound ``*_locked`` methods to assert ownership.

    Instance-level setattr — the class is untouched, so two servers
    can run with and without assertions in one process. Returns the
    number of methods wrapped (so callers/tests can assert the
    contract surface is nonempty).
    """
    wrapped = 0
    for name in dir(type(obj)):
        if not name.endswith("_locked") or name.startswith("__"):
            continue
        fn = getattr(obj, name, None)
        if not callable(fn):
            continue

        def _make(fn, name):
            @functools.wraps(fn)
            def checked(*args, **kwargs):
                assert_held(lock, name)
                return fn(*args, **kwargs)
            return checked

        setattr(obj, name, _make(fn, name))
        wrapped += 1
    return wrapped
