"""Bounded host-side journal of resumable in-flight request state.

The serving stack's recovery story (runtime/recovery.py, rung 15) used
to guarantee bit-identical tokens only for requests *re-submitted*
after revive/reformation — poison failed every in-flight request. This
module is the durability half of rung 22: at quiescent boundaries the
decode loop checkpoints each live request's resumable state here — KV
pages as the verbatim host bytes ``kvcache.swapout_pages`` already
produces (including int8 scale slabs), the emitted-token log, the
sampler key, budgets, and the scheduler ticket — so ``revive()`` can
re-admit the journaled requests into fresh slots instead of failing
them, resuming decode from the checkpointed offset bit-identically.

Design constraints:

* **Dumb container, one owner.** Every method is called with the
  serving work lock held (the journal lives inside the server's
  single-lock discipline — locklint's L1/L4 apply to the caller, not
  here). The journal itself takes no locks and runs no device ops.
* **Bounded.** ``max_bytes`` caps the sum of checkpointed KV bytes
  (0 = unbounded). A ``put`` that would blow the budget is refused —
  the caller counts it as a skipped checkpoint and the request simply
  keeps its previous (older but internally consistent) entry, or none.
* **Per-request transactional.** ``put`` replaces the request's entry
  atomically w.r.t. the budget: the old entry's bytes are released
  before the new entry is admitted, so a mid-checkpoint fault leaves a
  mix of newer/older entries, each individually resumable.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Hashable


@dataclass
class JournalEntry:
    """One request's resumable state, as of a quiescent boundary.

    ``saved_len`` is ``len(prompt) + gen_len`` — the KV pool holds
    positions ``0..saved_len-1`` and ``next_token`` is the pending
    token to feed at position ``saved_len`` (exactly the preempt/resume
    contract of rung 17). ``arrays`` are the verbatim host pages from
    ``swapout_pages`` covering the first ``ceil(saved_len/page_size)``
    pages of the slot; ``emitted`` is the count of tokens delivered to
    the client's stream at checkpoint time (the exactly-once watermark
    — regenerated tokens below it are suppressed on resume).
    """

    req: Any
    pclass: str
    ticket_no: int
    admit_seq: int
    pages_reserved: int
    saved_len: int
    gen_len: int
    next_token: int
    emitted: int
    arrays: tuple = field(repr=False)
    nbytes: int = 0
    # Prefix refcounting (rung 24): a request whose table starts on
    # cached-prefix pages journals a REFERENCE to the shared bytes —
    # ``prefix_node`` is the trie node id whose shadow snapshot holds
    # the first ``prefix_pages_n`` pages (``prefix_tokens`` prompt
    # tokens), and ``arrays``/``nbytes`` then cover only the request's
    # OWN pages. None = self-contained full-bytes entry (the pre-rung
    # format; also the fallback when the shadow would blow the budget).
    prefix_node: int | None = None
    prefix_pages_n: int = 0
    prefix_tokens: int = 0


class RequestJournal:
    """request -> JournalEntry map with a byte budget. The key is any
    hashable request identity (the serving layer uses its ``_Request``
    object itself — request IDs can be absent or duplicated, the live
    object cannot). Caller holds the lock."""

    def __init__(self, max_bytes: int = 0):
        if max_bytes < 0:
            raise ValueError("max_bytes must be >= 0")
        self.max_bytes = int(max_bytes)
        self._entries: dict[Hashable, JournalEntry] = {}
        self._nbytes = 0
        self._extra = 0
        # Entry-drop observer (rung 24): called for each entry that
        # leaves the journal via replacement (``put`` over an old
        # entry) or ``pop`` — NOT via ``take_all``, whose caller takes
        # ownership of the drained entries and settles their prefix
        # references itself after restore. The serving layer hangs its
        # shadow-store refcount decrement here so a dropped reference
        # can release the shared bytes it billed.
        self.on_drop = None

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, key: Hashable) -> bool:
        return key in self._entries

    @property
    def nbytes(self) -> int:
        return self._nbytes + self._extra

    @property
    def extra_bytes(self) -> int:
        return self._extra

    def adjust_extra(self, delta: int) -> None:
        """Bill (or release, negative) out-of-entry bytes against the
        budget — the shared prefix shadow snapshots, which back many
        entries but must count ONCE. The caller adjusts at shadow
        create/drop; ``put`` prices new entries against the total."""
        self._extra += int(delta)
        if self._extra < 0:
            raise ValueError("journal extra bytes went negative")

    def get(self, key: Hashable) -> JournalEntry | None:
        return self._entries.get(key)

    def put(self, key: Hashable, entry: JournalEntry,
            extra: int = 0) -> bool:
        """Replace ``key``'s entry. False (and no change) on budget.
        ``extra`` prices shadow bytes this entry would NEWLY pin (0
        when the shadow already exists); on success the caller then
        bills them via :meth:`adjust_extra`."""
        old = self._entries.get(key)
        freed = old.nbytes if old is not None else 0
        if self.max_bytes and (self._nbytes + self._extra - freed
                               + entry.nbytes + extra > self.max_bytes):
            return False
        self._nbytes += entry.nbytes - freed
        self._entries[key] = entry
        if old is not None and self.on_drop is not None:
            self.on_drop(old)
        return True

    def pop(self, key: Hashable) -> JournalEntry | None:
        entry = self._entries.pop(key, None)
        if entry is not None:
            self._nbytes -= entry.nbytes
            if self.on_drop is not None:
                self.on_drop(entry)
        return entry

    def take_all(self) -> list[JournalEntry]:
        """Drain every entry, oldest ticket first (admission order).
        Ownership transfers: ``on_drop`` does NOT fire — the caller
        settles each entry's prefix references after restoring it."""
        entries = sorted(self._entries.values(),
                         key=lambda e: (e.admit_seq, e.ticket_no))
        self._entries.clear()
        self._nbytes = 0
        return entries

    def clear(self) -> None:
        self._entries.clear()
        self._nbytes = 0
