"""Request-scoped tracing: span timelines, a flight recorder, Perfetto export.

The status surface this repo grew (/status, /metrics, POST /profile) is
all *aggregates* — until now there were no request IDs anywhere in the
codebase, so when an interactive request blew its p99 there was no way
to attribute the time to queue wait vs prefill vs window dispatch vs a
preemptive swap vs a slow slice follower. This module is the missing
attribution layer, in the same spirit as the device-level story
``jax.profiler`` already tells in runtime/profiling.py — but for the
HOST side of serving: the scheduler, the decode loop, the slice op
stream, and the failure/recovery machinery.

Design constraints (SERVING.md rung 18):

* **Lock-cheap.** Spans are recorded from under the server's ONE work
  lock (SERVING.md invariant 5) and from the decode loop's hot path.
  A record is ONE ``deque.append`` of a plain tuple — appends on a
  bounded deque are atomic under the GIL, so the recorder takes no
  lock of its own and never wakes anything. The uncontended-admit
  timing contract (serving.py) is preserved: tracing adds O(1) host
  work and zero notifies.
* **Bounded.** The buffer is a fixed-size ring (the **flight
  recorder**): the newest ``capacity`` events win, the oldest fall
  off. ``dropped`` counts what fell off. On pool poison the last N
  events are embedded in the ``last-failure.json`` post-mortem
  (runtime/workload.py), so a crash ships its own timeline.
* **Monotonic clocks.** Every stamp is ``time.perf_counter()`` —
  wall-clock steps (NTP) cannot reorder a timeline. Export rebases on
  the tracer's epoch so Chrome/Perfetto sees small positive
  microsecond stamps.
* **Deterministic sampling.** The ``serving_trace`` knob is
  off / on / a sample rate in (0, 1]. The sampling decision is a pure
  hash of the request ID, made ONCE at ingress — all spans of one
  request share fate, and a caller-supplied ``X-Request-Id`` yields
  the same decision on every pod. Global (non-request) spans — window
  timing, slice ops, failure/recovery events — always record when the
  tracer is enabled: they are the fabric the sampled request spans
  hang from.
* **Zero effect on tokens.** The tracer never touches device state,
  never sleeps, never raises into the serving path; tracing on vs off
  is token-bit-identical (pinned by tests/test_tracing.py) and the
  tracer object survives ``revive()`` and slice reformation unchanged
  (it holds no device or thread state).

Export targets:

* ``GET /trace`` (runtime/status.py) returns
  :meth:`Tracer.export_chrome` — Chrome trace-event JSON, loadable in
  Perfetto / ``chrome://tracing`` next to the XProf captures.
* ``/metrics`` per-stage histograms (``serve_ttft_ms`` and the
  queue-vs-decode split) are fed by models/serving.py from the same
  span boundaries.
* ``last-failure.json`` embeds :meth:`Tracer.last_events`.
"""

from __future__ import annotations

import collections
import time
import uuid
import zlib

# Record layout (plain tuple — cheap to build under the work lock):
#   (ph, t0, dur, name, cat, rid, args)
# ph is "X" (complete span, dur in seconds) or "i" (instant, dur 0.0).
# rid is "" for global events; args is a small JSON-safe dict or None.

# Flight-recorder tail embedded in the last-failure.json post-mortem.
POSTMORTEM_EVENTS = 64

# Request-id hygiene: caller-supplied X-Request-Id values ride into
# logs, JSON and trace exports; cap length and restrict the alphabet so
# a hostile header cannot smuggle structure anywhere downstream.
_RID_MAX_LEN = 64
_RID_OK = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789-_.:"
)


def new_request_id() -> str:
    """Mint a request ID at HTTP ingress (workload.py). Random, not
    sequential: IDs must not collide across pods behind one
    LoadBalancer, and must not leak request volume."""
    return "req-" + uuid.uuid4().hex[:16]


def clean_request_id(raw) -> str:
    """A caller-supplied request ID, sanitized; "" when unusable."""
    if not isinstance(raw, str) or not raw:
        return ""
    rid = raw[:_RID_MAX_LEN]
    if all(c in _RID_OK for c in rid):
        return rid
    return ""


class Tracer:
    """A lock-cheap, bounded span recorder (the flight recorder).

    One instance per serving pool, shared by reference with the
    scheduler, the cache (slice op stream) and the recovery machinery.
    All methods are safe to call from any thread without additional
    locking: the only mutation is an append on a bounded deque (atomic
    under the GIL) and a few monotonically-increasing counters whose
    races are benign (observability, not accounting).
    """

    def __init__(self, sample: float = 1.0, capacity: int = 4096):
        self.sample = float(sample)
        self.capacity = int(capacity)
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        self._appended = 0
        self.epoch = time.perf_counter()
        # Counter-track source (SERVING.md rung 25): a callable
        # ``epoch -> [event dict]`` returning fully-formed Chrome
        # counter events (ph="C") to merge into export_chrome — the
        # serving layer hangs its occupancy timeline ring here
        # (runtime/slo.py OccupancyRing.chrome_counters) so Perfetto
        # draws HBM/page/bucket occupancy under the span timeline.
        # None = no counter tracks; export is unchanged.
        self.counter_source = None

    # ---- construction from the config knob -------------------------------

    @staticmethod
    def from_knob(value, capacity: int = 4096) -> "Tracer | None":
        """``serving_trace`` (off / on / rate in (0,1]) -> a tracer or
        None. None is the off state: every call site guards with
        ``if tracer is not None`` so off costs one attribute read."""
        if value in ("off", "", None, False):
            return None
        if value in ("on", True):
            return Tracer(sample=1.0, capacity=capacity)
        rate = float(value)
        if not (0.0 < rate <= 1.0):
            raise ValueError(
                f"serving_trace sample rate must be in (0, 1], got {rate!r}"
            )
        return Tracer(sample=rate, capacity=capacity)

    # ---- recording --------------------------------------------------------

    @staticmethod
    def now() -> float:
        return time.perf_counter()

    def sampled(self, rid: str) -> bool:
        """Deterministic per-request sampling decision: a pure hash of
        the ID, so all spans of one request share fate and a replayed
        ``X-Request-Id`` traces (or not) identically everywhere."""
        if self.sample >= 1.0:
            return True
        bucket = zlib.crc32(rid.encode("utf-8", "replace")) % 10_000
        return bucket < int(self.sample * 10_000)

    def span(self, name: str, cat: str, t0: float, t1: float | None = None,
             rid: str = "", args: dict | None = None) -> None:
        """Record a complete span [t0, t1] (tracer clock)."""
        if t1 is None:
            t1 = time.perf_counter()
        self._ring.append(("X", t0, max(0.0, t1 - t0), name, cat, rid, args))
        self._appended += 1

    def event(self, name: str, cat: str, rid: str = "",
              args: dict | None = None) -> None:
        """Record an instant event at now()."""
        self._ring.append(
            ("i", time.perf_counter(), 0.0, name, cat, rid, args)
        )
        self._appended += 1

    # ---- introspection ----------------------------------------------------

    def __len__(self) -> int:
        return len(self._ring)

    @property
    def dropped(self) -> int:
        """Events that fell off the ring (flight-recorder overwrite)."""
        return max(0, self._appended - len(self._ring))

    def stats(self) -> dict:
        return {
            "trace_events": len(self._ring),
            "trace_events_total": self._appended,
            "trace_dropped_total": self.dropped,
            "trace_sample": self.sample,
        }

    def _snapshot(self) -> list:
        """A consistent copy of the ring. deque iteration can raise
        RuntimeError if a writer appends concurrently; retry a few
        times, then settle for list() (which copies atomically enough
        for observability purposes)."""
        for _ in range(4):
            try:
                return list(self._ring)
            except RuntimeError:
                continue
        return list(self._ring)

    def last_events(self, n: int = POSTMORTEM_EVENTS) -> list[dict]:
        """The newest ``n`` events as JSON-safe dicts, oldest first —
        the post-mortem embed for ``last-failure.json``."""
        out = []
        for ph, t0, dur, name, cat, rid, args in self._snapshot()[-n:]:
            doc = {
                "name": name,
                "cat": cat,
                "t_ms": round((t0 - self.epoch) * 1000.0, 3),
            }
            if ph == "X":
                doc["dur_ms"] = round(dur * 1000.0, 3)
            if rid:
                doc["rid"] = rid
            if args:
                doc["args"] = args
            out.append(doc)
        return out

    # ---- Chrome/Perfetto export -------------------------------------------

    def export_chrome(self) -> dict:
        """The ring as Chrome trace-event JSON (``GET /trace``).

        One process (pid 1), one track (tid) per span category, named
        with ph="M" thread_name metadata so Perfetto labels the rows.
        Timestamps are microseconds from the tracer's epoch (perf
        counter — monotonic, so the timeline cannot fold)."""
        tids: dict[str, int] = {}
        events = []
        for ph, t0, dur, name, cat, rid, args in self._snapshot():
            tid = tids.get(cat)
            if tid is None:
                tid = tids[cat] = len(tids) + 1
            ev = {
                "name": name,
                "cat": cat,
                "ph": ph,
                "ts": round((t0 - self.epoch) * 1e6, 1),
                "pid": 1,
                "tid": tid,
            }
            if ph == "X":
                ev["dur"] = round(dur * 1e6, 1)
            else:
                ev["s"] = "t"  # instant scope: thread
            a = dict(args) if args else {}
            if rid:
                a["rid"] = rid
            if a:
                ev["args"] = a
            events.append(ev)
        if self.counter_source is not None:
            # Occupancy counter tracks (ph="C", rung 25). Best-effort:
            # a broken source must never take /trace down with it.
            try:
                events.extend(self.counter_source(self.epoch) or [])
            except Exception:
                pass
        meta = [
            {
                "name": "thread_name",
                "ph": "M",
                "pid": 1,
                "tid": tid,
                "args": {"name": cat},
            }
            for cat, tid in sorted(tids.items(), key=lambda kv: kv[1])
        ]
        return {
            "traceEvents": meta + events,
            "displayTimeUnit": "ms",
            "otherData": {
                "recorder": "kvedge-tpu flight recorder",
                "dropped": self.dropped,
                "sample": self.sample,
            },
        }
