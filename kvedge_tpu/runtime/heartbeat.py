"""Durable heartbeats in the PVC-backed state directory.

The reference's persistence capability: EdgeHub message state survives VM
rescheduling because the boot disk is PVC-backed (``README.md:77,88``).
kvedge-tpu proves the same property observably: the runtime writes heartbeat
records (with a monotonically increasing ``boot_count``) through the PVC
mount, so after a node failure and reschedule the new pod's heartbeat shows
``boot_count`` incremented rather than reset — state survived.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable

HEARTBEAT_FILE = "heartbeat.json"

# Written by the native PID-1 supervisor (native/kvedge-init.cc) when the
# pod command wraps the entrypoint with it; one JSON object per lifecycle
# event, appended across pod generations. This module owns the filename so
# the renderer (which wires the supervisor's --events flag) and the status
# server (which tails the file) cannot drift.
INIT_EVENTS_FILE = "init-events.jsonl"
INIT_EVENTS_TAIL = 20
# The file is append-only and never truncated; /status must stay O(1) no
# matter how long a crash-loop history the volume carries, so only this
# many trailing bytes are ever read.
_INIT_EVENTS_READ_BYTES = 64 * 1024


def read_init_events(state_dir: str, tail: int = INIT_EVENTS_TAIL) -> list:
    """Last ``tail`` supervisor events, oldest first ([] if never written).

    Reads a bounded tail of the file and skips unparseable lines rather
    than failing: the first line of the window is usually cut mid-record,
    and a crash can truncate the final line mid-write.
    """
    path = os.path.join(state_dir, INIT_EVENTS_FILE)
    try:
        with open(path, "rb") as fh:
            fh.seek(0, os.SEEK_END)
            size = fh.tell()
            fh.seek(max(0, size - _INIT_EVENTS_READ_BYTES))
            window = fh.read().decode("utf-8", errors="replace")
    except OSError:
        return []
    events = []
    for line in window.splitlines()[-tail:]:
        try:
            events.append(json.loads(line))
        except ValueError:
            continue
    return events


def append_init_event(state_dir: str, doc: dict) -> dict:
    """Append one lifecycle event to ``init-events.jsonl``, stamped with
    ts and the current boot_count.

    The native PID-1 supervisor is the file's primary author; the
    in-process recovery supervisor (runtime/recovery.py) appends its
    own outcomes here so its crash-loop breaker shares the same
    cross-generation memory. Append-only single-line writes are atomic
    enough for the tail reader above (a torn final line is skipped).
    """
    os.makedirs(state_dir, exist_ok=True)
    record = dict(doc)
    record["ts"] = time.time()
    record.setdefault("boot_count", int(
        (read_heartbeat(state_dir) or {}).get("boot_count", 0)
    ))
    path = os.path.join(state_dir, INIT_EVENTS_FILE)
    with open(path, "a", encoding="utf-8") as fh:
        fh.write(json.dumps(record) + "\n")
    return record


def _read_json_doc(path: str) -> dict | None:
    """One JSON object from ``path``, or None if absent/corrupt."""
    try:
        with open(path, "r", encoding="utf-8") as fh:
            doc = json.load(fh)
    except (OSError, json.JSONDecodeError):
        return None
    return doc if isinstance(doc, dict) else None


def _write_json_atomic(path: str, doc: dict, **dump_kwargs) -> None:
    """tmp + os.replace so readers never observe a half-written file."""
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, **dump_kwargs)
    os.replace(tmp, path)


def read_heartbeat(state_dir: str) -> dict | None:
    """Read the last heartbeat, or None if absent/corrupt (fresh volume)."""
    return _read_json_doc(os.path.join(state_dir, HEARTBEAT_FILE))


# Live progress of the `train` payload, written after every step and
# read back into /status — without it a long training run looks like
# "booting" until it finishes. On the PVC, so the last known step/loss
# also survives a crash for post-mortems and the next generation's
# /status shows where its predecessor got to.
TRAIN_PROGRESS_FILE = "train-progress.json"


def write_train_progress(state_dir: str, doc: dict) -> None:
    """Atomically persist the latest training progress document."""
    os.makedirs(state_dir, exist_ok=True)
    _write_json_atomic(os.path.join(state_dir, TRAIN_PROGRESS_FILE), doc)


def read_train_progress(state_dir: str) -> dict | None:
    """The last persisted progress, or None (absent/corrupt/not training)."""
    return _read_json_doc(os.path.join(state_dir, TRAIN_PROGRESS_FILE))


# Post-mortem record of the last serving-path failure (typed taxonomy,
# runtime/failures.py): written when the serving pool degrades, read
# back into /status by boot.snapshot(). On the PVC so the REPLACEMENT
# pod — the whole point of degrading is to be rescheduled — can report
# why its predecessor died, the same boot_count-style continuity the
# heartbeat itself proves.
FAILURE_FILE = "last-failure.json"


def write_failure_record(state_dir: str, doc: dict) -> dict:
    """Atomically persist a failure record, stamped with ts and the
    current boot_count (the generation that failed)."""
    os.makedirs(state_dir, exist_ok=True)
    record = dict(doc)
    record["ts"] = time.time()
    record["boot_count"] = int(
        (read_heartbeat(state_dir) or {}).get("boot_count", 0)
    )
    _write_json_atomic(
        os.path.join(state_dir, FAILURE_FILE), record,
        indent=2, sort_keys=True,
    )
    return record


def read_failure_record(state_dir: str) -> dict | None:
    """The last persisted failure, or None (absent/corrupt/never failed)."""
    return _read_json_doc(os.path.join(state_dir, FAILURE_FILE))


# Flight-recorder bundle (SERVING.md rung 25): the full post-mortem
# document — metrics snapshot, SLO/burn state, occupancy timeline
# tail, journal summary, page books, config fingerprint, trace tail —
# written next to last-failure.json when [payload] serving_bundle is
# on. The failure record stays the small human-first summary; the
# bundle is the machine-complete one a tool (or the chaos harness's
# completeness invariant) consumes.
BUNDLE_FILE = "flight-bundle.json"


def write_flight_bundle(state_dir: str, doc: dict) -> dict:
    """Atomically persist a flight-recorder bundle, stamped with ts
    and the current boot_count like the failure record it rides with."""
    os.makedirs(state_dir, exist_ok=True)
    record = dict(doc)
    record["ts"] = time.time()
    record["boot_count"] = int(
        (read_heartbeat(state_dir) or {}).get("boot_count", 0)
    )
    _write_json_atomic(
        os.path.join(state_dir, BUNDLE_FILE), record,
        indent=2, sort_keys=True,
    )
    return record


def read_flight_bundle(state_dir: str) -> dict | None:
    """The last persisted bundle, or None (absent/corrupt/knob off)."""
    return _read_json_doc(os.path.join(state_dir, BUNDLE_FILE))


def write_heartbeat(state_dir: str, payload: dict) -> dict:
    """Atomically write a heartbeat, advancing seq and preserving boot_count."""
    os.makedirs(state_dir, exist_ok=True)
    previous = read_heartbeat(state_dir) or {}
    doc = dict(payload)
    doc["ts"] = time.time()
    doc["seq"] = int(previous.get("seq", 0)) + 1
    doc.setdefault("boot_count", int(previous.get("boot_count", 0)))
    _write_json_atomic(
        os.path.join(state_dir, HEARTBEAT_FILE), doc,
        indent=2, sort_keys=True,
    )
    return doc


def next_boot_count(state_dir: str) -> int:
    """The boot counter for a (re)starting runtime: persisted count + 1."""
    previous = read_heartbeat(state_dir) or {}
    return int(previous.get("boot_count", 0)) + 1


class HeartbeatWriter(threading.Thread):
    """Background heartbeat loop; ``build`` supplies each record's payload."""

    def __init__(self, state_dir: str, interval_s: float,
                 build: Callable[[], dict]):
        super().__init__(name="kvedge-heartbeat", daemon=True)
        self._state_dir = state_dir
        self._interval_s = interval_s
        self._build = build
        self._stop = threading.Event()
        self.last: dict | None = None

    def beat_once(self) -> dict:
        self.last = write_heartbeat(self._state_dir, self._build())
        return self.last

    def run(self) -> None:
        while not self._stop.is_set():
            self.beat_once()
            self._stop.wait(self._interval_s)

    def stop(self) -> None:
        self._stop.set()
