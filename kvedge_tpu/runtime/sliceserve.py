"""Cross-host continuous batching: the paged scheduler on a multi-host slice.

The design of record from SERVING.md ("Left on the table" — now built):
the control plane is NOT distributed. Admission, slot assignment, block
tables, reservations, and the prefix trie stay host metadata on the
leader (process 0), exactly as they are single-host; followers only ever
execute the *device program* with the leader's inputs. Concretely, a
:class:`SlicePagedKVCache` on the leader broadcasts each device call —
table sync, prefill chunk, decode step, decode window — as a fixed-shape
header plus its inputs, then every process executes the SAME jitted
kernel on global arrays, so XLA's collectives span the slice exactly as
they do in multi-host training. The follower side is
:func:`follow_paged`: a loop that receives ops and replays them.

Why this is sound:

* **Total order.** Every cache-state mutation in the serving layer
  serializes on the server lock (SERVING.md invariant 5), so the
  leader's broadcasts form one totally-ordered op stream; the follower
  replays it in order. There is no second broadcaster by construction.
* **Followers hold no host state.** Free lists, refcounts, LRU stamps,
  reservations — none of it is replicated (the LRU clock isn't even
  deterministic across hosts). The follower's device state evolves
  identically because the device inputs — tables, lengths, tokens,
  masks — arrive by value in the op stream.
* **Windows amortize the broadcast like they amortize RTT.** Between
  page boundaries the decode loop dispatches one WINDOW op per
  ``page_size`` greedy tokens; the cross-host control traffic rides the
  same cadence as the single-host loop's host reads.
* **Failure is bounded, and no longer always fatal.** A follower that
  dies used to leave the leader blocked in a collective forever,
  holding the server's work lock. Every leader-side op now runs
  through a :class:`~kvedge_tpu.runtime.failures.DeadlineRunner` with
  compile-aware budgets: a wedged op is orphaned on the op thread and
  surfaces as a typed
  :class:`~kvedge_tpu.runtime.failures.SliceFollowerLost`, the op
  stream latches dead, and the serving layer degrades (poisons
  in-flight requests, refuses new ones, keeps ``close()`` bounded).
  The recovery supervisor (runtime/recovery.py, SERVING.md rung 15)
  then tries to heal in place: :meth:`SlicePagedKVCache.reform`
  installs a fresh op stream and runs a deadline-bounded barrier SYNC
  that a re-entered follower replays as its first op. Only when
  reformation keeps failing does the old story — reschedule the slice
  — take over. A full follower *state machine* (rejoin mid-stream at
  an arbitrary op) remains rejected; rejoin at the reformation
  barrier is the one boundary cheap enough to keep.

The reference has no serving and no multi-host anything (SURVEY.md §0,
§5); this module is the last rung of the serving ladder this repo
climbs on top of the reference's deployment story.
"""

from __future__ import annotations

import dataclasses
import time

import numpy as np

from kvedge_tpu.runtime.failures import (
    DeadlineRunner,
    DeviceOpTimeout,
    OpBudgets,
    SliceFollowerLost,
)
from kvedge_tpu.models.kvcache import (
    PagedCacheError,
    PagedKVCache,
    PagedState,
    _cow_page_impl,
    _decode_step_core,
    _gather_pages_impl,
    _paged_decode_window_capped_impl,
    _paged_decode_window_impl,
    _paged_decode_window_sampled_capped_impl,
    _paged_decode_window_sampled_impl,
    _paged_prefill_impl,
    _paged_spec_window_impl,
    _paged_spec_window_sampled_impl,
    _scatter_pages_impl,
    _spec_verify_core,
)

# Op codes (header[0]). STOP ends the follower loop. WINDOWP/WSAMPLEP
# are the pipelined (overlap) window pair: dispatched WITHOUT reading
# the result, so the leader can broadcast window N+1 while window N is
# still executing — followers likewise replay the dispatch and never
# block on a result (they never read tokens at all). New codes append
# at the end: the numbering is wire protocol.
(OP_STOP, OP_SYNC, OP_PREFILL, OP_STEP, OP_WINDOW, OP_SPEC,
 OP_WSAMPLE, OP_WINDOWP, OP_WSAMPLEP, OP_SWAPOUT, OP_SWAPIN,
 OP_SPECW, OP_SPECWS, OP_MULTI, OP_COWP) = range(15)
_HEADER_LEN = 4  # [op, a, b, c] — meanings per op below.

# Human names for follower-side replay spans (runtime/tracing.py).
_OP_NAMES = {
    OP_STOP: "stop", OP_SYNC: "sync", OP_PREFILL: "prefill",
    OP_STEP: "step", OP_WINDOW: "window", OP_SPEC: "spec",
    OP_WSAMPLE: "wsample", OP_WINDOWP: "windowp",
    OP_WSAMPLEP: "wsamplep", OP_SWAPOUT: "swapout",
    OP_SWAPIN: "swapin", OP_SPECW: "specw", OP_SPECWS: "specws",
    OP_MULTI: "multi", OP_COWP: "cowp",
}

# Ops whose payloads may ride a coalesced OP_MULTI frame (SERVING.md
# rung 23): the deferred table sync and swap-in that precede a window
# dispatch at a page boundary, plus the pipelined dispatches
# themselves. Every one of these has payload shapes fully derivable
# from its own [op, a, b, c] header, which is what lets the follower
# carve a packed frame without any out-of-band shape agreement.
_COALESCABLE = frozenset((
    OP_SYNC, OP_SWAPIN, OP_WINDOWP, OP_WSAMPLEP, OP_SPECW, OP_SPECWS,
    OP_COWP,
))


def _slice_kernels(mesh, cfg, quantized: bool = False):
    """The paged kernels re-jitted with pinned output shardings: the
    K/V pools shard over the ``model`` axis on the kv-heads dim (the
    per-token K/V a model-sharded layer produces is already
    head-sharded, so scatters stay local and no host ever materializes
    the whole pool), falling back to replication when the heads don't
    divide; logits/tokens/tables pin REPLICATED so each process reads
    them from its own addressable shard (``addressable_data(0)``) with
    no extra collective. Compiled programs are the single-host impl
    functions unchanged — the exactness argument is structural, not
    re-proven."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    rep = NamedSharding(mesh, P())
    axis_sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    model = axis_sizes.get("model", 1)
    head_sharded = model > 1 and cfg.kv_heads % model == 0
    pool_sh = (
        NamedSharding(mesh, P(None, None, None, "model", None))
        if head_sharded else rep
    )
    # int8 scales [L, P, page, K] shard with the pool's kv-head dim.
    scale_sh = (
        (NamedSharding(mesh, P(None, None, None, "model"))
         if head_sharded else rep)
        if quantized else None
    )
    state_sh = PagedState(
        pool_k=pool_sh, pool_v=pool_sh, tables=rep, lengths=rep,
        scale_k=scale_sh, scale_v=scale_sh,
    )
    prefill = jax.jit(
        _paged_prefill_impl, static_argnames=("cfg",),
        donate_argnums=(1,), out_shardings=(rep, state_sh),
    )
    step = jax.jit(
        _decode_step_core, static_argnames=("cfg",),
        donate_argnums=(1,), out_shardings=(rep, state_sh),
    )
    window = jax.jit(
        _paged_decode_window_impl, static_argnames=("cfg", "n_steps"),
        donate_argnums=(1,), out_shardings=(rep, state_sh),
    )
    spec = jax.jit(
        _spec_verify_core, static_argnames=("cfg",),
        donate_argnums=(1,), out_shardings=(rep, rep, rep, state_sh),
    )
    wsample = jax.jit(
        _paged_decode_window_sampled_impl,
        static_argnames=("cfg", "n_steps"), donate_argnums=(1,),
        out_shardings=(rep, state_sh),
    )
    window_capped = jax.jit(
        _paged_decode_window_capped_impl,
        static_argnames=("cfg", "n_steps"), donate_argnums=(1,),
        out_shardings=(rep, state_sh),
    )
    wsample_capped = jax.jit(
        _paged_decode_window_sampled_capped_impl,
        static_argnames=("cfg", "n_steps"), donate_argnums=(1,),
        out_shardings=(rep, state_sh),
    )
    # Device-resident spec windows (SERVING.md rung 20): emitted,
    # counts, and the pending/context carry all pin REPLICATED so the
    # leader host-reads results from its shard and every process holds
    # its own copy of the carry for the next window's dispatch.
    specw = jax.jit(
        _paged_spec_window_impl,
        static_argnames=("cfg", "n_passes", "k_len"),
        donate_argnums=(1,),
        out_shardings=(rep, rep, rep, rep, rep, state_sh),
    )
    # Mixed greedy/sampled spec window (SERVING.md rung 23): same
    # carry triple and output shardings as the greedy program — the
    # two share one device-resident carry, so a pipeline may hand the
    # carry between them when the batch's sampled population drains.
    specws = jax.jit(
        _paged_spec_window_sampled_impl,
        static_argnames=("cfg", "n_passes", "k_len"),
        donate_argnums=(1,),
        out_shardings=(rep, rep, rep, rep, rep, state_sh),
    )
    # Preemptive swap (SERVING.md rung 17): the gather pins REPLICATED
    # outputs — an all-gather over the model-sharded pool dims, so the
    # leader can host-read the as-stored page bytes; the scatter takes
    # replicated page bytes back into the sharded pools (each process
    # keeps its own head shard of the update). No dtype conversion in
    # either — the swap path's bit-exactness contract.
    swap_gather = jax.jit(_gather_pages_impl, out_shardings=rep)
    swap_scatter = jax.jit(
        _scatter_pages_impl, donate_argnums=(0,), out_shardings=state_sh,
    )
    # COW divergence (SERVING.md rung 24): one device-side page copy
    # per (src, dst) pair, traced ONCE — the pair arrives as a traced
    # [2] int32 array so every copy replays the same program. Each
    # process copies its own head shard; nothing crosses hosts.
    cow = jax.jit(
        _cow_pair_core, donate_argnums=(0,), out_shardings=state_sh,
    )
    return (rep, state_sh, prefill, step, window, spec, wsample,
            window_capped, wsample_capped, swap_gather, swap_scatter,
            specw, specws, cow)


def _cow_pair_core(state, pair):
    """Header-derived form of :func:`_cow_page_impl` for the op
    stream: ``pair = [src, dst]`` rides the broadcast as one array."""
    return _cow_page_impl(state, pair[0], pair[1])


class SlicePagedKVCache(PagedKVCache):
    """A :class:`PagedKVCache` whose device calls span a multi-host mesh.

    Constructed identically on EVERY process (the zeroed global state
    and the jitted kernels are collective creations, so construction
    order is part of the protocol). On the leader it is handed to a
    regular :class:`~kvedge_tpu.models.serving.PagedGenerationServer`
    and behaves like any cache — all the host bookkeeping of the base
    class runs as-is; only the device seams broadcast first. On
    followers, :func:`follow_paged` drives :meth:`_follow_op` until the
    leader broadcasts STOP.

    Single-process meshes work too (broadcast_one_to_all degenerates to
    a copy), which is how tests/test_sliceserve.py pins leader-path
    token equality against the plain cache without subprocesses.
    """

    def __init__(self, cfg, *, slots: int, pages: int, page_size: int,
                 mesh, max_pages_per_seq: int | None = None,
                 kv_dtype: str = "", op_budgets: OpBudgets | None = None):
        import jax

        # Slice pools always use the gather path: the Pallas kernel has
        # no partitioning rule, so tracing it over a model-sharded pool
        # would poison the first decode step on a real slice. Pinned
        # here (every process constructs the same cfg, so the pin is
        # part of the protocol) rather than left to _use_paged_kernel's
        # per-trace heuristics — even an explicit "kernel" override is
        # downgraded, and __init__'s forced-kernel VMEM refusal never
        # fires spuriously for a slice cache.
        cfg = dataclasses.replace(cfg, paged_attention="gather")
        self.mesh = mesh
        (self._rep, self._state_sh, self._k_prefill, self._k_step,
         self._k_window, self._k_spec, self._k_wsample,
         self._k_window_capped, self._k_wsample_capped,
         self._k_swapout, self._k_swapin,
         self._k_specw, self._k_specws,
         self._k_cow) = _slice_kernels(
             mesh, cfg, quantized=kv_dtype == "int8"
         )
        self._is_leader = jax.process_index() == 0
        self._stopped = False
        # Coalesced slice broadcasts (SERVING.md rung 23): leader-side
        # buffer of (header, payload, exec) triples for ops whose
        # broadcast may be deferred to the next dispatch seam, where
        # everything pending goes out as ONE framed OP_MULTI — a table
        # sync or swap-in at a page boundary no longer pays its own
        # pair of collectives. Counters are plain observability.
        self._pending_ops: list = []
        self.coalesced_flushes = 0
        self.coalesced_ops = 0
        # Per-op broadcast attribution (SERVING.md rung 25): cumulative
        # wall time each op KIND spent in the header+payload broadcast
        # and collective execution, keyed by the op name ("sync",
        # "windowp", ..., "multi" for coalesced frames). Plain dict of
        # [count, total_ms] mutated only by the leader's op thread
        # under the serving work lock; rendered in /metrics as the
        # labelled kvedge_serve_device_ms_broadcast_total family.
        self.op_broadcast_ms: dict[str, list] = {}
        # Leader-side watchdog over the op stream (header send,
        # broadcast, exec): a wedged collective surfaces as a typed
        # SliceFollowerLost instead of an eternal hang holding the
        # server's work lock. Followers run a bounded rejoin loop
        # (runtime/workload.py) before giving up and letting the pod
        # die. The budgets object is kept: reform() builds each
        # replacement runner over the SAME instance, so compiled-key
        # knowledge survives — a program compiled before the failure
        # keeps its steady budget after the heal.
        self._op_budgets = op_budgets if op_budgets is not None else OpBudgets()
        self._ops = DeadlineRunner(
            self._op_budgets, failure=SliceFollowerLost,
            name="kvedge-slice-ops",
        )
        super().__init__(
            cfg, slots=slots, pages=pages, page_size=page_size,
            max_pages_per_seq=max_pages_per_seq, kv_dtype=kv_dtype,
        )

    # ---- refused host I/O ------------------------------------------------

    def snapshot_pages(self, ids):
        """Prefix-cache persistence is single-host only: the inherited
        implementation would run a leader-only computation on a global
        array — a collective the followers never join (wedge or crash).
        The refusal lives here, with the API, not just at the workload
        call-site guard."""
        raise PagedCacheError(
            "prefix-cache persistence is not supported on a slice cache"
        )

    def read_pages(self, ids):
        raise PagedCacheError(
            "prefix-cache persistence is not supported on a slice cache"
        )

    def write_pages(self, ids, k_vals, v_vals):
        raise PagedCacheError(
            "prefix-cache persistence is not supported on a slice cache"
        )

    # ---- global-array plumbing ------------------------------------------

    def _init_state(self, shape, dtype) -> PagedState:
        """Zeroed state as GLOBAL arrays: a collective jit execution
        (every process runs it at construction)."""
        import jax
        import jax.numpy as jnp

        slots, mpps = self.slots, self.max_pages_per_seq
        quantized = self.kv_quantized

        def scale():
            return (jnp.zeros(shape[:-1], jnp.float32)
                    if quantized else None)

        return jax.jit(
            lambda: PagedState(
                pool_k=jnp.zeros(shape, dtype),
                pool_v=jnp.zeros(shape, dtype),
                tables=jnp.zeros((slots, mpps), jnp.int32),
                lengths=jnp.zeros((slots,), jnp.int32),
                scale_k=scale(),
                scale_v=scale(),
            ),
            out_shardings=self._state_sh,
        )()

    def _global(self, arr: np.ndarray):
        """A replicated global array from identical per-process data."""
        import jax

        return jax.make_array_from_process_local_data(self._rep, arr)

    def _global_const(self, kind: str, arr: np.ndarray):
        """Memoized :meth:`_global` for the pipelined window seams'
        small operand rows (mask/caps/stops), which repeat verbatim
        between steady-state redispatches — every process (leader and
        follower alike) skips the per-window global-array construction
        on a byte-identical repeat. Shares the base class's
        ``_dev_memo`` store, so ``drop_carry`` (and through it
        ``reform``) invalidates it with the carries — a re-formed mesh
        never sees globals built on the dead one."""
        key = arr.tobytes()
        hit = self._dev_memo.get(kind)
        if hit is not None and hit[0] == key:
            return hit[1]
        dev = self._global(arr)
        self._dev_memo[kind] = (key, dev)
        return dev

    @staticmethod
    def _read(arr) -> np.ndarray:
        """Host copy of a replicated global array (local shard only)."""
        return np.asarray(arr.addressable_data(0))

    def _bcast(self, tree):
        from jax.experimental import multihost_utils

        return multihost_utils.broadcast_one_to_all(
            tree, is_source=self._is_leader
        )

    def _send_header(self, op: int, a: int = 0, b: int = 0, c: int = 0):
        hdr = np.array([op, a, b, c], np.int64)
        self._bcast(hdr)

    # ---- coalesced multi-op broadcasts (SERVING.md rung 23) --------------

    def _queue_op(self, hdr: tuple, payload: tuple, exec_thunk) -> None:
        """Buffer one coalescable op. The payload arrays MUST be
        snapshots (never views of live host bookkeeping): the
        broadcast is deferred to the next flush, and the serving layer
        keeps mutating ``_host_tables``/``_host_lengths`` in between."""
        self._pending_ops.append((
            np.array(hdr, np.int64),
            tuple(np.ascontiguousarray(a) for a in payload),
            exec_thunk,
        ))

    def _flush_ops(self, key: tuple | None = None,
                   budget_s: float | None = None):
        """Broadcast + execute everything pending, in queue order.

        One buffered op goes out exactly as it always did — its own
        header + payload pair, wire-identical to the pre-coalescing
        protocol. Two or more pack into a single OP_MULTI frame: one
        header (a = op count, b = frame bytes) and ONE uint8 payload
        broadcast carrying each op's [op, a, b, c] header followed by
        its raw array bytes; the follower re-derives every shape from
        the embedded headers (:meth:`_multi_templates`) and replays
        through the same exec path as the bare branches. Execution
        (leader-side jit enqueue) happens AFTER the frame broadcast,
        in op order, so the collective order every process sees is
        identical to the unbatched stream. Returns the LAST op's exec
        result (the dispatch that forced the flush)."""
        if not self._pending_ops:
            return None
        ops, self._pending_ops = self._pending_ops, []
        if key is None:
            key = ("multi", len(ops))

        if len(ops) == 1:
            hdr, payload, exec_thunk = ops[0]

            def op():
                self._bcast(hdr)
                self._bcast(payload)
                return exec_thunk()

            return self._traced_run(key, op, budget_s=budget_s)

        frame = np.frombuffer(
            b"".join(
                hdr.tobytes() + b"".join(a.tobytes() for a in payload)
                for hdr, payload, _ in ops
            ),
            np.uint8,
        )

        def op():
            self._send_header(OP_MULTI, len(ops), frame.shape[0])
            self._bcast(frame)
            out = None
            for _, _, exec_thunk in ops:
                out = exec_thunk()
            return out

        self.coalesced_flushes += 1
        self.coalesced_ops += len(ops)
        return self._traced_run(key, op, budget_s=budget_s)

    def _discard_pending_ops(self) -> None:
        """Drop buffered ops without broadcasting (stop/reform): the
        followers are released or rejoining at a barrier SYNC that
        re-syncs tables anyway — replaying onto a dead or reset stream
        would wedge or double-apply."""
        self._pending_ops.clear()

    def _multi_templates(self, op: int, a: int, b: int, c: int) -> tuple:
        """(shape, dtype) per payload array for a coalescable op, as a
        pure function of its header — the single source of truth for
        both the bare zero-template broadcasts and OP_MULTI frame
        carving, so the two wire forms can never drift apart."""
        n = self.slots
        if op == OP_SYNC:
            return (((n, self.max_pages_per_seq), np.int32),
                    ((n,), np.int32))
        if op == OP_SWAPIN:
            return tuple(
                (arr.shape, arr.dtype) for arr in self._swap_templates(a)
            )
        if op == OP_COWP:
            # a = src, b = dst (redundantly carried in the [2] int32
            # payload so the jitted copy replays one traced program).
            return (((2,), np.int32),)
        if op == OP_WINDOWP:
            # a = n_steps, b = carry flag.
            return (((n,), np.int32), ((n,), bool), ((n,), np.int32),
                    ((n,), np.int32))
        if op == OP_WSAMPLEP:
            # a = n_steps, b = key-data width, c = carry flag.
            return (((n,), np.int32), ((n,), bool), ((n, b), np.uint32),
                    ((n,), np.int32), ((n,), np.float32),
                    ((n,), np.float32), ((n,), bool), ((n,), np.int32),
                    ((n,), np.int32))
        if op == OP_SPECW:
            # a = n_passes, b = k_len, c = ctx width (0 = carry).
            width = c if c > 0 else 1
            return (((n,), np.int32), ((n,), bool), ((n,), np.int32),
                    ((n, width), np.int32), ((n,), np.int32))
        if op == OP_SPECWS:
            # a = n_passes, b = k_len * 256 + key-data width,
            # c = ctx width (0 = carry).
            kw = b % 256
            width = c if c > 0 else 1
            return (((n,), np.int32), ((n,), bool), ((n,), np.int32),
                    ((n, width), np.int32), ((n,), np.int32),
                    ((n, kw), np.uint32), ((n,), np.int32),
                    ((n,), np.float32), ((n,), np.float32), ((n,), bool))
        raise PagedCacheError(f"op {op} is not coalescable")

    def _replay_packed(self, params, op: int, a: int, b: int, c: int,
                       payload: list) -> None:
        """Follower: replay one coalescable op through the SAME exec
        seams the bare branches use — a frame-carried op and a bare op
        are indistinguishable past this point."""
        if op == OP_SYNC:
            self._apply_sync(payload[0], payload[1])
        elif op == OP_SWAPIN:
            self._exec_swapin(payload[0], tuple(payload[1:]))
        elif op == OP_COWP:
            self._exec_cow(np.asarray(payload[0]))
        elif op == OP_WINDOWP:
            self._exec_window_pipelined(
                params, *payload, n_steps=a, carry=bool(b))
        elif op == OP_WSAMPLEP:
            self._exec_window_sampled_pipelined(
                params, *payload, n_steps=a, carry=bool(c))
        elif op == OP_SPECW:
            self._exec_spec_window(
                params, *payload, n_passes=a, k_len=b, carry=c == 0)
        elif op == OP_SPECWS:
            self._exec_spec_window(
                params, *payload, n_passes=a, k_len=b // 256,
                carry=c == 0)
        else:  # pragma: no cover - _multi_templates already refused
            raise PagedCacheError(f"op {op} is not coalescable")

    # ---- leader-side device seams (base-class host logic unchanged) -----

    def _traced_run(self, key: tuple, op, budget_s: float | None = None):
        """One leader-side op through the deadline runner, stamped as a
        per-op broadcast span (cat "slice") when the serving layer
        shared a tracer (``cache.tracer``, runtime/tracing.py). The
        span covers header send + payload broadcast + the collective's
        execution — the seam where a slow or lost follower shows up, so
        a stalled slice is attributable to the op that stalled it.
        Tracer or not, the per-op-kind cumulative bill
        (``op_broadcast_ms``, rung 25) always accrues: two
        perf_counter stamps and a dict bump, the same always-on cost
        contract as the serving layer's stage histograms."""
        tr = getattr(self, "tracer", None)
        if tr is not None and self._ops.tracer is None:
            # Lazy share (also re-shares after reform() swaps in a
            # fresh runner): a timeout's "op-timeout" instant lands in
            # the same timeline as the op spans it interrupts.
            self._ops.tracer = tr
        t0 = time.perf_counter()
        try:
            return self._ops.run(key, op, budget_s=budget_s)
        finally:
            dt_ms = (time.perf_counter() - t0) * 1e3
            cell = self.op_broadcast_ms.get(str(key[0]))
            if cell is None:
                cell = self.op_broadcast_ms[str(key[0])] = [0, 0.0]
            cell[0] += 1
            cell[1] += dt_ms
            if tr is not None:
                tr.span(str(key[0]), "slice", t0,
                        args={"op": "/".join(str(k) for k in key)})

    def _sync(self) -> None:
        if self._stopped or self._ops.dead is not None:
            # Teardown tail: a request thread unwinding after a hard
            # close (or after the op stream died) still releases its
            # slot, which syncs tables — the followers are gone, the
            # device state is dead, so the host bookkeeping proceeds
            # without a broadcast.
            return
        # Deferred (rung 23): the broadcast rides the next flush — at
        # a page boundary that is the window dispatch a moment later,
        # so sync + dispatch go out as ONE OP_MULTI frame instead of
        # two header/payload collective pairs. np.array COPIES: the
        # serving layer mutates the host tables between queue and
        # flush, and the wire must carry this call's snapshot.
        tables = np.array(self._host_tables, np.int32)
        lengths = np.array(self._host_lengths, np.int32)
        self._queue_op(
            (OP_SYNC, 0, 0, 0), (tables, lengths),
            lambda: self._apply_sync(tables, lengths),
        )

    def _apply_sync(self, tables: np.ndarray, lengths: np.ndarray):
        import dataclasses

        self.state = dataclasses.replace(
            self.state,
            tables=self._global(tables.astype(np.int32)),
            lengths=self._global(lengths.astype(np.int32)),
        )

    def _check_live(self) -> None:
        if self._ops.dead is not None:
            raise SliceFollowerLost(
                f"slice op stream is dead (op {self._ops.dead} timed "
                f"out — follower lost); the slice must be rescheduled",
                op=self._ops.dead,
            )
        if self._stopped:
            raise PagedCacheError(
                "slice serve is stopped — the followers were released"
            )

    def _device_prefill(self, params, tokens, slot: int, offset: int):
        self._check_live()
        self._flush_ops()
        tokens = np.asarray(tokens, np.int32)

        def op():
            self._send_header(OP_PREFILL, slot, offset, tokens.shape[0])
            sent = np.asarray(self._bcast(tokens))
            return self._exec_prefill(params, sent, slot, offset)

        return self._traced_run(("prefill", tokens.shape[0]), op)

    def _exec_prefill(self, params, tokens: np.ndarray, slot: int,
                      offset: int):
        logits, self.state = self._k_prefill(
            params, self.state, self._global(tokens.astype(np.int32)),
            slot, self.cfg, offset,
        )
        return self._read(logits)

    def _active_np(self, active) -> np.ndarray:
        """bool [slots] mask on the HOST — the base class derives the
        default (None = every admitted slot) from device lengths, which
        a leader-only computation must not touch on a global array."""
        if active is None:
            return np.asarray(self._host_lengths, np.int64) > 0
        return np.asarray(active, bool)

    def _device_step(self, params, tokens, active):
        self._check_live()
        self._flush_ops()
        tokens = np.asarray(tokens, np.int32)
        mask = self._active_np(active)

        def op():
            self._send_header(OP_STEP)
            sent, m = self._bcast((tokens, mask))
            return self._exec_step(params, np.asarray(sent),
                                   np.asarray(m))

        return self._traced_run(("step",), op)

    def _exec_step(self, params, tokens: np.ndarray, mask: np.ndarray):
        logits, self.state = self._k_step(
            params, self.state, self._global(tokens.astype(np.int32)),
            self.cfg, self._global(mask.astype(bool)),
        )
        return self._read(logits)

    def _device_step_tokens(self, params, tokens, active):
        """Leader: the fused step+argmax seam rides the existing
        OP_STEP broadcast (a new fused op kind would buy the slice
        path little — the logits already come back replicated) and
        picks on the host copy. Token-identical to the base class's
        on-device argmax: same logits, same argmax tie-breaking
        (lowest index) in numpy and XLA."""
        logits = self._device_step(params, tokens, active)
        return np.argmax(logits, axis=-1).astype(np.int32)

    def _device_window(self, params, tokens, n_steps: int, active):
        self._check_live()
        self._flush_ops()
        tokens = np.asarray(tokens, np.int32)
        mask = self._active_np(active)

        def op():
            self._send_header(OP_WINDOW, n_steps)
            sent, m = self._bcast((tokens, mask))
            return self._exec_window(params, np.asarray(sent),
                                     np.asarray(m), n_steps)

        return self._traced_run(("window", n_steps), op)

    def _exec_window(self, params, tokens: np.ndarray, mask: np.ndarray,
                     n_steps: int):
        toks, self.state = self._k_window(
            params, self.state, self._global(tokens.astype(np.int32)),
            self.cfg, n_steps, self._global(mask.astype(bool)),
        )
        return self._read(toks)

    def _device_window_sampled(self, params, tokens, n_steps: int,
                               active, key_data, base_steps, temps,
                               top_ps, sampled_mask):
        self._check_live()
        self._flush_ops()
        tokens = np.asarray(tokens, np.int32)
        key_data = np.asarray(key_data, np.uint32)
        mask = self._active_np(active)

        def op():
            self._send_header(OP_WSAMPLE, n_steps, key_data.shape[1])
            payload = self._bcast((
                tokens, mask, key_data,
                np.asarray(base_steps, np.int32),
                np.asarray(temps, np.float32),
                np.asarray(top_ps, np.float32),
                np.asarray(sampled_mask, bool),
            ))
            return self._exec_window_sampled(
                params, *(np.asarray(x) for x in payload),
                n_steps=n_steps,
            )

        return self._traced_run(("wsample", n_steps), op)

    def _exec_window_sampled(self, params, tokens, mask, key_data,
                             base_steps, temps, top_ps, smask, *,
                             n_steps: int):
        toks, self.state = self._k_wsample(
            params, self.state, self._global(tokens.astype(np.int32)),
            self.cfg, n_steps, self._global(mask.astype(bool)),
            self._global(key_data.astype(np.uint32)),
            self._global(base_steps.astype(np.int32)),
            self._global(temps.astype(np.float32)),
            self._global(top_ps.astype(np.float32)),
            self._global(smask.astype(bool)),
        )
        return self._read(toks)

    # ---- pipelined (overlap) window pair --------------------------------

    def _device_window_dispatch(self, params, tokens, n_steps: int,
                                active, steps_left, stop_tokens):
        """Leader: broadcast + enqueue a capped window WITHOUT reading
        the result. ``tokens=None`` selects the device-resident carry
        (header flag ``b``) — the previous window's final token row,
        which every process slices locally from its own replicated
        copy, so neither the leader nor any follower blocks on the
        previous window between the pair. A zero placeholder still
        rides the broadcast so the payload shape is op-independent.
        The dispatch is a flush seam (rung 23): a buffered table sync
        rides the same framed broadcast."""
        self._check_live()
        carry = 0 if tokens is not None else 1
        tokens_np = (np.zeros((self.slots,), np.int32) if carry
                     else np.asarray(tokens, np.int32))
        mask = self._active_np(active)
        caps = np.asarray(steps_left, np.int32)
        stops = np.asarray(stop_tokens, np.int32)

        self._queue_op(
            (OP_WINDOWP, n_steps, carry, 0),
            (tokens_np, mask, caps, stops),
            lambda: self._exec_window_pipelined(
                params, tokens_np, mask, caps, stops,
                n_steps=n_steps, carry=bool(carry),
            ),
        )
        return self._flush_ops(("windowp", n_steps))

    def _exec_window_pipelined(self, params, tokens: np.ndarray,
                               mask: np.ndarray, caps: np.ndarray,
                               stops: np.ndarray, *,
                               n_steps: int, carry: bool):
        toks_in = (self._carry_tokens() if carry
                   else self._global(tokens.astype(np.int32)))
        toks, self.state = self._k_window_capped(
            params, self.state, toks_in, self.cfg, n_steps,
            self._global_const("w_act", mask.astype(bool)),
            self._global_const("w_caps", caps.astype(np.int32)),
            self._global_const("w_stops", stops.astype(np.int32)),
        )
        self._carry = (toks, n_steps)
        return toks

    def _device_window_sampled_dispatch(self, params, tokens,
                                        n_steps: int, active, key_data,
                                        base_steps, temps, top_ps,
                                        sampled_mask, steps_left,
                                        stop_tokens):
        self._check_live()
        carry = 0 if tokens is not None else 1
        tokens_np = (np.zeros((self.slots,), np.int32) if carry
                     else np.asarray(tokens, np.int32))
        key_data = np.asarray(key_data, np.uint32)
        mask = self._active_np(active)
        payload = (
            tokens_np, mask, key_data,
            np.asarray(base_steps, np.int32),
            np.asarray(temps, np.float32),
            np.asarray(top_ps, np.float32),
            np.asarray(sampled_mask, bool),
            np.asarray(steps_left, np.int32),
            np.asarray(stop_tokens, np.int32),
        )

        # a = n_steps, b = key-data width, c = carry flag.
        self._queue_op(
            (OP_WSAMPLEP, n_steps, key_data.shape[1], carry), payload,
            lambda: self._exec_window_sampled_pipelined(
                params, *payload, n_steps=n_steps, carry=bool(carry),
            ),
        )
        return self._flush_ops(("wsamplep", n_steps))

    def _exec_window_sampled_pipelined(self, params, tokens, mask,
                                       key_data, base_steps, temps,
                                       top_ps, smask, caps, stops, *,
                                       n_steps: int, carry: bool):
        toks_in = (self._carry_tokens() if carry
                   else self._global(tokens.astype(np.int32)))
        # key_data/base_steps advance every window; the rest repeat
        # in steady state and ride the memo.
        toks, self.state = self._k_wsample_capped(
            params, self.state, toks_in, self.cfg, n_steps,
            self._global_const("ws_act", mask.astype(bool)),
            self._global(key_data.astype(np.uint32)),
            self._global(base_steps.astype(np.int32)),
            self._global_const("ws_temps", temps.astype(np.float32)),
            self._global_const("ws_topps", top_ps.astype(np.float32)),
            self._global_const("ws_smask", smask.astype(bool)),
            self._global_const("ws_caps", caps.astype(np.int32)),
            self._global_const("ws_stops", stops.astype(np.int32)),
        )
        self._carry = (toks, n_steps)
        return toks

    def harvest_window(self, handle):
        """Leader: force a dispatched window's tokens. Deadline-bounded
        like every op, but NOT a broadcast — the tokens are replicated,
        every process already holds (or will hold, once its queued
        program runs) its own copy, and followers never read them. The
        read waits on device execution of everything queued up to and
        including this window — i.e. the in-flight pair — so it runs
        under the op budget rather than a bare timeout: the window
        programs were compiled at dispatch, and the steady budget is
        sized for device execution, not compilation."""
        self._check_live()
        self._flush_ops()
        return self._traced_run(("wharvest",), lambda: self._read(handle))

    # ---- preemptive swap (scheduler, SERVING.md rung 17) -----------------

    def _device_swapout(self, ids):
        """Leader: broadcast the page ids, then every process runs the
        same jitted gather — an all-gather over the model-sharded pool
        dims whose replicated result the leader reads host-side. The
        follower replays the op in the totally-ordered stream and
        discards its (identical) copy."""
        self._check_live()
        self._flush_ops()
        ids_np = np.asarray(ids, np.int32)

        def op():
            self._send_header(OP_SWAPOUT, ids_np.shape[0])
            sent = np.asarray(self._bcast(ids_np))
            return self._exec_swapout(sent)

        return self._traced_run(("swapout", ids_np.shape[0]), op)

    def _exec_swapout(self, ids: np.ndarray):
        out = self._k_swapout(
            self.state, self._global(ids.astype(np.int32))
        )
        return tuple(self._read(x) for x in out)

    def _device_swapin(self, ids, arrays) -> None:
        """Leader: broadcast ids + the as-stored page bytes, then every
        process scatters them back into its own shard of the pools.
        The snapshot rides the op stream by value, like every other
        device input — followers hold no swap state between ops."""
        self._check_live()
        ids_np = np.asarray(ids, np.int32)
        arrs = tuple(np.asarray(a) for a in arrays)

        # Deferred (rung 23): the snapshot bytes ride the next flush's
        # frame — a swap-in immediately followed by the window dispatch
        # that needed those pages pays one broadcast, not two.
        self._queue_op(
            (OP_SWAPIN, ids_np.shape[0], 0, 0), (ids_np,) + arrs,
            lambda: self._exec_swapin(ids_np, arrs),
        )

    def _exec_swapin(self, ids: np.ndarray, arrays: tuple) -> None:
        self.state = self._k_swapin(
            self.state, self._global(ids.astype(np.int32)),
            tuple(self._global(a) for a in arrays),
        )

    def _device_cow(self, src: int, dst: int) -> None:
        """Leader: broadcast the (src, dst) pair, then every process
        runs the same jitted page copy on its own pool shard. Deferred
        like a swap-in (rung 23): the COW at an admission rides the
        next flush's frame with the table sync and prefill dispatch
        that follow it, so divergence costs no extra collective."""
        self._check_live()
        pair = np.asarray([src, dst], np.int32)
        self._queue_op(
            (OP_COWP, int(src), int(dst), 0), (pair,),
            lambda: self._exec_cow(pair),
        )

    def _exec_cow(self, pair: np.ndarray) -> None:
        self.state = self._k_cow(
            self.state, self._global(pair.astype(np.int32))
        )

    def _swap_templates(self, n: int) -> tuple:
        """Follower zero templates for an OP_SWAPIN payload of ``n``
        pages: shapes/dtypes must match the leader's broadcast exactly
        (as stored — [L, n, page, K, Dh] pools plus fp32 scale slabs
        for an int8 pool)."""
        pk = self.state.pool_k
        shape = (pk.shape[0], n) + tuple(pk.shape[2:])
        out = [np.zeros((n,), np.int32),
               np.zeros(shape, pk.dtype), np.zeros(shape, pk.dtype)]
        if self.kv_quantized:
            out += [np.zeros(shape[:-1], np.float32),
                    np.zeros(shape[:-1], np.float32)]
        return tuple(out)

    def _device_spec(self, params, tokens, active, spec_mask):
        self._check_live()
        self._flush_ops()
        tokens = np.asarray(tokens, np.int32)
        mask = self._active_np(active)

        def op():
            self._send_header(OP_SPEC, tokens.shape[1] - 1)
            sent, m, smask = self._bcast(
                (tokens, mask, np.asarray(spec_mask, bool))
            )
            return self._exec_spec(params, np.asarray(sent),
                                   np.asarray(m), np.asarray(smask))

        return self._traced_run(("spec", tokens.shape[1]), op)

    def _exec_spec(self, params, tokens: np.ndarray, mask: np.ndarray,
                   spec_mask: np.ndarray):
        emitted, accepted, logits0, self.state = self._k_spec(
            params, self.state, self._global(tokens.astype(np.int32)),
            self.cfg, self._global(mask.astype(bool)),
            self._global(spec_mask.astype(bool)),
        )
        return (self._read(emitted), self._read(accepted),
                self._read(logits0))

    def _device_spec_window(self, params, tokens, n_passes: int,
                            k_len: int, active, budgets, ctx, ctx_len,
                            sampling=None):
        """Leader: broadcast + enqueue one device-resident spec window
        WITHOUT reading the result (the windowed twin of OP_WINDOWP).
        ``tokens=None`` selects the device-resident spec carry —
        pending token, drafting context, and context lengths from the
        previous window, which every process holds replicated from its
        own execution, so nothing blocks between back-to-back windows.
        Header ``c`` carries the drafting-context width (0 = carry, so
        followers know which payload template to expect).

        ``sampling`` (rung 23) switches the op to OP_SPECWS — the
        mixed greedy/sampled program — whose header ``b`` packs
        ``k_len * 256 + key-data width`` (both are tiny; the follower
        unpacks with divmod) and whose payload appends the five
        sampler arrays. The two programs share one carry triple, so a
        pipeline hands the carry between them freely."""
        self._check_live()
        carry = tokens is None
        if carry:
            tokens_np = np.zeros((self.slots,), np.int32)
            ctx_np = np.zeros((self.slots, 1), np.int32)
            ctx_len_np = np.zeros((self.slots,), np.int32)
            width = 0
        else:
            tokens_np = np.asarray(tokens, np.int32)
            ctx_np = np.asarray(ctx, np.int32)
            ctx_len_np = np.asarray(ctx_len, np.int32)
            width = int(ctx_np.shape[1])
        mask = self._active_np(active)
        budgets_np = np.asarray(budgets, np.int32)
        payload = (tokens_np, mask, budgets_np, ctx_np, ctx_len_np)
        if sampling is None:
            hdr = (OP_SPECW, n_passes, k_len, width)
        else:
            key_data, base_steps, temps, top_ps, smask = sampling
            key_data = np.asarray(key_data, np.uint32)
            payload = payload + (
                key_data,
                np.asarray(base_steps, np.int32),
                np.asarray(temps, np.float32),
                np.asarray(top_ps, np.float32),
                np.asarray(smask, bool),
            )
            hdr = (OP_SPECWS, n_passes,
                   k_len * 256 + key_data.shape[1], width)

        self._queue_op(
            hdr, payload,
            lambda: self._exec_spec_window(
                params, *payload,
                n_passes=n_passes, k_len=k_len, carry=carry,
            ),
        )
        return self._flush_ops((_OP_NAMES[hdr[0]], n_passes, k_len))

    def _exec_spec_window(self, params, tokens: np.ndarray,
                          mask: np.ndarray, budgets: np.ndarray,
                          ctx: np.ndarray, ctx_len: np.ndarray,
                          key_data=None, base_steps=None, temps=None,
                          top_ps=None, smask=None, *,
                          n_passes: int, k_len: int, carry: bool):
        if carry:
            pending, ctx_dev, ctx_len_dev = self._spec_carry
        else:
            pending = self._global(tokens.astype(np.int32))
            ctx_dev = self._global(ctx.astype(np.int32))
            ctx_len_dev = self._global(ctx_len.astype(np.int32))
        if key_data is None:
            kernel, extra = self._k_specw, ()
        else:
            kernel = self._k_specws
            extra = (
                self._global(np.asarray(key_data).astype(np.uint32)),
                self._global(np.asarray(base_steps).astype(np.int32)),
                self._global(np.asarray(temps).astype(np.float32)),
                self._global(np.asarray(top_ps).astype(np.float32)),
                self._global(np.asarray(smask).astype(bool)),
            )
        (emitted, counts, pend_out, ctx_out, ctx_len_out,
         self.state) = kernel(
            params, self.state, pending, self.cfg, n_passes, k_len,
            self._global(mask.astype(bool)),
            self._global(budgets.astype(np.int32)),
            ctx_dev, ctx_len_dev, *extra,
        )
        self._spec_carry = (pend_out, ctx_out, ctx_len_out)
        return emitted, counts, pend_out

    def _force_spec_window(self, handle):
        """Leader: force a dispatched spec window's results. Like
        ``harvest_window``: deadline-bounded but NOT a broadcast — the
        outputs are replicated and followers never read them."""
        self._check_live()
        self._flush_ops()
        return self._traced_run(
            ("specwharvest",),
            lambda: (self._read(handle["emitted"]),
                     self._read(handle["counts"]),
                     self._read(handle["pending"])),
        )

    def stop(self) -> None:
        """Leader: release the followers (end of serve). Idempotent —
        the serving layer calls this from ``close()`` UNDER the server
        lock (after the decode loop has exited), which serializes it
        after any in-flight request thread's cache call and makes the
        flag check atomic; a second STOP would be a collective the
        departed followers never join. After stop, table syncs become
        local no-ops (teardown still releases slots) and device ops
        refuse loudly.

        Deadline-bounded like every other op: if the followers are
        already dead the STOP broadcast would wedge ``close()`` — the
        stream is skipped when it has latched dead, and a fresh wedge
        here is swallowed after its budget (close() must return; the
        followers it failed to release are lost either way)."""
        if self._stopped:
            return
        self._stopped = True
        # Buffered coalescable ops die here unbroadcast: post-stop
        # device state is irrelevant (the followers are released and
        # teardown syncs are already local no-ops).
        self._discard_pending_ops()
        if self._ops.dead is not None:
            return  # stream already wedged; nothing left to release
        try:
            # STOP is a bare header — no compilation — so it gets the
            # steady budget even as a first use.
            self._traced_run(("stop",), lambda: self._send_header(OP_STOP),
                          budget_s=self._ops.steady_s)
        except DeviceOpTimeout:
            pass

    def reform(self, *, budget_s: float | None = None) -> None:
        """Leader: replace a dead op stream and re-form the slice
        (recovery supervisor, runtime/recovery.py).

        The dead :class:`DeadlineRunner`'s worker is parked on the
        wedged collective forever — it is shut down and abandoned, and
        a FRESH runner over the SAME :class:`OpBudgets` (compiled
        programs survived, so already-seen keys keep steady budgets)
        takes its place. Then one deadline-bounded **barrier SYNC**
        flows through it: a follower that re-entered
        :func:`follow_paged` replays it as its first op, re-syncing
        tables/lengths, and its success proves every follower is back
        in the collective. On timeout the fresh runner latches dead and
        the typed :class:`SliceFollowerLost` propagates — the old
        (also dead) stream state is effectively unchanged and the
        caller's next attempt, or escalation, takes over.

        ``budget_s`` bounds the barrier (None = the stream's steady
        budget — the SYNC program was compiled long before the
        failure). Raises PagedCacheError after ``stop()``: released
        followers are gone by contract, not by failure.
        """
        if self._stopped:
            raise PagedCacheError(
                "slice serve is stopped — the followers were released, "
                "not lost; there is nothing to re-form"
            )
        old, self._ops = self._ops, DeadlineRunner(
            self._op_budgets, failure=SliceFollowerLost,
            name="kvedge-slice-ops",
        )
        old.shutdown()
        # Ops buffered before the failure never reached the followers
        # and never ran on the leader either — and the barrier SYNC
        # below re-syncs tables from the authoritative host copies, so
        # replaying them into the fresh stream would be a double-apply.
        self._discard_pending_ops()
        # Any in-flight pipelined window died with the old stream; the
        # revived serving loop restarts from host tokens (its first
        # dispatch is never a carry), so the stale device carry must
        # not survive into the new stream.
        self.drop_carry()
        tables = np.asarray(self._host_tables, np.int32)
        lengths = np.asarray(self._host_lengths, np.int32)

        def op():
            self._send_header(OP_SYNC)
            return self._bcast((tables, lengths))

        try:
            got = self._ops.run(
                ("reform-barrier",), op,
                budget_s=budget_s if budget_s is not None
                else self._ops.steady_s,
            )
        except SliceFollowerLost:
            # The fresh stream latched dead on the barrier: the
            # followers are still gone. State is exactly as before the
            # call (a dead stream installed) — re-entrant for the next
            # attempt.
            raise
        t, l = got
        self._apply_sync(np.asarray(t), np.asarray(l))

    # ---- follower side ---------------------------------------------------

    def _follow_op(self, params) -> bool:
        """Receive and replay one op. Returns False on STOP."""
        hdr = np.asarray(self._bcast(np.zeros(_HEADER_LEN, np.int64)))
        op, a, b, c = (int(v) for v in hdr)
        if op == OP_STOP:
            return False
        # Per-follower replay span (cat "slice-follower"): stamped from
        # AFTER the header lands (the header wait is leader idle time,
        # not this follower's work) through payload receive + replay, so
        # each host's own contribution to a slow collective is visible
        # in its own timeline.
        tr = getattr(self, "tracer", None)
        t0 = tr.now() if tr is not None else 0.0
        if op in _COALESCABLE:
            # One zero-template broadcast shaped by _multi_templates —
            # the same shape table that carves OP_MULTI frames — then
            # the shared replay path. Bare and frame-carried ops are
            # identical past the receive.
            payload = [
                np.asarray(x) for x in self._bcast(tuple(
                    np.zeros(shape, dtype)
                    for shape, dtype in self._multi_templates(op, a, b, c)
                ))
            ]
            self._replay_packed(params, op, a, b, c, payload)
        elif op == OP_MULTI:
            # a = op count, b = frame bytes: one uint8 broadcast, then
            # carve [header | arrays]* by the embedded headers and
            # replay each through the same exec path, in frame order.
            frame = np.asarray(self._bcast(np.zeros((b,), np.uint8)))
            off = 0
            for _ in range(a):
                sub = np.frombuffer(
                    frame.data, np.int64, count=_HEADER_LEN, offset=off)
                off += _HEADER_LEN * 8
                sop, sa, sb, sc = (int(v) for v in sub)
                payload = []
                for shape, dtype in self._multi_templates(sop, sa, sb, sc):
                    count = int(np.prod(shape, dtype=np.int64))
                    arr = np.frombuffer(
                        frame.data, dtype, count=count, offset=off,
                    ).reshape(shape)
                    off += arr.nbytes
                    payload.append(arr)
                self._replay_packed(params, sop, sa, sb, sc, payload)
        elif op == OP_PREFILL:
            tokens = self._bcast(np.zeros((c,), np.int32))
            self._exec_prefill(params, np.asarray(tokens), a, b)
        elif op == OP_STEP:
            tokens, mask = self._bcast((
                np.zeros((self.slots,), np.int32),
                np.zeros((self.slots,), bool),
            ))
            self._exec_step(params, np.asarray(tokens), np.asarray(mask))
        elif op == OP_WINDOW:
            tokens, mask = self._bcast((
                np.zeros((self.slots,), np.int32),
                np.zeros((self.slots,), bool),
            ))
            self._exec_window(params, np.asarray(tokens),
                              np.asarray(mask), a)
        elif op == OP_WSAMPLE:
            # a = n_steps, b = key-data width (impl-dependent: 2 for
            # threefry) — the follower's zero templates must match the
            # leader's broadcast shapes exactly.
            payload = self._bcast((
                np.zeros((self.slots,), np.int32),
                np.zeros((self.slots,), bool),
                np.zeros((self.slots, b), np.uint32),
                np.zeros((self.slots,), np.int32),
                np.zeros((self.slots,), np.float32),
                np.zeros((self.slots,), np.float32),
                np.zeros((self.slots,), bool),
            ))
            self._exec_window_sampled(
                params, *(np.asarray(x) for x in payload), n_steps=a
            )
        elif op == OP_SPEC:
            tokens, mask, smask = self._bcast((
                np.zeros((self.slots, a + 1), np.int32),
                np.zeros((self.slots,), bool),
                np.zeros((self.slots,), bool),
            ))
            self._exec_spec(params, np.asarray(tokens),
                            np.asarray(mask), np.asarray(smask))
        elif op == OP_SWAPOUT:
            # a = page count. The gather's replicated result is
            # discarded — only the leader's host copy becomes the
            # snapshot; the follower just joins the collective.
            ids = self._bcast(np.zeros((a,), np.int32))
            self._exec_swapout(np.asarray(ids))
        else:  # pragma: no cover - protocol corruption is slice-fatal
            raise PagedCacheError(f"unknown slice-serve op {op}")
        if tr is not None:
            tr.span(_OP_NAMES.get(op, str(op)), "slice-follower", t0,
                    args={"op": op})
        return True


def follow_paged(cache: SlicePagedKVCache, params) -> None:
    """Follower loop: replay the leader's op stream until STOP.

    An exception here means this follower fell out of the collective
    (the leader's deadline watchdog will type it SliceFollowerLost and
    degrade the pool). The caller (runtime/workload.py) RE-ENTERS this
    loop a bounded number of times: the rejoined follower's first
    received op is the leader's reformation barrier SYNC (a shape it
    always knows how to replay), which restores its tables/lengths and
    puts it back in lockstep. Only when the rejoin budget is exhausted
    does the caller let the pod die — the StatefulSet restart remains
    the recovery path of last resort.
    """
    while cache._follow_op(params):
        pass
