"""Typed failure taxonomy + deadline-bounded device ops for serving.

The serving stack's failure story used to stop at "slice-fatal, by
policy": a follower wedged in a collective left the leader blocked
forever, holding the server's work lock, and close() documented the
hang rather than preventing it (sliceserve.py's old module docstring;
serving.py close()). This module is the DETECTION half of the recovery
contract — the RECOVERY half lives in runtime/recovery.py, whose
supervisor turns the degraded mode these types produce into slice
reformation and warm restart, escalating to the terminal/reschedule
path only when healing keeps failing:

* a small exception hierarchy every layer agrees on — what failed,
  whether a client should retry, and how soon;
* :class:`OpBudgets`, per-op deadlines that are *compile-aware*: the
  first execution of a given device program shape pays XLA compilation
  (minutes on a big model), so it gets the compile budget; steady-state
  repeats of the same shape get the much tighter steady budget;
* :class:`DeadlineRunner`, a single-thread op pump that runs each
  device op with its budget. A collective blocked on a dead follower
  cannot be cancelled — the runner instead *orphans* it (the worker
  thread stays parked on the wedged op) and raises a typed error in
  the caller, so the serving thread gets its lock back and the server
  degrades instead of deadlocking. Once one op times out the stream is
  dead: every later op refuses immediately with the same typed error.

The taxonomy is the contract the rest of the PR threads through:
``serving.py`` poisons in-flight requests with these types, the HTTP
layer maps ``retryable`` onto 503-with-retry-hint vs 500, and the
fault-injection harness (testing/servingfaults.py) asserts requests
terminate in exactly these types.
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass, field
from typing import Callable, Hashable

# Client guidance carried by retryable failures: how long a refused
# client should wait before retrying — roughly the reschedule window
# (or an in-process recovery, which is much faster). The operator knob
# is ``[payload] serving_retry_after_s`` (RuntimeConfig), threaded into
# PagedGenerationServer; when the recovery supervisor is active the
# hint is the MEASURED recovery time instead. This constant is only
# the last-resort default for failures raised outside that wiring.
DEFAULT_RETRY_AFTER_S = 30.0


class ServingFailure(RuntimeError):
    """Base of the serving failure taxonomy.

    ``retryable`` is the client-facing split: True means the request
    was refused or killed by a condition a *replacement* process will
    not have (retry against the rescheduled pod); False means the
    request itself cannot succeed. ``retry_after_s`` is the hint the
    HTTP layer surfaces for retryable failures.
    """

    retryable: bool = False
    retry_after_s: float | None = None


class DeviceOpTimeout(ServingFailure):
    """A deadline-bounded device op exceeded its budget.

    Terminal for the op stream that raised it: the wedged op cannot be
    cancelled, so the stream refuses all later ops with this same type.
    """

    retryable = False

    def __init__(self, message: str, *, op: Hashable | None = None,
                 budget_s: float | None = None, compiling: bool = False):
        super().__init__(message)
        self.op = op
        self.budget_s = budget_s
        self.compiling = compiling


class SliceFollowerLost(DeviceOpTimeout):
    """A slice op (header send / broadcast / exec) blew its deadline —
    a follower is dead or wedged. The leader's op stream is unusable
    from this point; recovery is slice reformation (a fresh op stream
    + barrier SYNC the rejoined follower replays — sliceserve.reform,
    driven by runtime/recovery.py), falling back to rescheduling the
    slice when reformation keeps failing."""


class PoolPoisoned(ServingFailure):
    """The serving pool's decode loop died; in-flight requests were
    poisoned and new submits are refused. Retryable — against the
    replacement pod, after the reschedule window."""

    retryable = True

    def __init__(self, message: str,
                 retry_after_s: float = DEFAULT_RETRY_AFTER_S):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class PageAccountingError(ServingFailure):
    """The page-conservation audit (``serving_debug_pages`` /
    testing/chaos.py invariant 1) found the pool's books broken at a
    quiescent boundary: free + live != pages_total, a negative
    refcount, or a page both free and referenced. NOT retryable — a
    leaked or double-freed page is a host-side bookkeeping bug, and a
    replacement process running the same code will leak the same way;
    the failure exists to be loud, not survivable."""

    retryable = False


def classify_failure(exc: BaseException) -> ServingFailure:
    """The typed error a failed decode loop hands its waiters.

    Already-typed failures pass through (a ``SliceFollowerLost`` tells
    the client more than a generic wrapper would); anything else is a
    ``PoolPoisoned`` chained to the cause so post-mortems keep the
    original traceback.
    """
    if isinstance(exc, ServingFailure):
        return exc
    wrapped = PoolPoisoned(f"serving pool poisoned by {type(exc).__name__}: "
                           f"{exc}")
    wrapped.__cause__ = exc
    return wrapped


@dataclass
class OpBudgets:
    """Compile-aware per-op deadlines.

    ``budget(key)`` returns ``(seconds, first_time)``. The first call
    for a given key — an op label including every shape-affecting
    parameter, e.g. ``("prefill", chunk_len)`` — gets ``compile_s``
    (XLA compiles the program on first execution); repeats get
    ``steady_s``. Defaults are deliberately generous: a false timeout
    poisons a healthy pool, while a true one merely trims minutes off
    an already-lost slice.
    """

    steady_s: float = 120.0
    compile_s: float = 900.0
    _seen: set = field(default_factory=set, repr=False)

    def budget(self, key: Hashable) -> tuple[float, bool]:
        first = key not in self._seen
        self._seen.add(key)
        return (self.compile_s if first else self.steady_s), first


class DeadlineRunner:
    """Run device ops on one dedicated thread, each bounded by a budget.

    Single-threaded by design: the slice protocol's soundness rests on
    a totally-ordered op stream, and one worker preserves submission
    order even though callers already serialize on the server lock.

    On timeout the worker is *orphaned* mid-op (a blocked collective
    has no cancellation path), ``dead`` latches to the failed op's
    label, and the configured failure type is raised; every subsequent
    ``run()`` refuses with the same type without touching the device.
    The orphaned thread is a daemon — it never blocks interpreter exit.
    """

    # NOT concurrent.futures: its workers are non-daemon and joined by
    # an atexit hook, so an orphaned (wedged) worker would hang
    # interpreter shutdown — the exact failure mode this runner exists
    # to remove. A plain daemon thread + queue has no such hook.

    _STOP = object()

    def __init__(self, budgets: OpBudgets | None = None, *,
                 failure: type[DeviceOpTimeout] = DeviceOpTimeout,
                 name: str = "kvedge-device-ops"):
        self._budgets = budgets or OpBudgets()
        self._failure = failure
        self._name = name
        self._queue: queue.SimpleQueue = queue.SimpleQueue()
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()
        self.dead: str | None = None  # label of the op that wedged
        # Optional flight recorder (runtime/tracing.py), shared by the
        # serving layer: a timeout lands as an instant in the same
        # timeline the post-mortem embeds, so the op that killed the
        # stream is visible next to the spans it stranded.
        self.tracer = None

    @property
    def steady_s(self) -> float:
        return self._budgets.steady_s

    def _ensure_worker(self) -> None:
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._worker, name=self._name, daemon=True,
                )
                self._thread.start()

    def _worker(self) -> None:
        while True:
            item = self._queue.get()
            if item is self._STOP:
                return
            fn, box, done = item
            try:
                box["result"] = fn()
            except BaseException as e:  # hand every outcome to the caller
                box["error"] = e
            done.set()

    def _refusal(self, detail: str, *, op=None, budget_s=None,
                 compiling=False) -> DeviceOpTimeout:
        return self._failure(detail, op=op, budget_s=budget_s,
                             compiling=compiling)

    def run(self, key: Hashable, fn: Callable,
            budget_s: float | None = None):
        """``fn()`` on the op thread, bounded by ``key``'s budget (or
        an explicit ``budget_s`` for ops that never compile, e.g. a
        bare STOP header)."""
        if self.dead is not None:
            raise self._refusal(
                f"device-op stream is dead (op {self.dead} timed out "
                f"earlier); refusing {key}", op=key,
            )
        if budget_s is None:
            budget_s, first = self._budgets.budget(key)
        else:
            first = False
        self._ensure_worker()
        box: dict = {}
        done = threading.Event()
        self._queue.put((fn, box, done))
        if not done.wait(timeout=budget_s):
            self.dead = str(key)
            if self.tracer is not None:
                self.tracer.event(
                    "op-timeout", "failure",
                    args={"op": str(key), "budget_s": budget_s,
                          "compiling": first},
                )
            raise self._refusal(
                f"device op {key} exceeded its "
                f"{'compile' if first else 'steady'} budget of "
                f"{budget_s:g}s — follower dead or wedged; op stream "
                f"is now poisoned", op=key, budget_s=budget_s,
                compiling=first,
            )
        if "error" in box:
            raise box["error"]
        return box["result"]

    def shutdown(self) -> None:
        """Release the worker if it is idle; a wedged worker stays
        orphaned (the STOP sentinel queues behind the wedged op and is
        simply never consumed — the thread is a daemon)."""
        self._queue.put(self._STOP)
