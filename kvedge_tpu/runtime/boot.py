"""Runtime boot orchestration: applied config -> payload -> heartbeat + status.

This is what ``kvedge-runtime boot`` (the final ``runcmd`` of the boot
document) executes — the analogue of the IoT Edge daemon starting after
``iotedge config apply`` (``_helper.tpl:74``). In a real pod it never
returns; ``once=True`` performs a single heartbeat cycle for tests and
local verification.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Callable

from kvedge_tpu.config.runtime_config import RuntimeConfig
from kvedge_tpu.parallel.distributed import DistributedState, maybe_initialize
from kvedge_tpu.runtime import heartbeat, recovery
from kvedge_tpu.runtime.devicecheck import DeviceCheckResult, run_device_check
from kvedge_tpu.runtime.profiling import CaptureUnavailable, TraceCapture
from kvedge_tpu.runtime.status import GenerateUnavailable, StatusServer


@dataclasses.dataclass
class RuntimeHandle:
    """A started runtime: payload result, heartbeat writer, status server."""

    cfg: RuntimeConfig
    check: DeviceCheckResult
    writer: heartbeat.HeartbeatWriter
    server: StatusServer
    boot_count: int
    started_at: float
    distributed: DistributedState = dataclasses.field(
        default_factory=lambda: DistributedState(active=False)
    )
    # Set by the ``serve`` payload once its model is restored; the status
    # server's POST /generate routes through it.
    serve_fn: Callable[[dict], dict] | None = None

    @property
    def status_port(self) -> int:
        return self.server.port

    def snapshot(self) -> dict:
        last = heartbeat.read_heartbeat(self.cfg.state_dir) or {}
        return {
            "name": self.cfg.name,
            "ok": self.check.ok,
            "payload": self.cfg.payload,
            "check": self.check.to_dict(),
            "boot_count": self.boot_count,
            "uptime_s": round(time.time() - self.started_at, 3),
            "heartbeat_seq": last.get("seq", 0),
            "heartbeat_age_s": (
                round(time.time() - last["ts"], 3) if "ts" in last else None
            ),
            "distributed": self.distributed.to_dict(),
            # Supervision history from the native PID-1 supervisor
            # (native/kvedge-init.cc) — restarts, give-ups, forwarded
            # signals — persisted on the state volume across pod
            # generations: the pod-world `systemctl status`.
            "init_events": heartbeat.read_init_events(self.cfg.state_dir),
            # Live (or last-known) train-payload progress; None unless a
            # train payload has written it.
            "train_progress": heartbeat.read_train_progress(
                self.cfg.state_dir
            ),
            # Serving request/pool stats; None unless the serve payload
            # is live (runtime/workload.py attaches .stats to serve_fn).
            "serving": (
                self.serve_fn.stats()
                if getattr(self.serve_fn, "stats", None) is not None
                else None
            ),
            # Post-mortem of the last serving failure, persisted on the
            # state volume (runtime/heartbeat.py) — survives rescheduling
            # so the replacement pod reports why its predecessor died.
            "last_failure": heartbeat.read_failure_record(
                self.cfg.state_dir
            ),
        }

    def shutdown(self) -> None:
        self.writer.stop()
        self.server.shutdown()
        # The serve payload's backend may own a decode thread + device
        # page pool (models/serving.py); release them with the runtime.
        closer = getattr(self.serve_fn, "close", None)
        if closer is not None:
            closer()


def _degraded(error: str) -> DeviceCheckResult:
    """A failed check that still serves /status (degraded, debuggable from
    outside — like ssh-ing into a VM whose payload daemon failed) instead
    of crash-looping the pod with a raw traceback."""
    return DeviceCheckResult(
        ok=False, platform="unknown", device_count=0, device_kinds=(),
        mesh_axes=(), mesh_shape=(), probe_ms=0.0, probe_checksum=0.0,
        error=error,
    )


def _topology_mismatch(cfg: RuntimeConfig) -> str:
    """Non-empty iff the chart topology and the config TOML disagree.

    The multi-host chart re-states its replica count as
    ``KVEDGE_EXPECTED_PROCESSES`` (render/manifests.py:runtime_statefulset);
    plain Helm cannot parse the config TOML at install time, so this
    boot-time check is what catches a TOML whose ``[distributed]`` section
    is missing or wrong — otherwise N pods would boot as N healthy,
    *independent* single-host runtimes and the misconfiguration would be
    invisible.
    """
    expected_raw = os.environ.get("KVEDGE_EXPECTED_PROCESSES", "")
    if not expected_raw:
        return ""
    try:
        expected = int(expected_raw)
    except ValueError:
        return f"KVEDGE_EXPECTED_PROCESSES={expected_raw!r} is not an integer"
    if expected != cfg.distributed.num_processes:
        return (
            f"topology mismatch: the chart was rendered for {expected} "
            f"hosts (KVEDGE_EXPECTED_PROCESSES) but the runtime config "
            f"declares [distributed] num_processes="
            f"{cfg.distributed.num_processes}; fix the config TOML"
        )
    return ""


def _booting() -> DeviceCheckResult:
    """The pre-payload state served while boot work is still in flight."""
    return DeviceCheckResult(
        ok=False, platform="booting", device_count=0, device_kinds=(),
        mesh_axes=(), mesh_shape=(), probe_ms=0.0, probe_checksum=0.0,
        error="boot in progress (multi-host join / payload not finished)",
    )


def _run_payload(cfg: RuntimeConfig,
                 handle: "RuntimeHandle") -> DeviceCheckResult:
    if cfg.payload == "none":
        return DeviceCheckResult(
            ok=True, platform="skipped", device_count=0, device_kinds=(),
            mesh_axes=(), mesh_shape=(), probe_ms=0.0, probe_checksum=0.0,
        )
    try:
        if cfg.payload == "transformer-probe":
            from kvedge_tpu.runtime.workload import run_transformer_probe

            return run_transformer_probe(cfg)
        if cfg.payload == "inference-probe":
            from kvedge_tpu.runtime.workload import run_inference_probe

            return run_inference_probe(cfg)
        if cfg.payload == "train":
            from kvedge_tpu.runtime.workload import run_train_payload

            return run_train_payload(cfg)
        if cfg.payload == "eval":
            from kvedge_tpu.runtime.workload import run_eval_payload

            return run_eval_payload(cfg)
        if cfg.payload == "serve":
            from kvedge_tpu.runtime.workload import run_serve_payload

            check, serve_fn = run_serve_payload(cfg)
            handle.serve_fn = serve_fn
            return check
        return run_device_check(cfg)
    except Exception as e:
        return _degraded(f"payload {cfg.payload!r} failed: {e!r}")


def start_runtime(cfg: RuntimeConfig) -> RuntimeHandle:
    """Start the status server, run the boot work, keep the heartbeat going.

    The status server starts FIRST, serving the ``booting`` state, because
    the boot work can block for minutes: a multi-host join waits for every
    pod in the slice, and the first payload compile is slow. If the server
    only came up afterwards, kubelet's liveness probe (which targets
    /version) would kill and restart the pod mid-join — precisely the
    crash-loop the degraded-state design exists to avoid.
    """
    started_at = time.time()
    boot_count = heartbeat.next_boot_count(cfg.state_dir)

    handle: RuntimeHandle = None  # assigned below; closures capture it

    # Every consumer (heartbeat, /healthz, /status) reads handle.check —
    # one source of truth, so a later update (e.g. a re-probe) cannot
    # leave the endpoints disagreeing about health.
    def build_heartbeat() -> dict:
        return {
            "name": cfg.name,
            "ok": handle.check.ok,
            "payload": cfg.payload,
            "boot_count": boot_count,
            "check": handle.check.to_dict(),
        }

    writer = heartbeat.HeartbeatWriter(
        cfg.state_dir, cfg.heartbeat_interval_s, build_heartbeat
    )

    # The profiler must not run before boot completes: a capture touches
    # the JAX backend, and initializing the backend from the handler
    # thread would permanently break the multi-host join below
    # (jax.distributed.initialize must precede any backend init).
    boot_complete = threading.Event()
    trace_capture = TraceCapture(cfg.state_dir)

    def profile(seconds: float) -> dict:
        if not boot_complete.is_set():
            raise CaptureUnavailable(
                "runtime is still booting; retry once /status shows the "
                "payload check"
            )
        return trace_capture.capture(seconds)

    def generate(doc: dict) -> dict:
        # The handler thread reads handle.serve_fn at request time: it is
        # None until the serve payload finishes restoring its model.
        if handle.serve_fn is None:
            raise GenerateUnavailable(
                "no generation backend yet (payload is not 'serve', it "
                "failed, or the runtime is still booting)"
            )
        return handle.serve_fn(doc)

    def trace_doc() -> dict | None:
        # GET /trace: the serving flight recorder as Chrome trace-event
        # JSON. Read at request time — None (404) until the serve
        # payload is live AND [payload] serving_trace is enabled.
        tracer = getattr(handle.serve_fn, "tracer", None)
        return tracer.export_chrome() if tracer is not None else None

    def profile_traces() -> list:
        # GET /profile/traces: on-disk profiler captures under
        # <state_dir>/traces/ (newest last; TraceCapture.list).
        return trace_capture.list()

    def slo_doc() -> dict | None:
        # GET /slo: the rolling SLI + burn-rate document. Read at
        # request time — None (404) until the serve payload is live
        # AND [payload] serving_slo is enabled.
        fn = getattr(handle.serve_fn, "slo", None)
        return fn() if fn is not None else None

    def bundle_doc() -> dict | None:
        # GET /debug/bundle: the flight-recorder bundle, assembled on
        # demand under one server lock acquisition so its metrics,
        # SLO state, and page books are mutually consistent.
        fn = getattr(handle.serve_fn, "bundle", None)
        return fn() if fn is not None else None

    def serve_degraded() -> str | None:
        # Lock-free by contract (workload.py attaches a plain attribute
        # read): /healthz is hit by liveness probes every few seconds
        # and must never queue behind the serving work lock.
        fn = getattr(handle.serve_fn, "degraded", None)
        return fn() if fn is not None else None

    def health_detail() -> dict | None:
        # Enriches an unhealthy /healthz body. A poisoned serving pool
        # under active recovery (runtime/recovery.py) reports 503
        # NON-terminal with a retry-after hint, so probes
        # (healthcheck.wait_healthy) keep polling through the heal;
        # without a supervisor — or after its escalation — the poison
        # is terminal (it only clears by rescheduling) and probes stop
        # polling early.
        reason = serve_degraded()
        if reason is not None:
            rec = getattr(handle.serve_fn, "recovery", None)
            if rec is not None:
                try:
                    doc = rec()
                except Exception:
                    doc = None
                if doc and doc.get("state") == "recovering":
                    out = {"reason": reason, "terminal": False,
                           "recovering": True}
                    # Always a retry hint: the supervisor's measured
                    # estimate when it has one, else the operator's
                    # configured reschedule window — a recovering 503
                    # must never leave the client guessing.
                    out["retry_after_s"] = (
                        doc["retry_after_s"]
                        if doc.get("retry_after_s") is not None
                        else cfg.serving_retry_after_s
                    )
                    # Capacity context (pages_free, pages_total,
                    # bucket) rides along when the serve path exposes
                    # its lock-free probe — operators triaging a
                    # recovery see how much pool the revive must
                    # rebuild without touching the work lock.
                    cap = getattr(handle.serve_fn, "capacity", None)
                    if cap is not None:
                        try:
                            out.update(cap())
                        except Exception:
                            pass
                    return out
            return {"reason": reason, "terminal": True}
        if not handle.check.ok and handle.check.error:
            return {"reason": handle.check.error}
        return None

    server = StatusServer(
        cfg.status_bind, cfg.status_port,
        snapshot=lambda: handle.snapshot(),
        healthy=lambda: handle.check.ok and serve_degraded() is None,
        profiler=profile,
        token=cfg.status_token,
        generator=generate,
        health_detail=health_detail,
        trace_doc=trace_doc,
        profile_traces=profile_traces,
        slo_doc=slo_doc,
        bundle_doc=bundle_doc,
    )
    handle = RuntimeHandle(
        cfg=cfg, check=_booting(), writer=writer, server=server,
        boot_count=boot_count, started_at=started_at,
        distributed=DistributedState(active=False),
    )
    # Sweep atomic-write leftovers before anything writes to the state
    # dir: a SIGKILL mid-dump strands `<name>.tmp` (a prefix dump can be
    # hundreds of MB) and no other writer exists this early, so every
    # surviving tmp is garbage by definition.
    swept = recovery.sweep_stranded_tmp(cfg.state_dir)
    if swept:
        print(f"[kvedge-boot] swept {len(swept)} stranded tmp file(s) "
              f"from the state dir: {', '.join(swept)}", flush=True)
    writer.beat_once()  # heartbeat visible before the server answers
    server.start()

    # Multi-host: join the cross-host JAX cluster BEFORE the payload, so
    # jax.devices() sees the whole slice. A join failure degrades the pod
    # (status stays queryable) instead of crash-looping it.
    topo_error = _topology_mismatch(cfg)
    if topo_error:
        handle.check = _degraded(topo_error)
    else:
        try:
            handle.distributed = maybe_initialize(cfg.distributed)
        except Exception as e:
            handle.check = _degraded(
                f"multi-host join failed "
                f"(num_processes={cfg.distributed.num_processes}): {e!r}"
            )
        else:
            handle.check = _run_payload(cfg, handle)
    boot_complete.set()  # safe to touch the backend from handler threads now
    writer.beat_once()  # refresh: the booting heartbeat is now stale
    return handle


def boot(config_path: str, once: bool = False, root: str = "/") -> None:
    """Entry for ``kvedge-runtime boot --config <path>``.

    ``root`` is accepted for signature symmetry with the other boot
    commands; paths inside the config were already rebased when
    ``kvedge-bootstrap apply`` wrote it.
    """
    del root
    with open(config_path, "r", encoding="utf-8") as fh:
        cfg = RuntimeConfig.parse(fh.read())
    handle = start_runtime(cfg)
    print(
        f"[kvedge-runtime] {cfg.name}: payload={cfg.payload} "
        f"ok={handle.check.ok} devices={handle.check.device_count} "
        f"status=:{handle.status_port} boot_count={handle.boot_count}",
        flush=True,
    )
    if not handle.check.ok:
        # Degraded: keep serving /status (debuggable from outside, like
        # ssh-ing into a VM whose payload failed), but say so loudly.
        print(f"[kvedge-runtime] DEGRADED: {handle.check.error}", flush=True)
    if once:
        handle.shutdown()
        return
    try:
        handle.writer.run()  # heartbeat loop on the main thread, forever
    finally:
        handle.shutdown()
