"""The JAX TPU runtime — the payload the accelerator provisions.

The reference's payload is the externally-installed Azure IoT Edge daemon:
after cloud-init applies the injected config, ``iotedge config apply``
starts a runtime that connects out and brokers messages, persisting state to
the PVC-backed disk (``README.md:88``). Nothing in the reference repo
executes after boot — the runtime is the capability being *hosted*.

kvedge-tpu's hosted runtime is JAX-native (SURVEY.md §7 step 4's minimum
end-to-end slice, widened):

* :mod:`kvedge_tpu.runtime.devicecheck` — TPU visibility probe + a pjit'd
  matmul across the configured device mesh;
* :mod:`kvedge_tpu.runtime.heartbeat` — durable heartbeat records in the
  PVC-backed state dir (the persistence-across-rescheduling proof);
* :mod:`kvedge_tpu.runtime.status` — the HTTP status endpoint exposed by
  the access Service (the ``kubectl get vmi`` / ssh-smoke analogue);
* :mod:`kvedge_tpu.runtime.boot` — orchestration: config -> payload ->
  heartbeat loop + status server.
"""
