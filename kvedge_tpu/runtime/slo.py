"""Rolling SLO engine: multi-window SLIs, error-budget burn rate, and
the occupancy timeline ring (SERVING.md rung 25).

The serving stack's observability through rung 24 is *cumulative*:
``/metrics`` exports monotone histograms and counters since boot, which
is the right contract for Prometheus but useless for a router or
autoscaler that needs to know how the pool is doing NOW. This module
closes that gap without touching the hot path: the decode loop already
visits quiescent boundaries (where checkpoints and page audits run);
at those boundaries it hands this engine one cheap snapshot of the
cumulative state, and every SLI is computed here, lazily, from the
DELTA between two ring entries — p99s by histogram-bucket
interpolation, goodput from token counters over wall time, shed rate
from the scheduler's shed counter.

Design constraints:

* **Deltas, not samples.** An SLI over window W is derived from
  ``newest - (newest entry at least W old)``. Cumulative snapshots make
  the math immune to missed boundaries (a saturated overlap pipeline
  visits few) — the window just stretches to the data that exists.
* **Reset-safe.** A counter that goes BACKWARDS between snapshots
  means the underlying server state was rebuilt (supervisor escalation
  replaced the pool, or a test recycled it). The ring rebases: cleared,
  counted in ``resets_total``, and every window starts fresh — a delta
  is never computed across a reset, so burn rates cannot go negative
  or explode. (``revive()`` preserves counters, so a plain heal is NOT
  a reset and windows ride straight through it.)
* **Bounded and lock-free here.** The ring is a ``deque(maxlen=...)``;
  ``observe`` is called under the serving work lock by its one writer,
  readers (``/slo``, ``/metrics``, the flight bundle) take consistent
  enough copies via ``list()`` (GIL-atomic for observability purposes).
* **Zero effect on tokens.** Nothing here touches device state or the
  decode schedule; the engine's only output consumed by the serving
  path is the knob-gated burn-rate shed input, default off and
  bit-identical when off (pinned by tests/test_slo.py).

Burn-rate semantics (the SRE error-budget formulation): with a
compliance target T (e.g. 0.99), the error budget is ``1 - T``; the
burn rate over a window is ``bad_fraction / (1 - T)`` where
``bad_fraction`` is the worst offender among the latency SLIs'
over-objective fractions and the shed rate. Burn 1.0 = spending the
budget exactly at sustainable pace; the alert fires only when BOTH the
fast and the slow window burn hot (the classic multi-window rule: the
slow window proves it is real, the fast window proves it is still
happening).
"""

from __future__ import annotations

import collections
import dataclasses

# Default ring depth: at the boundary-throttled snapshot cadence this
# covers hours of history in a few hundred small dicts.
DEFAULT_RING = 256

# Multi-window alert thresholds (Google SRE workbook's fast/slow page
# pair). Objectives are knobs; these multipliers are the convention.
BURN_FAST_ALERT = 14.0
BURN_SLOW_ALERT = 6.0


@dataclasses.dataclass(frozen=True)
class SloObjectives:
    """The configured objectives ([payload] serving_slo_* knobs)."""

    target: float = 0.99        # compliance target; budget = 1 - target
    ttft_ms: float = 1000.0     # TTFT p99 objective
    itl_ms: float = 250.0       # inter-token p99 objective
    queue_ms: float = 1000.0    # queue-wait p99 objective
    fast_window_s: float = 60.0
    slow_window_s: float = 600.0

    def validate(self) -> None:
        if not 0.0 < self.target < 1.0:
            raise ValueError("slo target must be in (0, 1)")
        for name in ("ttft_ms", "itl_ms", "queue_ms"):
            if getattr(self, name) <= 0.0:
                raise ValueError(f"slo {name} objective must be > 0")
        if not 0.0 < self.fast_window_s <= self.slow_window_s:
            raise ValueError(
                "slo windows must satisfy 0 < fast <= slow"
            )


# ---- histogram-delta math -------------------------------------------------
#
# Snapshots are models/serving._Hist.snapshot() dicts:
#   {"edges": [e0..e{n-1}], "counts": [c0..cn], "sum": s, "count": n}
# counts are PER-BUCKET (not cumulative); counts[i] falls in
# (edges[i-1], edges[i]], the final slot is the +Inf bucket.


def hist_delta(cur: dict, prev: dict) -> dict | None:
    """``cur - prev`` as a snapshot-shaped dict, or None on a reset
    (shape changed, or any count went backwards — the caller rebases)."""
    if (not isinstance(cur, dict) or not isinstance(prev, dict)
            or list(cur.get("edges", ())) != list(prev.get("edges", ()))
            or len(cur.get("counts", ())) != len(prev.get("counts", ()))):
        return None
    if cur["count"] < prev["count"]:
        return None
    counts = [c - p for c, p in zip(cur["counts"], prev["counts"])]
    if any(c < 0 for c in counts):
        return None
    return {
        "edges": list(cur["edges"]),
        "counts": counts,
        "sum": cur["sum"] - prev["sum"],
        "count": cur["count"] - prev["count"],
    }


def hist_quantile(snap: dict, q: float) -> float | None:
    """Bucket-interpolated quantile of a snapshot (None when empty).

    Linear interpolation inside the containing bucket, Prometheus
    ``histogram_quantile`` style; a quantile landing in the +Inf bucket
    clamps to the highest finite edge (the honest answer a bounded
    histogram can give)."""
    total = snap["count"]
    if total <= 0:
        return None
    edges = snap["edges"]
    rank = q * total
    cum = 0.0
    for i, c in enumerate(snap["counts"]):
        if c <= 0:
            continue
        if cum + c >= rank:
            if i >= len(edges):          # +Inf bucket
                return float(edges[-1])
            lo = edges[i - 1] if i > 0 else 0.0
            frac = (rank - cum) / c
            return float(lo + (edges[i] - lo) * frac)
        cum += c
    return float(edges[-1])


def hist_frac_over(snap: dict, threshold: float) -> float | None:
    """Fraction of observations ABOVE ``threshold`` (None when empty),
    interpolating linearly inside the bucket the threshold splits —
    the per-window error fraction of a latency SLI."""
    total = snap["count"]
    if total <= 0:
        return None
    edges = snap["edges"]
    over = 0.0
    for i, c in enumerate(snap["counts"]):
        if c <= 0:
            continue
        lo = edges[i - 1] if i > 0 else 0.0
        hi = edges[i] if i < len(edges) else float("inf")
        if threshold <= lo:
            over += c
        elif threshold < hi:
            if hi == float("inf"):
                # Can't interpolate into +Inf: count the whole bucket
                # as over (conservative — alerts early, never late).
                over += c
            else:
                over += c * (hi - threshold) / (hi - lo)
    return min(1.0, over / total)


class SloEngine:
    """Bounded ring of boundary snapshots -> rolling SLIs + burn rate.

    ``observe`` is the single-writer feed (serving decode loop, lock
    held); everything else is a pure reader over ring copies.
    """

    def __init__(self, objectives: SloObjectives,
                 ring: int = DEFAULT_RING):
        objectives.validate()
        self.objectives = objectives
        self._ring: collections.deque = collections.deque(maxlen=ring)
        self.snapshots_total = 0
        self.resets_total = 0
        # Snapshot throttle: a boundary-happy idle loop must not churn
        # the ring; one entry per ~1/32 of the fast window is plenty of
        # resolution for a window-delta computation.
        self.min_interval_s = min(
            5.0, max(0.01, objectives.fast_window_s / 32.0)
        )

    def __len__(self) -> int:
        return len(self._ring)

    # ---- writer (serving decode loop, work lock held) -------------------

    def observe(self, t: float, snap: dict) -> bool:
        """Append one cumulative snapshot ``snap`` stamped ``t``
        (tracer clock — ``time.perf_counter()``). Returns False when
        throttled. A snapshot whose counters went backwards rebases
        the ring (reset semantics above)."""
        if self._ring:
            t_last, last = self._ring[-1]
            if t - t_last < self.min_interval_s:
                return False
            if self._is_reset(snap, last):
                self._ring.clear()
                self.resets_total += 1
        self._ring.append((t, snap))
        self.snapshots_total += 1
        return True

    @staticmethod
    def _is_reset(cur: dict, prev: dict) -> bool:
        for key in ("tokens_total", "done_total", "shed_total"):
            if cur.get(key, 0) < prev.get(key, 0):
                return True
        for key in ("ttft_ms", "itl_ms", "queue_ms"):
            if hist_delta(cur.get(key, {}), prev.get(key, {})) is None:
                return True
        return False

    # ---- readers ---------------------------------------------------------

    def _entries(self) -> list:
        return list(self._ring)

    def _window_pair(self, entries: list, now: float,
                     window_s: float) -> tuple | None:
        """(base, head) snapshot pair covering ~``window_s`` ending at
        the newest entry; None when fewer than two entries exist. The
        base is the NEWEST entry at least ``window_s`` older than
        ``now`` (so the delta covers the whole window), falling back to
        the oldest entry when history is still shorter than the
        window."""
        if len(entries) < 2:
            return None
        head = entries[-1]
        base = entries[0]
        cutoff = now - window_s
        for t, snap in entries:
            if t <= cutoff:
                base = (t, snap)
            else:
                break
        if base[0] >= head[0]:
            return None
        return base, head

    def slis(self, window_s: float, now: float | None = None) -> dict:
        """The window's SLIs, or {} when the window is empty (fewer
        than two snapshots, or a reset just rebased the ring)."""
        entries = self._entries()
        if now is None:
            now = entries[-1][0] if entries else 0.0
        pair = self._window_pair(entries, now, window_s)
        if pair is None:
            return {}
        (t0, prev), (t1, cur) = pair
        span = t1 - t0
        out: dict = {"window_s": round(span, 3)}
        for key, objective in (
            ("ttft_ms", self.objectives.ttft_ms),
            ("itl_ms", self.objectives.itl_ms),
            ("queue_ms", self.objectives.queue_ms),
        ):
            delta = hist_delta(cur.get(key, {}), prev.get(key, {}))
            if delta is None or delta["count"] <= 0:
                continue
            out[key.replace("_ms", "_p99_ms")] = round(
                hist_quantile(delta, 0.99), 3
            )
            out[key.replace("_ms", "_frac_over")] = round(
                hist_frac_over(delta, objective), 6
            )
        d_tokens = cur.get("tokens_total", 0) - prev.get("tokens_total", 0)
        d_done = cur.get("done_total", 0) - prev.get("done_total", 0)
        d_shed = cur.get("shed_total", 0) - prev.get("shed_total", 0)
        out["requests_done"] = max(0, d_done)
        out["requests_shed"] = max(0, d_shed)
        out["goodput_tps"] = round(max(0, d_tokens) / span, 3) \
            if span > 0 else 0.0
        offered = max(0, d_done) + max(0, d_shed)
        out["shed_rate"] = round(max(0, d_shed) / offered, 6) \
            if offered else 0.0
        return out

    def error_fraction(self, window_s: float,
                       now: float | None = None) -> float | None:
        """The window's worst bad-event fraction: max of each latency
        SLI's over-objective fraction and the shed rate. None = no
        data (an empty window burns nothing)."""
        s = self.slis(window_s, now)
        if not s:
            return None
        fracs = [v for k, v in s.items() if k.endswith("_frac_over")]
        fracs.append(s.get("shed_rate", 0.0))
        return max(fracs) if fracs else None

    def burn(self, window_s: float,
             now: float | None = None) -> float | None:
        ef = self.error_fraction(window_s, now)
        if ef is None:
            return None
        return ef / (1.0 - self.objectives.target)

    def alert(self, now: float | None = None) -> bool:
        """The multi-window page condition: both windows burning hot.
        Missing data in either window is healthy (no alert) — an idle
        or freshly-rebased pool must not page anyone."""
        fast = self.burn(self.objectives.fast_window_s, now)
        slow = self.burn(self.objectives.slow_window_s, now)
        return (fast is not None and slow is not None
                and fast >= BURN_FAST_ALERT and slow >= BURN_SLOW_ALERT)

    def doc(self, now: float | None = None) -> dict:
        """The ``GET /slo`` document (and the flight bundle's SLO/burn
        state): objectives, both windows' SLIs and burn, the alert."""
        obj = self.objectives
        fast = self.slis(obj.fast_window_s, now)
        slow = self.slis(obj.slow_window_s, now)
        return {
            "objectives": dataclasses.asdict(obj),
            "burn_alert_thresholds": {
                "fast": BURN_FAST_ALERT, "slow": BURN_SLOW_ALERT,
            },
            "windows": {
                "fast": {**fast, "burn": self.burn(obj.fast_window_s,
                                                   now)},
                "slow": {**slow, "burn": self.burn(obj.slow_window_s,
                                                   now)},
            },
            "alert": self.alert(now),
            "snapshots": len(self._ring),
            "snapshots_total": self.snapshots_total,
            "resets_total": self.resets_total,
        }

    def metrics(self) -> dict:
        """Flat numeric gauges for ``/metrics`` (0.0 = no data — a
        Prometheus series must exist even before the first window
        fills, or recording rules break on the gap)."""
        obj = self.objectives
        fast = self.slis(obj.fast_window_s)
        burn_fast = self.burn(obj.fast_window_s)
        burn_slow = self.burn(obj.slow_window_s)
        return {
            "slo_ttft_p99_ms": fast.get("ttft_p99_ms", 0.0),
            "slo_itl_p99_ms": fast.get("itl_p99_ms", 0.0),
            "slo_queue_p99_ms": fast.get("queue_p99_ms", 0.0),
            "slo_goodput_tps": fast.get("goodput_tps", 0.0),
            "slo_shed_rate": fast.get("shed_rate", 0.0),
            "slo_burn_fast": burn_fast if burn_fast is not None else 0.0,
            "slo_burn_slow": burn_slow if burn_slow is not None else 0.0,
            "slo_alert": 1 if self.alert() else 0,
            "slo_snapshots_total": self.snapshots_total,
            "slo_resets_total": self.resets_total,
        }


class OccupancyRing:
    """Bounded timeline of occupancy samples (HBM pages, bucket,
    prefix residency, journal bytes) taken at quiescent boundaries.

    Single writer (decode loop, lock held); readers copy. Exported two
    ways: the latest sample flattens into ``/metrics`` gauges
    (``serve_occupancy_*``), and the whole tail merges into the Chrome
    trace as counter tracks (ph="C") so Perfetto draws the pool's
    occupancy under the span timeline it already shows."""

    def __init__(self, capacity: int):
        if capacity < 1:
            raise ValueError("occupancy ring capacity must be >= 1")
        self._ring: collections.deque = collections.deque(
            maxlen=int(capacity)
        )
        self.samples_total = 0

    def __len__(self) -> int:
        return len(self._ring)

    def sample(self, t: float, fields: dict) -> None:
        self._ring.append((t, fields))
        self.samples_total += 1

    def last(self) -> dict | None:
        if not self._ring:
            return None
        return dict(self._ring[-1][1])

    def tail(self, n: int = 64) -> list[dict]:
        """The newest ``n`` samples, oldest first, JSON-safe — the
        flight bundle's occupancy timeline."""
        return [
            {"t": round(t, 6), **fields}
            for t, fields in list(self._ring)[-n:]
        ]

    def chrome_counters(self, epoch: float) -> list[dict]:
        """The ring as Chrome counter events (ph="C"), stacked per
        sample under one 'occupancy' track; ts microseconds from the
        tracer ``epoch`` (both clocks are ``time.perf_counter()``)."""
        return [
            {
                "name": "occupancy",
                "cat": "occupancy",
                "ph": "C",
                "ts": round((t - epoch) * 1e6, 1),
                "pid": 1,
                "tid": 0,
                "args": dict(fields),
            }
            for t, fields in list(self._ring)
        ]
