"""The transformer-probe payload: prove real sharded training works.

A step up from the matmul device check: build the flagship transformer on
the configured mesh, run one jitted, dp×tp-sharded train step, and verify
the loss is finite and near log(vocab) for random data. This is the
strongest "the provisioned runtime actually works" signal the status
endpoint can report.
"""

from __future__ import annotations

import time

from kvedge_tpu.config.runtime_config import RuntimeConfig
from kvedge_tpu.runtime.devicecheck import DeviceCheckResult, run_device_check

# Deliberately tiny: the probe verifies machinery, not throughput.
PROBE_VOCAB = 512
PROBE_D_MODEL = 128
PROBE_LAYERS = 2
PROBE_SEQ = 64
PROBE_BATCH_PER_DEVICE = 2


def run_transformer_probe(cfg: RuntimeConfig) -> DeviceCheckResult:
    # The matmul device check runs first: fail fast on visibility problems
    # with a cheaper, clearer error before compiling a model.
    base = run_device_check(cfg)
    if not base.ok:
        return base

    import dataclasses
    import math

    import jax
    import jax.numpy as jnp

    from kvedge_tpu.models import (
        TransformerConfig, init_params, make_train_step,
    )
    from kvedge_tpu.parallel import build_mesh, shard_batch, shard_params

    mesh = build_mesh(cfg.mesh)
    axis_sizes = dict(zip(base.mesh_axes, base.mesh_shape))
    model_axis = axis_sizes.get("model", 1)
    # A `seq` axis in the operator's mesh selects the long-context path:
    # the probe then exercises ring attention's ppermute ring, not just
    # the annotation-sharded dp×tp step.
    ring = axis_sizes.get("seq", 1) > 1
    tcfg = TransformerConfig(
        vocab=PROBE_VOCAB,
        d_model=PROBE_D_MODEL,
        n_heads=max(4, model_axis),
        n_layers=PROBE_LAYERS,
        d_ff=4 * PROBE_D_MODEL,
        max_seq=PROBE_SEQ,
        attention="ring" if ring else "naive",
    )
    try:
        key = jax.random.PRNGKey(0)
        params = shard_params(mesh, init_params(key, tcfg))
        init_opt, train_step = make_train_step(tcfg, mesh=mesh if ring else None)
        opt_state = init_opt(params)
        batch = shard_batch(
            mesh,
            jax.random.randint(
                key,
                (PROBE_BATCH_PER_DEVICE * base.device_count, PROBE_SEQ + 1),
                0, tcfg.vocab, dtype=jnp.int32,
            ),
        )
        start = time.perf_counter()
        params, opt_state, loss = train_step(params, opt_state, batch)
        loss = float(loss)
        elapsed_ms = (time.perf_counter() - start) * 1000.0
    except Exception as e:
        return dataclasses.replace(
            base, ok=False, error=f"transformer probe failed: {e!r}",
        )

    # Untrained model on random tokens: loss ≈ ln(vocab). Allow a wide band;
    # NaN/inf or wildly-off values mean broken math or sharding.
    expected = math.log(tcfg.vocab)
    if not math.isfinite(loss) or abs(loss - expected) > 0.5 * expected:
        return dataclasses.replace(
            base, ok=False,
            error=f"probe loss {loss:.3f} far from ln(V)={expected:.3f}",
        )
    return dataclasses.replace(
        base, probe_ms=elapsed_ms, probe_checksum=loss,
    )
