"""Workload payloads: prove real sharded training / serving works.

A step up from the matmul device check:

* ``transformer-probe`` builds the flagship transformer on the configured
  mesh, runs one jitted, dp×tp-sharded train step, and verifies the loss
  is finite and near log(vocab) for random data.
* ``inference-probe`` exercises the serving path instead: GQA prefill +
  KV-cache greedy decode (models/decode.py) cross-checked token-for-token
  against the cache-less forward pass — broken cache plumbing cannot agree
  with teacher forcing.

These are the strongest "the provisioned runtime actually works" signals
the status endpoint can report.
"""

from __future__ import annotations

import collections
import threading
import time

from kvedge_tpu.config.runtime_config import RuntimeConfig
from kvedge_tpu.runtime.devicecheck import DeviceCheckResult, run_device_check

# Deliberately tiny: the probe verifies machinery, not throughput. The
# shape itself is models/transformer.py PRESETS["probe"] — the same table
# the [model] TOML section resolves against — so the probes and an
# unconfigured payload can never drift apart.
PROBE_VOCAB = 512
PROBE_D_MODEL = 128
PROBE_LAYERS = 2
PROBE_SEQ = 64
PROBE_BATCH_PER_DEVICE = 2


class MeshConfigError(ValueError):
    """The operator's mesh cannot run this payload (clear config message)."""


def derive_model_config(cfg: RuntimeConfig, *, seq: int):
    """(TransformerConfig, mesh) for a payload: ``[model]`` x the mesh.

    One derivation shared by the transformer-probe, ``train``, ``eval``,
    and ``serve`` payloads, so every mesh family the probe exercises is a
    mesh family training (and checkpoint-compatible serving) supports.

    The architecture comes from the ``[model]`` TOML section: a preset
    ("probe" by default, "flagship" for the bench model —
    models/transformer.py PRESETS) overridden by any explicitly-set
    field. The mesh then constrains execution:

    * ``seq`` axis -> sequence-parallel attention (ring by default, or
      the strategy named by ``[payload] attention``);
    * ``expert`` axis -> mixture-of-experts FFN sharded over it;
    * ``stage`` axis -> pipelined layer stack; composes with ``model``,
      ``expert``, and ``seq`` (ring or ulysses — the seq axis joins the
      pipeline's manual axes and the strategy's per-device body runs
      inside them);
    * ``model`` axis -> Megatron tensor parallelism (annotation-only).

    Merge discipline: preset-derived values ADAPT to the mesh (head
    count rounds up for ulysses, depth rounds up to a stage multiple,
    expert count follows the expert axis) — the same templated config
    must boot across deployment sizes. Explicitly-set ``[model]`` values
    are authoritative: a mesh they cannot run on raises
    :class:`MeshConfigError`, never a silent adjustment — the operator
    asked for a specific architecture and must get exactly it or a
    clear refusal.
    """
    from kvedge_tpu.models import PRESETS, TransformerConfig
    from kvedge_tpu.parallel import build_mesh

    mesh = build_mesh(cfg.mesh)
    axis_sizes = dict(mesh.shape)
    model_axis = axis_sizes.get("model", 1)
    sp = axis_sizes.get("seq", 1)
    attention = cfg.payload_attention or ("ring" if sp > 1 else "naive")
    if sp > 1 and attention not in ("ring", "ulysses"):
        # The old data x model-only guard existed to keep mesh axes from
        # being SILENTLY ignored; an explicit [payload] attention override
        # must not reopen that hole — a seq axis with local attention
        # would train replicas and report success.
        raise MeshConfigError(
            f"mesh declares a 'seq' axis but [payload] attention = "
            f"{attention!r} would silently ignore it (the axis devices "
            "would hold replicas); use attention = \"ring\"/\"ulysses\" "
            "or drop the seq axis"
        )
    if "seq" not in axis_sizes and attention in ("ring", "ulysses"):
        # Presence, not size: a seq axis that resolves to 1 on a small
        # deployment still exists in the mesh, and the degenerate
        # one-shard ring runs fine — the same templated config must boot
        # across deployment sizes.
        raise MeshConfigError(
            f"[payload] attention = {attention!r} is sequence-parallel "
            "and needs a 'seq' axis in the mesh"
        )
    spec = cfg.model
    base = PRESETS[spec.preset or "probe"]
    n_heads = spec.n_heads or max(base["n_heads"], model_axis)
    group = sp * model_axis
    if attention == "ulysses" and n_heads % group:
        # Ulysses scatters each model shard's heads over the seq axis:
        # heads must divide by sp x tp (parallel/ulysses.py).
        if spec.n_heads:
            raise MeshConfigError(
                f"[model] n_heads = {spec.n_heads} cannot run ulysses "
                f"attention on this mesh: the head count must divide by "
                f"seq x model = {group}"
            )
        n_heads = group * -(-n_heads // group)  # round up, preset-derived
    n_experts_axis = axis_sizes.get("expert", 1)
    if spec.experts:
        n_experts = spec.experts
        if n_experts % n_experts_axis:
            raise MeshConfigError(
                f"[model] experts = {n_experts} must divide by the "
                f"mesh's expert axis ({n_experts_axis}) — each device "
                "holds E/ep whole experts (parallel/sharding.py)"
            )
    else:
        n_experts = n_experts_axis if n_experts_axis > 1 else 0
    if not n_experts and (spec.expert_top_k or spec.expert_capacity_factor):
        # The authoritative-override contract cuts both ways: MoE knobs
        # on a model that resolved dense would be silently dead config.
        raise MeshConfigError(
            "[model] expert_top_k/expert_capacity_factor are set but the "
            "model is dense (no [model] experts and no 'expert' mesh "
            "axis) — set experts = N or drop the MoE knobs"
        )
    stages = axis_sizes.get("stage", 1)
    n_layers = spec.n_layers or base["n_layers"]
    if stages > 1 and n_layers % stages:
        if spec.n_layers:
            raise MeshConfigError(
                f"[model] n_layers = {n_layers} must divide by the "
                f"mesh's stage axis ({stages}) — each stage holds L/S "
                "whole layers"
            )
        n_layers = stages * -(-n_layers // stages)  # round up
    top_k = spec.expert_top_k or 1
    # Default: provably drop-free capacity (factor * top_k >= E): the
    # same derived config feeds train AND serve, and serving routes
    # droplessly — a binding training capacity would make POST /generate
    # silently disagree with the trained model (the
    # warn_if_train_serve_divergence regime). Operators who accept that
    # divergence set [model] expert_capacity_factor themselves.
    capacity = (spec.expert_capacity_factor
                or max(n_experts, 1) / top_k)
    # pp x tp and pp x ep run fp32: bf16 contractions against
    # auto-partitioned model/expert axes crash XLA's CPU backend (see
    # parallel/pipeline.py), and payloads must be portable across the
    # CPU test mesh and real TPUs.
    import jax

    dtype = ("float32"
             if stages > 1 and (model_axis > 1 or n_experts > 1)
             and jax.default_backend() == "cpu"
             else TransformerConfig.dtype)
    tcfg = TransformerConfig(
        vocab=spec.vocab or base["vocab"],
        d_model=spec.d_model or base["d_model"],
        n_heads=n_heads,
        n_kv_heads=spec.n_kv_heads or base["n_kv_heads"],
        n_layers=n_layers,
        d_ff=spec.d_ff or base["d_ff"],
        max_seq=seq,
        dtype=dtype,
        attention=attention,
        n_experts=n_experts,
        expert_top_k=top_k,
        expert_capacity_factor=float(capacity),
        pipeline_stages=stages if stages > 1 else 0,
        pipeline_schedule=spec.pipeline_schedule or "gpipe",
        paged_attention=cfg.payload_paged_attention or "auto",
    )
    try:
        # Cross-field architecture errors (d_model % n_heads, GQA head
        # divisibility, top_k vs experts) surface as the same clear
        # config-refusal every other bad combination gets.
        tcfg.validate()
    except ValueError as e:
        raise MeshConfigError(f"[model] configuration is invalid: {e}") \
            from e
    return tcfg, mesh


def run_transformer_probe(cfg: RuntimeConfig) -> DeviceCheckResult:
    # The matmul device check runs first: fail fast on visibility problems
    # with a cheaper, clearer error before compiling a model.
    base = run_device_check(cfg)
    if not base.ok:
        return base

    import dataclasses
    import math

    import jax
    import jax.numpy as jnp

    from kvedge_tpu.models import init_params, make_train_step
    from kvedge_tpu.parallel import shard_batch, shard_params

    try:
        tcfg, mesh = derive_model_config(cfg, seq=PROBE_SEQ)
    except MeshConfigError as e:
        # A healthy runtime with an un-runnable mesh combination: surface
        # a clear config message, not a generic "probe failed" traceback.
        return dataclasses.replace(base, ok=False, error=str(e))
    try:
        # Inside the try: an sp-derived head count can make the model
        # config itself invalid (d_model % n_heads), and that must surface
        # as a structured probe failure like every other error here.
        key = jax.random.PRNGKey(0)
        params = shard_params(mesh, init_params(key, tcfg))
        init_opt, train_step = make_train_step(
            tcfg, mesh=mesh if tcfg.needs_mesh else None
        )
        opt_state = init_opt(params)
        batch = shard_batch(
            mesh,
            jax.random.randint(
                key,
                (PROBE_BATCH_PER_DEVICE * base.device_count, PROBE_SEQ + 1),
                0, tcfg.vocab, dtype=jnp.int32,
            ),
        )
        start = time.perf_counter()
        params, opt_state, loss = train_step(params, opt_state, batch)
        loss = float(loss)
        elapsed_ms = (time.perf_counter() - start) * 1000.0
    except Exception as e:
        return dataclasses.replace(
            base, ok=False, error=f"transformer probe failed: {e!r}",
        )

    # Untrained model on random tokens: loss ≈ ln(vocab). Allow a wide band;
    # NaN/inf or wildly-off values mean broken math or sharding.
    expected = math.log(tcfg.vocab)
    if not math.isfinite(loss) or abs(loss - expected) > 0.5 * expected:
        return dataclasses.replace(
            base, ok=False,
            error=f"probe loss {loss:.3f} far from ln(V)={expected:.3f}",
        )
    return dataclasses.replace(
        base, probe_ms=elapsed_ms, probe_checksum=loss,
    )


def run_train_payload(cfg: RuntimeConfig) -> DeviceCheckResult:
    """The "train" payload: resumable training over a corpus on the PVC.

    The full persistence story, live: train ``[payload] steps`` total
    steps over the ``corpus`` token file, checkpointing through the
    state volume. A rescheduled pod restores the latest checkpoint and
    reopens the feeder at exactly that batch (deterministic order), so
    steps count from 0 across ALL pod generations — the payload-level
    analogue of EdgeHub's PVC-backed message state (reference
    ``README.md:88``). A run whose target was already reached reports ok
    immediately.

    On a multi-host slice (``jax.process_count() > 1``) each process
    feeds its own rows of the global batch (sharded feeder offsets) and
    the global array is assembled with
    ``jax.make_array_from_process_local_data``; checkpoints then REQUIRE
    ``[runtime] checkpoint_dir`` on shared storage. A killed slice
    resumes to the same trajectory as an uninterrupted single-process
    run (tests/test_distributed.py).
    """
    base = run_device_check(cfg)
    if not base.ok:
        return base

    import dataclasses
    import functools
    import math

    import jax
    import numpy as np

    from kvedge_tpu.data import open_feeder
    from kvedge_tpu.models import TransformerConfig
    from kvedge_tpu.models.training import run_training
    from kvedge_tpu.parallel import build_mesh, shard_batch, shard_tree
    from kvedge_tpu.runtime import heartbeat
    from kvedge_tpu.runtime.checkpoint import StateCheckpointer

    error, geometry = _feed_geometry(cfg, base, "train")
    if error is not None:
        return error
    local_rows, shard_offset, n_proc = geometry
    # The model derives from the mesh exactly like the probe's (seq axis
    # -> sequence-parallel attention, expert -> MoE, stage -> pipelined
    # layers): every mesh family the probe exercises, training trains.
    try:
        tcfg, mesh = train_model_config(cfg)
    except MeshConfigError as e:
        return dataclasses.replace(base, ok=False, error=str(e))
    feeder = None
    try:
        # Peek the resume point first: the feeder must start at the
        # batch the restored step would consume next.
        with StateCheckpointer(
            cfg.state_dir, checkpoint_dir=cfg.checkpoint_dir
        ) as ckpt:
            resume_step = ckpt.latest_step() or 0
        feeder = open_feeder(
            cfg.train_corpus, batch=local_rows, seq=cfg.train_seq,
            start_batch=resume_step, global_batch=cfg.train_batch,
            shard_offset=shard_offset,
        )
        batches = _global_batches(cfg, tcfg, mesh, feeder, n_proc)

        last_write = 0.0

        def on_step(step: int, loss: float) -> None:
            # Live progress into /status (and the PVC, so the last known
            # step/loss survives a crash). Best-effort telemetry:
            # throttled off the hot loop (always written on the final
            # step), non-finite losses recorded as null (bare NaN in the
            # persisted JSON would corrupt every later /status body),
            # and a failed write must never abort healthy training.
            nonlocal last_write
            now = time.time()
            if step < cfg.train_steps and now - last_write < 1.0:
                return
            last_write = now
            try:
                heartbeat.write_train_progress(cfg.state_dir, {
                    "step": step,
                    "target_steps": cfg.train_steps,
                    "loss": round(loss, 6) if math.isfinite(loss) else None,
                    "ts": now,
                })
            except OSError:
                pass

        start = time.perf_counter()
        result = run_training(
            tcfg, cfg.state_dir, num_steps=cfg.train_steps,
            batches=batches, checkpoint_every=cfg.train_checkpoint_every,
            prepare=functools.partial(shard_tree, mesh),
            on_step=on_step, checkpoint_dir=cfg.checkpoint_dir,
            mesh=mesh if tcfg.needs_mesh else None,
        )
        elapsed_ms = (time.perf_counter() - start) * 1000.0
    except Exception as e:
        return dataclasses.replace(
            base, ok=False, error=f"train payload failed: {e!r}",
        )
    finally:
        if feeder is not None:
            feeder.close()
    final_loss = result.losses[-1] if result.losses else float("nan")
    if result.losses and not math.isfinite(final_loss):
        return dataclasses.replace(
            base, ok=False,
            error=f"training diverged: loss {final_loss}",
        )
    return dataclasses.replace(
        base, probe_ms=elapsed_ms,
        probe_checksum=final_loss if result.losses else 0.0,
    )


def train_model_config(cfg: RuntimeConfig):
    """The train payload's model, derived from the runtime config.

    One definition shared by ``train`` and ``serve`` (via
    :func:`derive_model_config`) so the serving payload restores exactly
    the architecture training checkpointed — a drift here would surface
    as an orbax tree-structure mismatch.
    """
    return derive_model_config(cfg, seq=cfg.train_seq)


def _feed_geometry(cfg: RuntimeConfig, base: DeviceCheckResult, kind: str):
    """Shared prechecks + per-host feed geometry for corpus payloads.

    Returns ``(error_result | None, (local_rows, shard_offset, n_proc))``.
    One definition for ``train`` and ``eval`` so the two can never
    disagree on batch/mesh divisibility rules or multi-host requirements
    — a clear message at /status beats an opaque sharding traceback.
    """
    import dataclasses

    import jax

    axis_sizes = dict(zip(base.mesh_axes, base.mesh_shape))
    data_size = axis_sizes.get("data", 1)
    if cfg.train_batch % max(1, data_size):
        return dataclasses.replace(
            base, ok=False,
            error=(
                f"[payload] batch = {cfg.train_batch} must divide by the "
                f"mesh's data axis size ({data_size}) — it is the global "
                "batch, sharded across data-parallel devices"
            ),
        ), None
    n_proc = jax.process_count()
    if n_proc > 1:
        if not cfg.checkpoint_dir:
            return dataclasses.replace(
                base, ok=False,
                error=(
                    f"multi-host {kind} needs [runtime] checkpoint_dir "
                    "on shared storage (a shared-filesystem mount or "
                    "gs://bucket/prefix): per-host PVCs cannot hold a "
                    "slice-wide checkpoint (README 'Multi-host')"
                ),
            ), None
        if cfg.train_batch % n_proc:
            return dataclasses.replace(
                base, ok=False,
                error=(
                    f"[payload] batch = {cfg.train_batch} must divide by "
                    f"the process count ({n_proc}) for per-host feeding"
                ),
            ), None
    local_rows = cfg.train_batch // n_proc
    return None, (local_rows, jax.process_index() * local_rows, n_proc)


def _global_batches(cfg: RuntimeConfig, tcfg, mesh, feeder, n_proc: int):
    """Iterator of sharded global [B, T+1] batches from a (possibly
    host-sharded) feeder. Token ids fold into the payload vocab (% V):
    deterministic, so resume stays exact. Single definition for ``train``
    and ``eval`` — how batches are assembled is part of the resume
    contract and must not fork."""
    import jax

    from kvedge_tpu.parallel import shard_batch

    if n_proc > 1:
        import numpy as np
        from jax.sharding import NamedSharding

        from kvedge_tpu.parallel.sharding import batch_spec

        sharding = NamedSharding(mesh, batch_spec(mesh))
        global_shape = (cfg.train_batch, cfg.train_seq + 1)
        for batch in feeder:
            yield jax.make_array_from_process_local_data(
                sharding, np.asarray(batch) % tcfg.vocab, global_shape
            )
    else:
        for batch in feeder:
            yield shard_batch(mesh, batch % tcfg.vocab)


def _restore_latest_params(cfg: RuntimeConfig, tcfg, mesh=None):
    """(step | None, params) from the latest checkpoint, or the fresh
    deterministic init when the volume has none.

    Shared by ``eval`` and ``serve``: the abstract tree MUST mirror
    models/training.py's ``fresh_state`` exactly (params AND optimizer
    state, seed 0) — that is the structure orbax wrote, and drift
    surfaces only as a tree-structure mismatch at restore time, so there
    is exactly one definition of it outside the trainer.

    With ``mesh``, the restore is placement-aware: orbax restores each
    param straight into its ``NamedSharding`` (the same rules training
    sharded it with), so a tp/ep-sharded checkpoint lands distributed —
    never materialized on one device first. Either way the optimizer
    moments are PLACEHOLDER-skipped, not restored-then-discarded: a
    serve pod sized for params + KV pool must not pay 3x params memory
    for Adam state it will never read.
    """
    import jax
    import orbax.checkpoint as ocp

    from kvedge_tpu.models import init_params, make_train_step
    from kvedge_tpu.parallel import abstract_shard_tree, shard_params
    from kvedge_tpu.runtime.checkpoint import StateCheckpointer

    init_opt, _ = make_train_step(tcfg)

    def fresh_state():
        p = init_params(jax.random.PRNGKey(0), tcfg)
        return {"params": p, "opt_state": init_opt(p)}

    abstract = jax.eval_shape(fresh_state)
    if mesh is not None:
        abstract = abstract_shard_tree(mesh, abstract)
    # Older orbax has no PLACEHOLDER: fall back to restoring the full
    # tree and dropping the moments afterwards — correct either way, the
    # skip is purely a memory optimisation.
    placeholder = getattr(ocp, "PLACEHOLDER", None)
    partial = placeholder is not None
    if partial:
        abstract["opt_state"] = jax.tree_util.tree_map(
            lambda _: placeholder, abstract["opt_state"]
        )
    with StateCheckpointer(
        cfg.state_dir, checkpoint_dir=cfg.checkpoint_dir
    ) as ckpt:
        restored = ckpt.restore_latest(abstract, partial=partial)
    if restored is not None:
        step, tree = restored
        return step, tree["params"]
    # fresh_state stays abstract — materializing it would allocate the
    # optimizer moments only to discard them.
    params = init_params(jax.random.PRNGKey(0), tcfg)
    return None, params if mesh is None else shard_params(mesh, params)


def run_eval_payload(cfg: RuntimeConfig) -> DeviceCheckResult:
    """The ``eval`` payload: held-out loss for the checkpointed model.

    The measurement half of the train/eval/serve loop: restores the
    latest checkpoint exactly like ``serve`` does (same derived model,
    same state tree) and computes the mean next-token cross-entropy over
    ``[payload] steps`` deterministic batches of ``corpus`` — no
    gradients, no optimizer, nothing written. The loss lands in
    ``probe_checksum`` (and therefore /status and the heartbeat), so an
    operator can read a checkpoint's quality from the same surface that
    reports everything else.

    Held-out convention: ``[payload] eval_corpus`` names the held-out
    split (produce one with ``kvedge-tpu corpus --holdout``); when it is
    unset, eval falls back to the TRAINING corpus and warns loudly that
    the number is training loss, not held-out loss. The batch order is
    the feeder's deterministic order from batch 0 either way.
    """
    base = run_device_check(cfg)
    if not base.ok:
        return base

    import dataclasses
    import functools
    import math

    import jax

    from kvedge_tpu.data import open_feeder
    from kvedge_tpu.models import loss_fn

    error, geometry = _feed_geometry(cfg, base, "eval")
    if error is not None:
        return error
    local_rows, shard_offset, n_proc = geometry

    feeder = None
    try:
        tcfg, mesh = train_model_config(cfg)
        step, params = _restore_latest_params(cfg, tcfg, mesh=mesh)

        # Pure next-token cross-entropy: zeroing the aux weight drops the
        # MoE router's load-balancing term from the reported number —
        # eval measures model quality, not the training regularizer.
        eval_tcfg = dataclasses.replace(tcfg, moe_aux_weight=0.0)
        eval_loss = jax.jit(functools.partial(
            loss_fn, cfg=eval_tcfg,
            mesh=mesh if tcfg.needs_mesh else None,
        ))
        corpus = cfg.eval_corpus or cfg.train_corpus
        held_out = bool(cfg.eval_corpus)
        if not held_out:
            print(
                "[kvedge-eval] WARNING: no [payload] eval_corpus set — "
                "evaluating on the TRAINING corpus; this number is "
                "training loss, NOT held-out loss (split one with "
                "`kvedge-tpu corpus --holdout`)",
                flush=True,
            )
        feeder = open_feeder(
            corpus, batch=local_rows, seq=cfg.train_seq,
            global_batch=cfg.train_batch, shard_offset=shard_offset,
        )
        batches = _global_batches(cfg, tcfg, mesh, feeder, n_proc)
        start = time.perf_counter()
        total = 0.0
        for _ in range(cfg.train_steps):
            total += float(eval_loss(params, next(batches)))
        mean_loss = total / cfg.train_steps
        elapsed_ms = (time.perf_counter() - start) * 1000.0
        print(
            f"[kvedge-eval] checkpoint_step={step} batches="
            f"{cfg.train_steps} held_out={held_out} loss={mean_loss:.4f} "
            f"ppl={math.exp(min(mean_loss, 30.0)):.2f}",
            flush=True,
        )
    except MeshConfigError as e:
        return dataclasses.replace(base, ok=False, error=str(e))
    except Exception as e:
        return dataclasses.replace(
            base, ok=False, error=f"eval payload failed: {e!r}",
        )
    finally:
        if feeder is not None:
            feeder.close()
    if not math.isfinite(mean_loss):
        return dataclasses.replace(
            base, ok=False, error=f"eval loss is {mean_loss}",
        )
    return dataclasses.replace(
        base, probe_ms=elapsed_ms, probe_checksum=mean_loss,
    )


class _ServeCounters:
    """Request accounting shared by the single-host serve path and the
    multi-host leader — ONE definition of the ``kvedge_serve_*`` counter
    vocabulary and of the exception -> outcome-bucket mapping
    (ValueError -> rejected/400, GenerateUnavailable and retryable
    ServingFailures -> unavailable/503, anything else — including
    terminal ServingFailures like SliceFollowerLost -> errors/500), so
    the two paths can never drift on the /metrics contract."""

    def __init__(self):
        import threading

        self._lock = threading.Lock()
        self.data = {
            "requests_total": 0,
            "completed_total": 0,
            "rejected_total": 0,
            "unavailable_total": 0,
            "errors_total": 0,
            "tokens_generated_total": 0,
            "last_latency_ms": 0.0,
            "latency_ms_sum": 0.0,
        }

    def count(self, key: str, n: int = 1) -> None:
        with self._lock:
            self.data[key] += n

    def count_outcome(self, exc: Exception) -> None:
        from kvedge_tpu.runtime.failures import ServingFailure
        from kvedge_tpu.runtime.status import GenerateUnavailable

        if isinstance(exc, GenerateUnavailable):
            self.count("unavailable_total")
        elif isinstance(exc, ServingFailure) and exc.retryable:
            # e.g. PoolPoisoned reaching a streamed request mid-flight
            # (the non-streamed path maps it to GenerateUnavailable
            # before it gets here): the client may retry after the
            # reschedule, so it is unavailability, not a server error.
            self.count("unavailable_total")
        elif isinstance(exc, ValueError):
            self.count("rejected_total")
        else:
            self.count("errors_total")

    def finish(self, start: float) -> None:
        import time

        ms = (time.perf_counter() - start) * 1000.0
        with self._lock:
            self.data["completed_total"] += 1
            self.data["last_latency_ms"] = ms
            self.data["latency_ms_sum"] += ms

    def snapshot(self) -> dict:
        with self._lock:
            return dict(self.data)


def _run_multihost_serve(cfg: RuntimeConfig, base, tcfg, mesh):
    """Multi-host ``serve``: leader-serves over the whole slice.

    VERDICT r3 #7. The round-3 refusal existed because N processes would
    each restore and answer /generate independently — N divergent
    replicas behind one Service. The leader-serves architecture fixes
    the coordination problem instead of routing around it:

    * every process restores the checkpoint into the GLOBAL mesh's
      placements (shared ``checkpoint_dir``, orbax reads each process's
      shards — exactly like multi-host train/eval);
    * process 0 (the leader) owns the HTTP endpoint. Followers park in
      a follow loop on ``multihost_utils.broadcast_one_to_all``;
    * per request, the leader broadcasts a fixed-shape header (request
      geometry + sampling controls), then the token rows, and ALL
      processes execute the same jitted ``generate`` on global arrays —
      XLA's collectives span the slice exactly as in training;
    * shutdown broadcasts a stop header; followers exit their loop.

    Requests serialize on the leader (one broadcast conversation at a
    time), which also guarantees every process issues collectives in
    the same order — the multi-controller contract. The K8s Service
    already routes to the leader: the chart's multi-host StatefulSet
    fronts ordinal 0 (the same pod that owns ``jax.distributed``'s
    coordinator), so "HTTP hits process 0" is the deployment's natural
    shape, not an extra router.

    Paged backend: the continuous-batching scheduler stays leader-only
    host state; its DEVICE calls broadcast to the slice via
    ``SlicePagedKVCache`` (runtime/sliceserve.py) — see
    :func:`_run_multihost_paged_serve`.
    """
    import dataclasses
    import threading
    import time as time_mod

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import multihost_utils
    from jax.sharding import NamedSharding, PartitionSpec as P

    from kvedge_tpu.models import generate
    from kvedge_tpu.runtime.status import GenerateUnavailable

    if not cfg.checkpoint_dir:
        raise MeshConfigError(
            "multi-host serve needs [runtime] checkpoint_dir on shared "
            "storage: every process restores the same checkpoint "
            "(README 'Multi-host')"
        )
    restored_step, params = _restore_latest_params(cfg, tcfg, mesh=mesh)
    if cfg.payload_serving == "paged":
        return _run_multihost_paged_serve(
            cfg, base, tcfg, mesh, restored_step, params
        )
    leader = jax.process_index() == 0
    replicated = NamedSharding(mesh, P())
    max_rows = _serve_max_rows(cfg, tcfg)

    def bcast(tree):
        return multihost_utils.broadcast_one_to_all(tree)

    # Header layout (fixed shapes — broadcast requires every process to
    # present identical structures): ints = [op, rows, prompt_len,
    # n_new, sampled, seed], floats = [temperature, top_p]. op 0 = stop.
    def zero_header():
        return (np.zeros(6, np.int64), np.zeros(2, np.float32))

    # One jitted replicator (not per-request — jit caches on function
    # identity): reshard any output so every process can read the full
    # array from its own shards.
    _replicate = jax.jit(lambda x: x, out_shardings=replicated)

    def run_request(ints, floats, tokens_np):
        """Executed by EVERY process with identical inputs — the caller
        must pass the BROADCAST-RETURNED values (broadcast canonicalizes
        dtypes, e.g. int64 -> int32 under default x64-disabled jax; a
        leader computing from its pre-broadcast locals could sample with
        a different seed than the followers)."""
        rows, n_new = int(ints[1]), int(ints[3])
        sampled = bool(ints[4])
        prompt = jax.make_array_from_process_local_data(
            replicated, tokens_np
        )
        sampling = None
        if sampled:
            base_key = jax.random.PRNGKey(int(ints[5]))
            seed_keys = jax.vmap(
                lambda i: jax.random.fold_in(base_key, i)
            )(jnp.arange(rows))
            sampling = (seed_keys, jnp.float32(float(floats[0])),
                        jnp.float32(float(floats[1])))
        out = generate(params, prompt, tcfg, n_new=n_new,
                       sampling=sampling, sampled=sampled)
        return np.asarray(_replicate(out).addressable_data(0))

    if not leader:
        def follow():
            try:
                while True:
                    ints, floats = bcast(zero_header())
                    if int(ints[0]) == 0:
                        return
                    rows, plen = int(ints[1]), int(ints[2])
                    tokens_np = bcast(np.zeros((rows, plen), np.int32))
                    run_request(ints, floats, tokens_np)
            except Exception as e:  # pragma: no cover - slice-fatal
                # Same contract as the paged follower: die loudly so
                # the StatefulSet restarts the slice instead of leaving
                # a healthy-looking pod the leader can never reach.
                print(f"[kvedge-serve] follower loop died: {e!r}",
                      flush=True)
                import os as os_mod

                os_mod._exit(13)

        thread = threading.Thread(target=follow,
                                  name="kvedge-serve-follow", daemon=True)
        thread.start()

        # This pod's own /generate answers 503 pointing at the leader;
        # its real job is the follow loop above. join() lets callers
        # (tests, an orderly pod shutdown) wait for the leader's stop
        # broadcast before exiting — killing the process mid-collective
        # would wedge the slice.
        def follower_fn(doc: dict) -> dict:
            raise GenerateUnavailable(
                f"this pod is follower process {jax.process_index()}; "
                "generation is served by the leader (process 0 — the "
                "Service routes to ordinal 0)"
            )

        follower_fn.stats = lambda: {
            "backend": "multihost-follower",
            "processes": jax.process_count(),
        }
        follower_fn.close = lambda drain=False: None
        follower_fn.join = thread.join
        return dataclasses.replace(
            base, probe_ms=0.0, probe_checksum=0.0,
        ), follower_fn

    lock = threading.Lock()
    stopped = False

    def _serve(doc: dict) -> dict:
        tokens, n_new, temperature, top_p, seed, stream, spec, _, _ = (
            _parse_generate_request(doc, tcfg, max_rows=max_rows,
                                    paged=False)
        )
        if spec:
            raise ValueError(
                "'speculative' is not supported on a multi-host serve "
                "deployment (single-host contiguous only)"
            )
        if not -2 ** 31 <= seed < 2 ** 31:
            # The broadcast canonicalizes the header to int32 (default
            # x64-disabled jax); refuse rather than silently truncate.
            raise ValueError("'seed' must fit in int32")
        arr = np.asarray(tokens, np.int32) % tcfg.vocab
        sampled = temperature > 0.0
        with lock:
            if stopped:
                raise GenerateUnavailable("server is shut down")
            ints = np.array(
                [1, arr.shape[0], arr.shape[1], n_new,
                 1 if sampled else 0, seed], np.int64,
            )
            floats = np.array([temperature, top_p], np.float32)
            # The leader consumes the broadcast RESULTS, exactly like the
            # followers — see run_request's dtype-canonicalization note.
            ints, floats = bcast((ints, floats))
            arr = bcast(arr)
            out = run_request(ints, floats, arr)
        return {
            "tokens": [[int(t) for t in row] for row in out.tolist()],
            "n_new": n_new,
            "restored_step": restored_step,
        }

    counters = _ServeCounters()

    def serve_fn(doc: dict) -> dict:
        counters.count("requests_total")
        start = time_mod.perf_counter()
        try:
            result = _serve(doc)
        except Exception as e:
            counters.count_outcome(e)
            raise
        counters.count("tokens_generated_total",
                       result["n_new"] * len(result["tokens"]))
        counters.finish(start)
        return result

    def serve_stats() -> dict:
        out = counters.snapshot()
        out["backend"] = "multihost-contiguous"
        out["processes"] = jax.process_count()
        return out

    serve_fn.stats = serve_stats

    def close(drain: bool = False) -> None:
        nonlocal stopped
        with lock:
            if stopped:
                return
            stopped = True
            bcast(zero_header())  # op 0: followers exit their loop

    serve_fn.close = close

    # Boot self-check through the REAL broadcast path: proves the whole
    # slice answers before the endpoint goes live (followers are already
    # in their loop — the first collective is the sync point).
    probe_prompt = list(range(1, min(4, tcfg.max_seq - 1) + 1))
    probe_new = min(2, tcfg.max_seq - len(probe_prompt))
    start = time_mod.perf_counter()
    probe = _serve({"tokens": [probe_prompt], "n_new": probe_new})
    elapsed_ms = (time_mod.perf_counter() - start) * 1000.0
    return dataclasses.replace(
        base, probe_ms=elapsed_ms,
        probe_checksum=float(sum(probe["tokens"][0])),
    ), serve_fn


def _spec_draft_len(cfg) -> int:
    """The draft length ``serving_speculative`` resolves to BEFORE the
    boot probe: "auto" sizes pools for draft 4 (the probe may still
    turn speculation off at boot — sizing for it keeps the pool
    derivation independent of the probe's outcome)."""
    if cfg.serving_speculative == "auto":
        return 4
    return cfg.serving_speculative


def _serving_page_bytes(cfg, tcfg) -> int:
    """HBM bytes ONE pool page costs: K and V slabs across every layer
    (``[n_layers, page_size, kv_heads, d_head]`` each), plus the two
    fp32 scale slabs an int8 pool carries alongside (kvcache.PagedState
    docstring). This mirrors ``PagedKVCache.__init__``'s allocation
    exactly — the budget arithmetic and the arrays it pays for must
    never drift apart."""
    import jax.numpy as jnp

    page_size = cfg.serving_page_size
    itemsize = (1 if cfg.serving_kv_dtype == "int8"
                else jnp.dtype(tcfg.dtype).itemsize)
    row = tcfg.n_layers * page_size * tcfg.kv_heads
    per_page = row * tcfg.d_head * itemsize * 2  # K + V
    if cfg.serving_kv_dtype == "int8":
        per_page += row * 4 * 2  # fp32 scale_k + scale_v
    return per_page


def _serving_pool_dims(cfg, tcfg) -> tuple[int, int, int, int]:
    """``(slots, pages, page_size, max_pages_per_seq)`` of the paged
    pool — ONE derivation for the single-host server and the slice
    cache (the two must never size differently). ``serving_pages = 0``
    auto-sizes so every slot can hold a worst-case request — admission
    then only ever waits on slots, never on pages. Speculative mode
    widens both by the draft slack (a verify pass writes K positions
    past a GREEDY request's budget even when nothing accepts).

    ``serving_hbm_budget_mb`` sizes the pool from a BYTE budget instead
    (mutually exclusive with ``serving_pages`` — config validation
    enforces it): pages = budget // page_bytes, floored. Admission then
    gates on pages, not slots (SERVING.md rung 21), so a budget smaller
    than ``slots`` worst-case requests is a deliberate oversubscription,
    not an error — but a budget too small for even ONE worst-case
    request can never admit anything and fails loudly here."""
    slots, page_size = cfg.serving_slots, cfg.serving_page_size
    mpps = -(-(tcfg.max_seq + _spec_draft_len(cfg)) // page_size)
    if cfg.serving_hbm_budget_mb:
        pages = (cfg.serving_hbm_budget_mb * 2**20
                 ) // _serving_page_bytes(cfg, tcfg)
        if pages < mpps:
            raise MeshConfigError(
                f"serving_hbm_budget_mb = {cfg.serving_hbm_budget_mb} "
                f"buys {pages} pages, but one worst-case request needs "
                f"{mpps} (max_seq {tcfg.max_seq} + draft slack at page "
                f"size {page_size}); raise the budget or shrink max_seq"
            )
    else:
        pages = cfg.serving_pages or slots * mpps
    return slots, pages, page_size, mpps


def _serve_max_rows(cfg, tcfg) -> int:
    """Ingress row ceiling for one ``/generate`` request: 4 waves of
    the pool's WORST-CASE concurrency — the number of full-length
    requests the page budget can actually hold at once, capped at the
    slot count. For auto-sized pools ``pages // mpps == slots``, so
    this reproduces the old ``4 * serving_slots`` ceiling exactly; a
    budget-sized pool that holds fewer worst-case residents than slots
    lowers the ceiling to match what admission can really run."""
    slots, pages, _, mpps = _serving_pool_dims(cfg, tcfg)
    return 4 * max(1, min(slots, pages // mpps))


def _run_multihost_paged_serve(cfg, base, tcfg, mesh, restored_step,
                               params):
    """Cross-host continuous batching: the paged scheduler on a slice.

    The leader runs the UNMODIFIED single-host serving stack —
    ``PagedGenerationServer`` with all its admission, chunked prefill,
    prefix sharing, cancellation, and windowing — over a
    ``SlicePagedKVCache`` whose device seams broadcast each op so every
    process executes the same jitted kernel on global arrays
    (runtime/sliceserve.py has the protocol and its soundness
    argument). Followers replay the op stream; their own /generate
    answers 503 pointing at the leader, exactly like the contiguous
    leader-serves path. Sampling stays leader-local (only the CHOSEN
    tokens enter the op stream), so the cross-backend key schedule
    holds without broadcasting seeds.
    """
    import dataclasses
    import threading

    import jax

    from kvedge_tpu.runtime.sliceserve import (
        SlicePagedKVCache,
        follow_paged,
    )
    from kvedge_tpu.runtime.status import GenerateUnavailable

    # Constructed identically on EVERY process, at the same point in
    # the collective order (the zeroed global pool is a collective jit
    # execution).
    slots, pages, page_size, mpps = _serving_pool_dims(cfg, tcfg)
    cache = SlicePagedKVCache(
        tcfg, slots=slots, pages=pages, page_size=page_size, mesh=mesh,
        max_pages_per_seq=mpps, kv_dtype=cfg.serving_kv_dtype,
    )

    if jax.process_index() != 0:
        def follow():
            # Bounded rejoin (SERVING.md rung 15): a replay failure no
            # longer kills the pod on the first strike. The follower
            # re-enters follow_paged — its first received op is the
            # leader's reformation barrier SYNC, which restores
            # tables/lengths and puts it back in lockstep. The budget
            # mirrors the leader supervisor's attempt budget; when it
            # is exhausted (or recovery is disabled) the old contract
            # holds: exit non-zero so the StatefulSet restarts the
            # slice — a swallowed replay failure would leave this pod
            # answering /healthz while the leader wedges forever.
            rejoins = max(0, int(cfg.serving_recovery_attempts))
            tries = 0
            while True:
                try:
                    follow_paged(cache, params)
                    return  # leader broadcast STOP: clean end of serve
                except Exception as e:
                    tries += 1
                    if tries > rejoins:  # pragma: no cover - slice-fatal
                        print(f"[kvedge-serve] paged follower died "
                              f"({tries - 1} rejoin(s) spent): {e!r}",
                              flush=True)
                        import os as os_mod

                        os_mod._exit(13)
                    print(f"[kvedge-serve] paged follower dropped from "
                          f"the op stream ({e!r}); rejoining "
                          f"({tries}/{rejoins})", flush=True)

        thread = threading.Thread(
            target=follow, name="kvedge-serve-follow", daemon=True
        )
        thread.start()

        def follower_fn(doc: dict) -> dict:
            raise GenerateUnavailable(
                f"this pod is follower process {jax.process_index()}; "
                "generation is served by the leader (process 0 — the "
                "Service routes to ordinal 0)"
            )

        follower_fn.stats = lambda: {
            "backend": "multihost-paged-follower",
            "processes": jax.process_count(),
        }
        follower_fn.close = lambda drain=False: None
        follower_fn.join = thread.join
        return dataclasses.replace(
            base, probe_ms=0.0, probe_checksum=0.0,
        ), follower_fn

    # Follower release rides the server's own close: PagedGenerationServer
    # calls cache.stop() under its lock after the decode loop exits —
    # serialized after every in-flight cache call, and idempotent.
    return _build_serve(
        cfg, base, tcfg, params, restored_step, cache=cache,
        backend="multihost-paged",
    )


def _parse_generate_request(doc: dict, tcfg, *, max_rows: int,
                            paged: bool):
    """Validate a ``POST /generate`` body. ONE definition shared by the
    single-host serve path and the multi-host leader (the two must never
    drift on what a well-formed request is). Returns
    ``(tokens, n_new, temperature, top_p, seed, stream, spec, priority,
    deadline_ms)``; raises ``ValueError`` (the HTTP layer's 400) for
    anything malformed.
    """
    tokens = doc.get("tokens")
    if (not isinstance(tokens, list) or not tokens
            or not all(isinstance(r, list) and r for r in tokens)):
        raise ValueError(
            "body must carry 'tokens': a non-empty list of "
            "non-empty token-id rows"
        )
    if len({len(r) for r in tokens}) != 1:
        raise ValueError("all token rows must have equal length")
    if len(tokens) > max_rows:
        # Both backends need a ceiling: the paged path fans rows out to
        # the bounded worker pool (a burst of thousands of rows would
        # queue, not thread-storm, but the client deserves a clear
        # refusal over an hour-long queue), and the contiguous path
        # jit-compiles one program per batch size (an unbounded compile
        # surface).
        raise ValueError(
            f"request carries {len(tokens)} token rows > the "
            f"runtime's ceiling of {max_rows} (4 x the page pool's "
            "worst-case request capacity); split the request"
        )
    try:
        n_new = int(doc.get("n_new", 16))
    except (TypeError, ValueError):
        raise ValueError("'n_new' must be an integer") from None
    if not 1 <= n_new <= tcfg.max_seq:
        raise ValueError(
            f"'n_new' must be in [1, {tcfg.max_seq}]"
        )
    if len(tokens[0]) + n_new > tcfg.max_seq:
        raise ValueError(
            f"prompt ({len(tokens[0])}) + n_new ({n_new}) exceeds "
            f"the model's max_seq ({tcfg.max_seq})"
        )
    if not all(
        isinstance(t, int) and not isinstance(t, bool)
        for row in tokens for t in row
    ):
        # Explicit check: jnp.asarray would silently TRUNCATE floats
        # (1.9 -> 1) and decode a different prompt than the client sent.
        raise ValueError("token rows must contain integers")
    # Sampling controls: temperature 0 (default) = greedy; > 0 samples
    # through the shared nucleus filter with the deterministic per-row
    # key schedule (seed, row, token) — identical across backends.
    raw_t = doc.get("temperature", 0.0)
    raw_p = doc.get("top_p", 1.0)
    raw_seed = doc.get("seed", 0)
    # Strict types, same discipline as the token check above: bool is an
    # int subclass (true would silently become 1.0 and switch the client
    # to sampling), and a float seed would silently truncate to a seed
    # the client did not send.
    if (not isinstance(raw_t, (int, float))
            or isinstance(raw_t, bool)
            or not isinstance(raw_p, (int, float))
            or isinstance(raw_p, bool)
            or not isinstance(raw_seed, int)
            or isinstance(raw_seed, bool)):
        raise ValueError(
            "'temperature'/'top_p' must be numbers and 'seed' "
            "an integer"
        )
    temperature, top_p, seed = float(raw_t), float(raw_p), raw_seed
    stream = doc.get("stream", False)
    if not isinstance(stream, bool):
        raise ValueError("'stream' must be a boolean")
    if stream and not paged:
        raise ValueError(
            "'stream' requires [payload] serving = \"paged\" — "
            "the contiguous backend decodes the whole request as "
            "one compiled program, so there is nothing to stream"
        )
    if temperature < 0.0:
        raise ValueError("'temperature' must be >= 0")
    if not 0.0 < top_p <= 1.0:
        raise ValueError("'top_p' must be in (0, 1]")
    # Speculative decoding ('speculative': K = draft length): greedy,
    # single-row, contiguous-backend — a latency lever, token-for-token
    # identical to plain greedy decode (models/speculative.py).
    spec = doc.get("speculative", 0)
    if (not isinstance(spec, int) or isinstance(spec, bool)
            or not 0 <= spec <= 16):
        raise ValueError(
            "'speculative' must be an integer draft length in "
            "[0, 16] (0 = off)"
        )
    if spec:
        # Stream check FIRST: on a paged runtime (the only place
        # 'stream' is legal) the composition error is the clearer
        # message; after the paged check it would be unreachable.
        if stream:
            raise ValueError(
                "'speculative' does not compose with 'stream'"
            )
        if paged:
            raise ValueError(
                "per-request 'speculative' runs on the contiguous "
                "backend; the paged backend speculates server-wide "
                "via [payload] serving_speculative (the batch-level "
                "schedule is a server policy, not a request knob)"
            )
        if len(tokens) != 1:
            raise ValueError(
                "'speculative' supports exactly one token row"
            )
        if temperature > 0.0:
            raise ValueError(
                "'speculative' is greedy-only (temperature 0): "
                "drafts verify against the argmax"
            )
    # SLO fields (SERVING.md rung 17): 'priority' names the admission
    # class, 'deadline_ms' bounds how long the request may queue. The
    # paged server validates the class name against its configured set
    # (an unknown class is this same 400 path); the contiguous backend
    # has no admission queue, so the fields are refused there rather
    # than silently ignored.
    priority = doc.get("priority", "interactive")
    if not isinstance(priority, str) or not priority:
        raise ValueError(
            "'priority' must be a non-empty class name "
            "(e.g. 'interactive' or 'batch')"
        )
    deadline_ms = doc.get("deadline_ms")
    if deadline_ms is not None and (
            not isinstance(deadline_ms, int)
            or isinstance(deadline_ms, bool) or deadline_ms < 1):
        raise ValueError("'deadline_ms' must be a positive integer")
    if not paged and ("priority" in doc or deadline_ms is not None):
        raise ValueError(
            "'priority'/'deadline_ms' require [payload] serving = "
            "\"paged\" — the contiguous backend runs one request at a "
            "time with no admission queue to schedule"
        )
    return (tokens, n_new, temperature, top_p, seed, stream, spec,
            priority, deadline_ms)


class _ResumeLog:
    """Bounded per-request delivery log backing client reconnects.

    The durability rung (SERVING.md rung 22) keeps a poisoned pool's
    in-flight requests alive server-side; this is the CLIENT half: the
    serve path records every generated token it hands (or buffers for)
    a request's consumer, keyed by request id, so a client that lost
    its connection can reconnect with its ``X-Request-Id`` and an
    ``emitted_offset`` and receive exactly the tokens it has not seen
    — no duplicates, no gaps — whether the request is still decoding,
    parked in the server's journal across a recovery, or finished.

    Bounded to the ``max_entries`` most recently opened requests; an
    evicted id simply cannot be resumed (the reconnect gets the same
    400 an unknown id gets). Pump threads write and reconnect handlers
    read under one condition variable; records are plain dicts mutated
    only while holding it.
    """

    def __init__(self, max_entries: int = 64):
        self.cond = threading.Condition()
        self.max_entries = int(max_entries)
        self._entries: collections.OrderedDict = collections.OrderedDict()

    def open(self, rid: str, n_rows: int, n_new: int) -> dict:
        """Register ``rid`` (replacing any previous use of the id)."""
        with self.cond:
            rec = {"rows": [[] for _ in range(n_rows)],
                   "live": n_rows, "n_new": n_new,
                   "done": False, "error": None}
            self._entries.pop(rid, None)
            self._entries[rid] = rec
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)
            return rec

    def get(self, rid: str) -> dict | None:
        with self.cond:
            return self._entries.get(rid)

    def append(self, rid: str, row: int, token: int) -> None:
        with self.cond:
            rec = self._entries.get(rid)
            if rec is not None:
                rec["rows"][row].append(token)
                self.cond.notify_all()

    def row_done(self, rid: str) -> None:
        """One row finished; the record is done when all rows are."""
        with self.cond:
            rec = self._entries.get(rid)
            if rec is not None:
                rec["live"] -= 1
                if rec["live"] <= 0:
                    rec["done"] = True
                self.cond.notify_all()

    def finish(self, rid: str, error: Exception | None = None) -> None:
        """Mark ``rid`` complete (the first error recorded wins)."""
        with self.cond:
            rec = self._entries.get(rid)
            if rec is not None:
                if error is not None and rec["error"] is None:
                    rec["error"] = error
                rec["done"] = True
                self.cond.notify_all()


def run_serve_payload(cfg: RuntimeConfig):
    """The ``serve`` payload: greedy decode behind ``POST /generate``.

    Closes the loop the state volume exists for: the ``train`` payload
    checkpoints through it, and a later ``serve`` pod restores the
    latest checkpoint (params only — optimizer state is training's
    business) and serves generation requests from it. A fresh volume
    serves the same deterministic init training would start from, so the
    endpoint works before any training has happened.

    Mesh-aware: params restore straight into the configured mesh's
    placements (the same partition rules training used), and decode runs
    under jit with those shardings driving XLA's SPMD partitioner — a
    checkpoint that needed the ``model``/``expert`` axes to train serves
    over them too. On a multi-host slice the payload switches to
    leader-serves (:func:`_run_multihost_serve`): process 0 owns HTTP
    and every decode is an SPMD computation the whole slice joins.

    Returns ``(DeviceCheckResult, serve_fn | None)``; ``serve_fn(doc)``
    implements the request contract::

        {"tokens": [[int, ...], ...], "n_new": int}   ->
        {"tokens": [[prompt + generated], ...], "n_new": N,
         "restored_step": int | null}

    The whole decode loop is one jitted program per (batch, prompt_len,
    n_new) shape (models/decode.py); a lock serializes requests — this
    is the reference-scale single-runtime story, not a batching server.
    """
    base = run_device_check(cfg)
    if not base.ok:
        return base, None

    import dataclasses

    import jax

    try:
        tcfg, mesh = train_model_config(cfg)
        if jax.process_count() > 1:
            # Leader-serves: process 0 owns HTTP; every decode is an
            # SPMD computation the whole slice joins (see
            # _run_multihost_serve). Followers return serve_fn=None —
            # their /generate answers 503 pointing at the leader.
            return _run_multihost_serve(cfg, base, tcfg, mesh)
        # Placement-aware restore: params land sharded over THIS mesh
        # (model/expert/stage axes), so a checkpoint whose model needed
        # tensor parallelism to fit serves over the same axes — decode
        # runs under jit with the input shardings driving XLA's SPMD
        # partitioner, exactly like the train step.
        restored_step, params = _restore_latest_params(cfg, tcfg, mesh=mesh)
        # The recovery supervisor's warm restart re-reads the latest
        # checkpoint (single-host only: a slice restore is a collective
        # the supervisor's thread must not run alone).
        return _build_serve(
            cfg, base, tcfg, params, restored_step,
            restore_params=lambda: _restore_latest_params(
                cfg, tcfg, mesh=mesh
            )[1],
        )
    except MeshConfigError as e:
        # Raised before any server/device state exists: surface the
        # operator-facing config message, not a wrapped traceback.
        return dataclasses.replace(base, ok=False, error=str(e)), None
    except Exception as e:
        return dataclasses.replace(
            base, ok=False, error=f"serve payload failed: {e!r}",
        ), None


def _build_serve(cfg, base, tcfg, params, restored_step, *, cache=None,
                 backend=None, restore_params=None):
    """Build the serve endpoint over restored ``params``.

    The ONE construction of the serving data path, shared by the
    single-host payload (``cache=None`` — it builds its own pool from
    the ``[payload] serving_*`` knobs) and the multi-host paged leader
    (``cache`` = a ``SlicePagedKVCache`` whose device calls span the
    slice; ``backend`` labels the stats). Returns
    ``(DeviceCheckResult, serve_fn)``; on failure, tears down anything
    it created and re-raises for the caller's error mapping.
    """
    import dataclasses
    import threading
    import time as time_mod

    import jax
    import jax.numpy as jnp

    from kvedge_tpu.models import generate
    from kvedge_tpu.runtime.tracing import (
        Tracer, clean_request_id, new_request_id,
    )

    # Row ceiling + worker pool sized from the serving knobs: the
    # serve path must not spawn one thread per row (VERDICT r3 #6 —
    # a burst of wide requests was an unbounded thread surface). The
    # ceiling is page-budget-derived (SERVING.md rung 21), not a bare
    # slot multiple — a budget-sized pool admits what pages allow.
    max_rows = _serve_max_rows(cfg, tcfg)
    # Request-scoped tracing ([payload] serving_trace, SERVING.md rung
    # 18): ONE flight recorder per serving pool, shared by reference
    # with the scheduler, the (slice) cache, the deadline runner and
    # the recovery machinery. None is the off state — every producer
    # guards on it, so off costs one attribute read per seam.
    tracer = Tracer.from_knob(cfg.serving_trace)
    row_pool = None
    paged_server = None
    recovery_sup = None
    resume_log = None
    prefix_path, fp = "", ""
    try:
        if cache is not None or cfg.payload_serving == "paged":
            from kvedge_tpu.models.serving import PagedGenerationServer

            # page_size passed explicitly so the sizing arithmetic and
            # the cache's pages can never drift apart; an injected
            # cache carries its own pool from the SAME derivation.
            slots, pages, page_size, _ = _serving_pool_dims(cfg, tcfg)
            spec_draft = _spec_draft_len(cfg)
            # SLO engine ([payload] serving_slo*, SERVING.md rung 25):
            # objectives travel as one frozen value object; None keeps
            # the engine (and its boundary feed) out of the process.
            slo_objectives = None
            if cfg.serving_slo:
                from kvedge_tpu.runtime.slo import SloObjectives
                slo_objectives = SloObjectives(
                    target=cfg.serving_slo_target,
                    ttft_ms=cfg.serving_slo_ttft_ms,
                    itl_ms=cfg.serving_slo_itl_ms,
                    queue_ms=cfg.serving_slo_queue_ms,
                    fast_window_s=cfg.serving_slo_fast_s,
                    slow_window_s=cfg.serving_slo_slow_s,
                )
            paged_server = PagedGenerationServer(
                params, tcfg, slots=slots, pages=pages,
                page_size=page_size,
                prefill_chunk=cfg.serving_prefill_chunk,
                prefix_cache=cfg.serving_prefix_cache,
                prefix_host_mb=cfg.serving_prefix_host_mb,
                speculative=spec_draft,
                # Device-resident spec windows (SERVING.md rung 20):
                # only meaningful when spec_draft resolved > 0 — the
                # server validates the pairing, and _spec_draft_len
                # already pins "auto" before construction, so a zero
                # draft with a nonzero window is a config error here,
                # not a silent fallback.
                spec_window=(cfg.serving_spec_window
                             if spec_draft > 0 else 0),
                spec_sampled_window=cfg.serving_spec_sampled_window,
                # "auto" hands window choice to the online controller
                # (SERVING.md rung 26) inside the min/max bounds; a
                # static int keeps the operator's cap.
                window=cfg.serving_window,
                window_min=cfg.serving_window_min,
                window_max=cfg.serving_window_max,
                kv_dtype=cfg.serving_kv_dtype,
                cache=cache,
                retry_after_s=cfg.serving_retry_after_s,
                # SLO-aware admission (SERVING.md rung 17): policy +
                # watermarks + host swap budget from the [payload]
                # serving_sched_* knobs; weights pre-parsed so a bad
                # string fails at config validation, not first request.
                sched_policy=cfg.serving_sched_policy,
                sched_weights=cfg.sched_weights_dict(),
                sched_max_queue_depth=cfg.serving_sched_max_queue_depth,
                sched_max_queue_wait_s=(
                    cfg.serving_sched_max_queue_wait_s),
                sched_swap_budget_mb=cfg.serving_sched_swap_budget_mb,
                # Capacity semantics (SERVING.md rung 21): power-of-two
                # compile buckets over the device batch dim, and
                # free-page watermarks feeding the scheduler's shed and
                # resume decisions. An injected cache (the slice path)
                # governs its own bucket — it pins to slots, and the
                # server follows the cache, so min_bucket only reaches
                # the pool this ctor builds itself.
                min_bucket=cfg.serving_min_bucket,
                page_low_watermark=cfg.serving_page_low_watermark,
                page_high_watermark=cfg.serving_page_high_watermark,
                # Overlapped window pipeline ([payload]
                # serving_overlap). Multi-host note: revive() after a
                # recovery restarts _loop, which re-selects the
                # pipelined body — the slice cache's reform() dropped
                # its device carry, so the revived pipeline re-enters
                # cleanly from host tokens on every recovery cycle.
                overlap=cfg.serving_overlap,
                tracer=tracer,
                # Lock-discipline assertions ([payload]
                # serving_debug_locks, SERVING.md rung 19): runtime
                # twin of tools/locklint.py — *_locked calls assert
                # ownership, Condition ops become thread-accurate.
                debug_locks=cfg.serving_debug_locks,
                # Durability (SERVING.md rung 22): boundary checkpoints
                # of in-flight requests into the host journal, and the
                # page-conservation audit at every quiescent boundary.
                checkpoint_every=cfg.serving_checkpoint_every,
                debug_pages=cfg.serving_debug_pages,
                # Observability plane (SERVING.md rung 25): the SLO
                # engine with its knob-gated burn-rate shed input, and
                # the occupancy timeline ring.
                slo=slo_objectives,
                slo_shed=cfg.serving_slo_shed,
                occupancy_ring=cfg.serving_occupancy_ring,
            )
            # Degraded-mode observability: when the pool poisons
            # (runtime/failures.py), persist a post-mortem failure
            # record on the state volume — it survives the reschedule
            # the degradation asks for, boot.snapshot() surfaces it
            # under "last_failure", and the NEXT pod generation's
            # /status shows why its predecessor died.
            if cfg.state_dir:
                from kvedge_tpu.runtime import heartbeat as hb_mod

                state_dir = cfg.state_dir

                def _record_failure(reason, failure):
                    record = {
                        "payload": "serve",
                        "backend": backend or "paged",
                        "type": type(failure).__name__,
                        "reason": reason,
                        "retryable": bool(getattr(failure, "retryable",
                                                  False)),
                    }
                    if tracer is not None:
                        # Flight-recorder tail: the last N trace events
                        # ship INSIDE the post-mortem, so the next pod
                        # generation's /status shows the timeline that
                        # led to the poison, not just the final error.
                        record["trace"] = tracer.last_events()
                    hb_mod.write_failure_record(state_dir, record)
                    if cfg.serving_bundle:
                        # Full post-mortem bundle (rung 25) next to
                        # the failure record: the machine-complete
                        # document — consistent metrics + SLO/burn +
                        # page books + occupancy tail — a dead
                        # replica explains itself with. Best-effort:
                        # a bundle failure must never mask the
                        # failure record above.
                        try:
                            hb_mod.write_flight_bundle(
                                state_dir,
                                paged_server.flight_bundle(),
                            )
                        except Exception:
                            pass

                paged_server.on_degraded = _record_failure
            # Spec-mode economics probe (VERDICT r4 #7): measure this
            # session's verify-pass and window costs before traffic;
            # "auto" falls back to windowed decode when windows
            # dominate speculation's BEST case, an explicit K keeps
            # the choice but warns loudly. Single-host only — the
            # probe's device ops would broadcast into the slice
            # op-stream before followers expect traffic shapes.
            if spec_draft > 0 and cache is None:
                decision = paged_server.resolve_speculation(
                    auto=cfg.serving_speculative == "auto"
                )
                print(f"[kvedge-serve] speculative mode: "
                      f"{decision['mode']} (best-case "
                      f"{decision['spec_best_tokens_per_sec']}/s vs "
                      f"windowed {decision['windowed_tokens_per_sec']}"
                      f"/s per slot)", flush=True)
            elif (spec_draft > 0 and cache is not None
                    and cfg.serving_speculative == "auto"):
                # "auto" promises measured economics; unmeasured
                # speculation on a degraded relay is the regression
                # the mode exists to prevent. Explicit K still runs
                # speculation on a slice.
                decision = paged_server.disable_speculation(
                    "auto unmeasured on a slice"
                )
                print(f"[kvedge-serve] speculative mode: "
                      f"{decision['mode']}", flush=True)
            # Prefix persistence (single-host only: the slice cache's
            # pool is a global array the leader cannot dump alone):
            # warm prefixes from the previous pod generation re-pin at
            # boot, fingerprint-guarded so K/V from other params are
            # ignored; the dump happens at close, below.
            if (cache is None and cfg.serving_prefix_persist
                    and cfg.serving_prefix_cache and cfg.state_dir):
                import os as os_mod

                prefix_path = os_mod.path.join(
                    cfg.state_dir, "prefix-cache.npz"
                )
                fp = (f"step={restored_step} {tcfg.vocab}v "
                      f"{tcfg.d_model}d {tcfg.n_heads}h "
                      f"{tcfg.kv_heads}kv {tcfg.n_layers}L "
                      f"{tcfg.d_ff}ff {tcfg.max_seq}T {tcfg.dtype}")
                n = paged_server.load_prefix_cache(prefix_path, fp)
                if n:
                    print(f"[kvedge-serve] re-pinned {n} prefix-cache "
                          f"entries from {prefix_path}", flush=True)
                # Periodic dumps (VERDICT r4 #10): a SIGKILL'd pod —
                # the reference's own failure story — keeps its warm
                # prefixes, not just a gracefully drained one. The
                # close-time dump below stays as the freshest copy.
                paged_server.start_prefix_persistence(
                    prefix_path, fp, interval=30.0
                )
            # Self-healing (SERVING.md rung 15): the supervisor chains
            # onto on_degraded AFTER the failure-record observer above
            # (attach() preserves it), so a poisoning failure is first
            # recorded, then healed — slice reformation + warm restart
            # with backoff — and only escalates to the terminal 503 /
            # reschedule path when the attempt budget or the crash-loop
            # breaker says in-process recovery is not working.
            if cfg.serving_recovery_attempts > 0:
                from kvedge_tpu.runtime.recovery import (
                    RecoveryPolicy,
                    RecoverySupervisor,
                )

                recovery_sup = RecoverySupervisor(
                    paged_server,
                    policy=RecoveryPolicy(
                        max_attempts=cfg.serving_recovery_attempts,
                    ),
                    state_dir=cfg.state_dir,
                    prefix_path=prefix_path,
                    prefix_fingerprint=fp,
                    restore_params=(restore_params if cache is None
                                    else None),
                ).attach()
            # One shared pool for row priming AND stream pumping, sized
            # 2x slots (only `slots` rows decode concurrently; one
            # primer + one pump each is the useful parallelism). Excess
            # rows queue here instead of spawning threads; progress is
            # guaranteed because decode never depends on a pool worker
            # (tokens buffer in each request's queue regardless).
            import concurrent.futures

            row_pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=2 * slots,
                thread_name_prefix="kvedge-serve-row",
            )
            # Reconnect log (rung 22): only when boundary checkpoints
            # are on — without them a disconnect still cancels rows,
            # so there would be nothing durable to resume against.
            if cfg.serving_checkpoint_every > 0:
                resume_log = _ResumeLog()
        lock = threading.Lock()

        def _resume(doc: dict) -> dict:
            """Reconnect path (SERVING.md rung 22): ``X-Request-Id`` +
            ``emitted_offset`` re-attaches to a previously issued
            request and delivers exactly the generated tokens the
            client has not seen. No new work is submitted — tokens
            come from the delivery log the original request's pumps
            keep feeding while the client is gone (a disconnect
            detaches instead of cancelling when checkpointing is on),
            so the stitched sequence is gap-free and duplicate-free
            even across a poison/revive cycle."""
            rid = clean_request_id(doc.get("_request_id"))
            if not rid:
                raise ValueError(
                    "reconnect needs the original request id "
                    "(X-Request-Id header or '_request_id')"
                )
            rec = resume_log.get(rid)
            if rec is None:
                raise ValueError(
                    f"unknown or expired request id {rid!r}: nothing "
                    "to resume (the delivery log keeps the "
                    f"{resume_log.max_entries} most recent requests)"
                )
            n_rows = len(rec["rows"])
            raw = doc.get("emitted_offset")
            offs = raw if isinstance(raw, list) else [raw] * n_rows
            if (len(offs) != n_rows
                    or not all(isinstance(o, int)
                               and not isinstance(o, bool)
                               and 0 <= o <= rec["n_new"]
                               for o in offs)):
                raise ValueError(
                    "'emitted_offset' must be an integer (or one per "
                    f"row, {n_rows} here) in [0, n_new="
                    f"{rec['n_new']}] — the count of generated "
                    "tokens already received for the row"
                )
            stream = doc.get("stream", False)
            if not isinstance(stream, bool):
                raise ValueError("'stream' must be a boolean")
            if not stream:
                # Buffered reconnect: wait out the original request
                # (its submitter is still parked on the server — across
                # a recovery if need be), then hand back the per-row
                # generated suffixes beyond the client's offsets.
                with resume_log.cond:
                    while not rec["done"]:
                        resume_log.cond.wait()
                    if rec["error"] is not None:
                        raise rec["error"]
                    suffix = [list(row[o:])
                              for row, o in zip(rec["rows"], offs)]
                return {"tokens": suffix, "n_new": rec["n_new"],
                        "restored_step": restored_step,
                        "request_id": rid, "resumed_at": list(offs)}

            def replay():
                # Streamed reconnect: drain the log beyond the offsets,
                # then follow it live until the original request's
                # pumps mark the record done. Tokens are read under the
                # log's condition but yielded outside it (the HTTP
                # write must not hold the log against the pumps).
                cursor = list(offs)
                while True:
                    out = []
                    with resume_log.cond:
                        while True:
                            for i in range(n_rows):
                                row = rec["rows"][i]
                                if cursor[i] < len(row):
                                    out.extend(
                                        (i, t)
                                        for t in row[cursor[i]:]
                                    )
                                    cursor[i] = len(row)
                            if out or rec["done"]:
                                done = rec["done"]
                                err = rec["error"]
                                break
                            resume_log.cond.wait()
                    for i, t in out:
                        yield {"row": i, "token": t}
                    if done:
                        if err is not None:
                            raise err
                        yield {
                            "done": True,
                            "tokens": [list(r[o:]) for r, o
                                       in zip(rec["rows"], offs)],
                            "n_new": rec["n_new"],
                            "restored_step": restored_step,
                            "request_id": rid,
                            "resumed_at": list(offs),
                        }
                        return

            return {"_stream": replay(), "request_id": rid}

        def _serve(doc: dict) -> dict:
            if "emitted_offset" in doc:
                # Reconnect, not a new request: every other body field
                # (tokens, sampling, budgets) is pinned by the original
                # submission and must not be re-parsed here.
                if resume_log is None:
                    raise ValueError(
                        "'emitted_offset' reconnect requires the paged "
                        "backend with [payload] "
                        "serving_checkpoint_every > 0"
                    )
                return _resume(doc)
            (tokens, n_new, temperature, top_p, seed, stream, spec,
             priority, deadline_ms) = (
                _parse_generate_request(
                    doc, tcfg, max_rows=max_rows,
                    paged=paged_server is not None,
                )
            )
            # Request ID, minted at ingress (or a sanitized
            # caller-supplied X-Request-Id, injected by the HTTP layer
            # as doc["_request_id"]): echoed in every response and
            # keying this request's span tree in the flight recorder.
            # Minted HERE — not in status.py — so programmatic callers
            # of serve_fn get the same attribution story as HTTP ones.
            rid = (clean_request_id(doc.get("_request_id"))
                   or new_request_id())
            sampled = temperature > 0.0
            base_key = jax.random.PRNGKey(seed) if sampled else None

            def row_sampling(i: int):
                """Row i's sampling triple — ONE definition of the
                cross-backend key schedule (fold_in(base, row))."""
                if not sampled:
                    return None
                return (jax.random.fold_in(base_key, i),
                        jnp.float32(temperature), jnp.float32(top_p))

            if paged_server is not None:
                # Continuous batching: each row is its own request into
                # the shared page pool, submitted CONCURRENTLY so the
                # rows (and any other HTTP handlers' rows) ride the same
                # batched decode step rather than decoding serially.
                from kvedge_tpu.models.serving import (
                    ServerBusy,
                    ServerClosed,
                )
                from kvedge_tpu.runtime.failures import ServingFailure
                from kvedge_tpu.runtime.status import GenerateUnavailable

                def retriable(e: Exception) -> bool:
                    """Conditions a client should retry — against this
                    pod (busy/draining) or its replacement (poisoned
                    pool): 503, not 500."""
                    return (isinstance(e, (ServerBusy, ServerClosed))
                            or (isinstance(e, ServingFailure)
                                and e.retryable))

                def fan_out_rows(n_rows: int, fn) -> None:
                    """Run ``fn(i)`` per row on the shared bounded pool
                    (rows must submit together to ride the same batched
                    decode step; excess rows queue behind the pool's
                    2 x slots workers), then apply the ONE
                    error-priority policy: real faults — including
                    terminal ServingFailures like SliceFollowerLost —
                    surface first (HTTP 500), retriable conditions
                    become GenerateUnavailable (503, with the failure's
                    retry-after hint when it carries one). Shared by
                    the streamed and non-streamed paths so the two can
                    never map the same server condition to different
                    statuses."""
                    errors: list = [None] * n_rows

                    def guarded(i):
                        try:
                            fn(i)
                        except Exception as e:
                            errors[i] = e

                    futures = [
                        row_pool.submit(guarded, i) for i in range(n_rows)
                    ]
                    for f in futures:
                        f.result()
                    for e in errors:
                        if e is not None and not retriable(e):
                            raise e
                    for e in errors:
                        if e is not None:
                            retry_after = getattr(e, "retry_after_s",
                                                  None)
                            hint = ("" if retry_after is None else
                                    f" (retry after ~{retry_after:g}s)")
                            raise GenerateUnavailable(
                                f"{e}{hint}"
                            ) from e

                if stream:
                    import queue as queue_mod

                    prompts = [[t % tcfg.vocab for t in row]
                               for row in tokens]
                    # Prime EVERY row for its first token HERE, before
                    # the handler commits a 200: admission failures
                    # (ServerBusy) must surface as a clean 503 status,
                    # which is impossible once streaming has started.
                    # (Rows beyond the slot count admit as earlier rows
                    # finish; on a timeout the already-admitted rows are
                    # CANCELLED so the 503 frees their slots and pages
                    # at the next decode boundary instead of decoding
                    # out budgets nobody will read.)
                    sources: list = [None] * len(prompts)
                    firsts: list = [None] * len(prompts)

                    def prime(i):
                        src = paged_server.submit_stream(
                            prompts[i], n_new, sampling=row_sampling(i),
                            priority=priority, deadline_ms=deadline_ms,
                            request_id=rid,
                        )
                        firsts[i] = next(src)
                        sources[i] = src

                    try:
                        fan_out_rows(len(prompts), prime)
                    except Exception:
                        for src in sources:
                            if src is not None:
                                src.cancel()
                        raise

                    # The 200 is committed: register the request for
                    # reconnects BEFORE any token leaves, so a client
                    # that dies on the first frame can still resume.
                    if resume_log is not None:
                        resume_log.open(rid, len(prompts), n_new)

                    _ROW_DONE = object()

                    def ndjson():
                        # Rows stream CONCURRENTLY, merged into one
                        # ndjson sequence with per-row attribution: one
                        # pump thread per row feeds a shared queue (the
                        # generators block on the decode loop, so a
                        # single-threaded round-robin would stall every
                        # row behind the slowest).
                        out_q = queue_mod.SimpleQueue()

                        def pump(i):
                            # Pumps feed the reconnect log DIRECTLY —
                            # not via the merger — so a dead merger
                            # (client gone) never stops the log, and a
                            # detached request keeps journaling its
                            # delivery for the eventual reconnect.
                            try:
                                out_q.put((i, firsts[i]))
                                if resume_log is not None:
                                    resume_log.append(rid, i, firsts[i])
                                for token in sources[i]:
                                    out_q.put((i, token))
                                    if resume_log is not None:
                                        resume_log.append(rid, i, token)
                                out_q.put((i, _ROW_DONE))
                                if resume_log is not None:
                                    resume_log.row_done(rid)
                            except Exception as e:
                                out_q.put((i, e))
                                if resume_log is not None:
                                    resume_log.finish(rid, error=e)

                        # Pumps ride the same bounded pool. Rows beyond
                        # the worker count pump after earlier rows
                        # finish — their tokens buffer in the server's
                        # per-request queues meanwhile, so decode never
                        # stalls on pump scheduling.
                        for i in range(len(prompts)):
                            row_pool.submit(pump, i)
                        generated = [[] for _ in prompts]
                        live = len(prompts)
                        try:
                            while live:
                                i, item = out_q.get()
                                if item is _ROW_DONE:
                                    live -= 1
                                    continue
                                if isinstance(item, Exception):
                                    # Attribute the failing row: the HTTP
                                    # layer's final {"error": ...} document
                                    # carries it (status.py), so healthy
                                    # rows' truncation is diagnosable.
                                    item.stream_row = i
                                    raise item
                                generated[i].append(item)
                                yield {"row": i, "token": item}
                        except GeneratorExit:
                            # The HTTP layer closed us: the client is
                            # gone. Without durability, cancel every
                            # row so slots and pages free at the next
                            # decode boundary instead of decoding out
                            # the reserved budgets (models/serving.py
                            # cancel); the pump threads unblock on the
                            # RequestCancelled their streams receive.
                            # With checkpointing on (rung 22) the
                            # disconnect DETACHES instead: the rows
                            # decode on, the pumps keep feeding the
                            # reconnect log, and the client stitches
                            # the stream back with emitted_offset.
                            if resume_log is None:
                                for src in sources:
                                    if src is not None:
                                        src.cancel()
                            raise
                        yield {
                            "done": True,
                            "tokens": [p + g for p, g
                                       in zip(prompts, generated)],
                            "n_new": n_new,
                            "restored_step": restored_step,
                            "request_id": rid,
                        }

                    return {"_stream": ndjson(), "request_id": rid}

                rows: list = [None] * len(tokens)

                def one_row(i):
                    rows[i] = paged_server.submit(
                        [t % tcfg.vocab for t in tokens[i]], n_new,
                        sampling=row_sampling(i),
                        priority=priority, deadline_ms=deadline_ms,
                        request_id=rid,
                    )

                # Buffered requests register for reconnect too: the
                # submitter blocks server-side through a recovery, so
                # a client whose connection died mid-wait re-asks with
                # emitted_offset=0 and collects the finished tokens.
                if resume_log is not None:
                    resume_log.open(rid, len(tokens), n_new)
                try:
                    fan_out_rows(len(tokens), one_row)
                except Exception as e:
                    if resume_log is not None:
                        resume_log.finish(rid, error=e)
                    raise
                if resume_log is not None:
                    for i, row in enumerate(rows):
                        for t in row[len(tokens[i]):]:
                            resume_log.append(rid, i, t)
                    resume_log.finish(rid)
                return {
                    "tokens": rows,
                    "n_new": n_new,
                    "restored_step": restored_step,
                    "request_id": rid,
                }
            prompt = jnp.asarray(tokens, jnp.int32) % tcfg.vocab
            if spec:
                from kvedge_tpu.models import generate_speculative

                with lock:
                    out, rate = generate_speculative(
                        params, prompt, tcfg, n_new=n_new, draft_len=spec
                    )
                return {
                    "tokens": [[int(t) for t in out.tolist()[0]]],
                    "n_new": n_new,
                    "restored_step": restored_step,
                    "request_id": rid,
                    # Observability: mean tokens emitted per verify pass
                    # (1.0 = speculation never paid; draft_len + 1 =
                    # every draft accepted).
                    "accepted_per_step": round(float(rate), 3),
                }
            sampling = None
            if sampled:
                seed_keys = jax.vmap(
                    lambda i: jax.random.fold_in(base_key, i)
                )(jnp.arange(len(tokens)))
                sampling = (seed_keys, jnp.float32(temperature),
                            jnp.float32(top_p))
            with lock:
                out = generate(params, prompt, tcfg, n_new=n_new,
                               sampling=sampling, sampled=sampled)
            return {
                "tokens": [[int(t) for t in row] for row in out.tolist()],
                "n_new": n_new,
                "restored_step": restored_step,
                "request_id": rid,
            }

        # Request accounting around _serve: the serving half of the
        # observability story (/metrics kvedge_serve_* gauges); counter
        # vocabulary and outcome mapping live in _ServeCounters (shared
        # with the multi-host leader).
        counters = _ServeCounters()

        def serve_fn(doc: dict) -> dict:
            counters.count("requests_total")
            start = time_mod.perf_counter()
            try:
                result = _serve(doc)
            except Exception as e:
                counters.count_outcome(e)
                raise
            stream = result.get("_stream")
            if stream is None:
                counters.count("tokens_generated_total",
                               result["n_new"] * len(result["tokens"]))
                counters.finish(start)
                return result

            def counted():
                # Latency for a streamed request = admission to final
                # document; tokens count as they actually go out. A
                # consumer abandoning the iterator mid-stream therefore
                # never records a completion — matching what the client
                # observed. A mid-decode FAILURE is not abandonment: it
                # lands in the same outcome buckets as non-streamed
                # requests (the HTTP status is already committed, but
                # the operator's error counters must still see it).
                try:
                    for item in stream:
                        if "token" in item:
                            counters.count("tokens_generated_total")
                        yield item
                except GeneratorExit:
                    # Closed by the HTTP layer on client disconnect:
                    # propagate so the inner generator cancels its rows.
                    # Still no completion recorded — matching what the
                    # client observed.
                    stream.close()
                    raise
                except Exception as e:
                    counters.count_outcome(e)
                    raise
                counters.finish(start)

            return {**result, "_stream": counted()}

        def serve_stats() -> dict:
            out = counters.snapshot()
            out["backend"] = backend or (
                "paged" if paged_server is not None else "contiguous"
            )
            if backend is not None:
                out["processes"] = jax.process_count()
            if paged_server is not None:
                # Pool occupancy straight from the server (in_flight,
                # free_slots, free_pages, reserved_pages).
                out.update(paged_server.stats())
            if recovery_sup is not None:
                # Recovery-machine gauges/counters (serve_recovering,
                # attempt totals) ride the same snapshot.
                out.update(recovery_sup.stats())
            return out

        serve_fn.stats = serve_stats
        # Flight-recorder handle for the HTTP layer: boot.py's /trace
        # closure reads this attribute at request time (None = 404,
        # tracing off). Plain reference — survives revive()/reform.
        serve_fn.tracer = tracer
        # Lock-free degraded probe for /healthz (boot.py): reading
        # stats() takes the server lock, which a health check must not
        # depend on; the property is a bare attribute read.
        serve_fn.degraded = (
            (lambda: paged_server.degraded)
            if paged_server is not None else (lambda: None)
        )
        # Lock-free capacity probe for /healthz's recovering payload
        # (satellite of rung 22): pages_free/pages_total/bucket as bare
        # attribute reads — same no-lock contract as `degraded`.
        if paged_server is not None:
            serve_fn.capacity = paged_server.capacity_probe
        # SLO + flight-bundle handles for the HTTP layer (rung 25):
        # boot.py's /slo and /debug/bundle closures call these at
        # request time. None = the route 404s with its knob pointer.
        serve_fn.slo = (
            paged_server.slo_doc
            if paged_server is not None and cfg.serving_slo
            else None
        )
        serve_fn.bundle = (
            paged_server.flight_bundle
            if paged_server is not None and cfg.serving_bundle
            else None
        )
        # Recovery-machine probe for /healthz: while the supervisor is
        # recovering, boot.health_detail reports 503 NON-terminal with
        # a retry-after hint; terminal only after escalation.
        if recovery_sup is not None:
            serve_fn.recovery = recovery_sup.health

        # Self-check: one tiny generation proves the restored params and
        # the decode path actually work before the endpoint goes live.
        # Sized from the model so a small (legal) train_seq cannot fail a
        # servable payload; max_seq == 1 genuinely cannot serve (every
        # request needs prompt + n_new >= 2) and errors out here.
        if tcfg.max_seq < 2:
            raise ValueError(
                f"[payload] seq = {tcfg.max_seq} is too small to serve: "
                "every request needs prompt + n_new >= 2"
            )
        probe_prompt = list(range(1, min(4, tcfg.max_seq - 1) + 1))
        probe_new = min(2, tcfg.max_seq - len(probe_prompt))
        start = time_mod.perf_counter()
        # Through _serve, not the counted wrapper: the boot self-check is
        # not operator traffic, so the kvedge_serve_* counters start at 0.
        probe = _serve({"tokens": [probe_prompt], "n_new": probe_new})
        elapsed_ms = (time_mod.perf_counter() - start) * 1000.0
        # Teardown path: the paged server owns a decode thread and the
        # device-side page pool, plus the bounded row pool; callers
        # (RuntimeHandle.shutdown, test fixtures) release them via
        # serve_fn.close(). drain=True finishes in-flight budgets
        # before stopping (models/serving.py close semantics).
        def _close(drain: bool = False) -> None:
            if recovery_sup is not None:
                # A recovery racing shutdown must not revive a pool the
                # close below is tearing down.
                recovery_sup.stop()
            if paged_server is not None:
                paged_server.close(drain=drain)
                if prefix_path:
                    # AFTER close: a drain's late completions register
                    # prefixes too, and the registry + device pool
                    # survive close (nothing clears them). Best-effort:
                    # a failed dump must not block the shutdown path.
                    try:
                        paged_server.dump_prefix_cache(prefix_path, fp)
                    except Exception as e:
                        print(f"[kvedge-serve] prefix-cache dump "
                              f"failed: {e!r}", flush=True)
            if row_pool is not None:
                # Drain must let QUEUED pumps run: a streamed request
                # wider than the pool still has rows waiting to pump,
                # and cancelling them would leave its ndjson merger
                # blocked on row-done markers that never come. The
                # pumps finish promptly — the drained server has
                # already completed (or poisoned) every stream queue.
                row_pool.shutdown(wait=drain, cancel_futures=not drain)

        serve_fn.close = _close
        return dataclasses.replace(
            base, probe_ms=elapsed_ms,
            probe_checksum=float(sum(probe["tokens"][0])),
        ), serve_fn
    except Exception:
        # paged_server.close() also releases a slice cache's followers
        # (the cache.stop hook); if the failure desynced the broadcast
        # stream the slice is already lost (restart path).
        if recovery_sup is not None:
            recovery_sup.stop()
        if paged_server is not None:
            paged_server.close()
        if row_pool is not None:
            row_pool.shutdown(wait=False, cancel_futures=True)
        raise


# Inference probe: small GQA model, short prompt, a few greedy steps.
PROBE_KV_HEADS = 2
PROBE_PROMPT = 8
PROBE_NEW_TOKENS = 4


def run_inference_probe(cfg: RuntimeConfig) -> DeviceCheckResult:
    """Prove the serving path: cached greedy decode == teacher forcing."""
    base = run_device_check(cfg)
    if not base.ok:
        return base

    import dataclasses
    import time

    import jax
    import jax.numpy as jnp

    from kvedge_tpu.models import (
        TransformerConfig, forward, generate, init_params,
    )

    tcfg = TransformerConfig(
        vocab=PROBE_VOCAB,
        d_model=PROBE_D_MODEL,
        n_heads=4,
        n_kv_heads=PROBE_KV_HEADS,
        n_layers=PROBE_LAYERS,
        d_ff=4 * PROBE_D_MODEL,
        max_seq=PROBE_SEQ,
    )
    try:
        params = init_params(jax.random.PRNGKey(0), tcfg)
        prompt = jax.random.randint(
            jax.random.PRNGKey(1), (2, PROBE_PROMPT), 0, tcfg.vocab,
            dtype=jnp.int32,
        )
        start = time.perf_counter()
        out = generate(params, prompt, tcfg, n_new=PROBE_NEW_TOKENS)
        out.block_until_ready()
        elapsed_ms = (time.perf_counter() - start) * 1000.0

        # Cross-check every generated token against the cache-less forward
        # pass — the decode path must reproduce training-time math exactly.
        so_far = prompt
        for _ in range(PROBE_NEW_TOKENS):
            nxt = jnp.argmax(forward(params, so_far, tcfg)[:, -1], axis=-1)
            so_far = jnp.concatenate(
                [so_far, nxt[:, None].astype(jnp.int32)], axis=1
            )
        if not bool(jnp.all(out == so_far)):
            return dataclasses.replace(
                base, ok=False,
                error="inference probe: cached decode disagrees with "
                      "teacher-forced forward pass",
            )
    except Exception as e:
        return dataclasses.replace(
            base, ok=False, error=f"inference probe failed: {e!r}",
        )
    return dataclasses.replace(
        base, probe_ms=elapsed_ms, probe_checksum=float(out.sum()),
    )
