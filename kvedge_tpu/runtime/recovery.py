"""The recovery half of the serving failure contract: heal in process.

runtime/failures.py is the *detection* half — typed taxonomy, deadline-
bounded ops, a pool that poisons instead of deadlocking. Until now the
only recovery was the worst case: flip /healthz terminal and wait for a
full pod replacement plus recompile. This module closes the loop with a
supervisor that owns an explicit state machine for the serving pool:

    healthy -> degraded -> recovering -> healthy
                              |
                              +-------> terminal (escalate: reschedule)

On a poisoning failure the supervisor, on its own worker thread:

1. **tears down** the dead op stream — joins the exited decode thread
   and shuts down the wedged :class:`DeadlineRunner` (its orphaned
   worker stays parked; the stream object is replaced, not revived);
2. **reforms the slice** (slice caches only): installs a fresh runner
   and runs a deadline-bounded barrier SYNC through it, so a follower
   that rejoined ``follow_paged`` (workload.py re-enters it instead of
   exiting) re-syncs tables/lengths and the op stream is live again;
3. **warm-restarts** the pool: :meth:`PagedGenerationServer.revive`
   clears the poison and restarts the decode loop over a scrubbed pool,
   then the emergency prefix-cache dump reloads and (single-host) the
   params re-restore via ``StateCheckpointer.restore_latest`` — compiled
   programs survive throughout, so no recompile is paid;
4. **retries with exponential backoff + jitter** under an attempt
   budget, and consults the PVC ``init-events.jsonl`` / ``boot_count``
   history as a **crash-loop breaker**: a volume that already witnessed
   repeated failed recoveries or supervisor give-ups escalates straight
   to today's terminal 503 path instead of thrashing.

While recovering, /healthz stays 503 but NON-terminal (boot.py), with a
retry-after hint derived from the measured recovery time — so probes
(healthcheck.wait_healthy) keep polling instead of fast-failing, and
clients refused by the poisoned pool get an honest wait estimate.
Escalation restores exactly the old contract: terminal 503, reschedule.

Every recovery outcome is appended to ``init-events.jsonl`` — the same
lifecycle log the native PID-1 supervisor writes — so the breaker's
memory survives pod generations the way the heartbeat's boot_count does.
"""

from __future__ import annotations

import os
import random
import threading
import time
from dataclasses import dataclass

from kvedge_tpu.runtime import heartbeat

# State-machine states (plain strings: they travel through stats()/JSON).
HEALTHY = "healthy"
DEGRADED = "degraded"
RECOVERING = "recovering"
TERMINAL = "terminal"

# init-events.jsonl event names that count as crash-loop strikes: the
# native supervisor's give-up, plus this module's own failed outcomes.
_STRIKE_EVENTS = ("give-up",)


class RecoveryError(RuntimeError):
    """One recovery attempt failed (teardown/reform/revive stage)."""


@dataclass
class RecoveryPolicy:
    """Knobs for the supervisor's retry discipline.

    Defaults suit production (seconds-scale backoff against a slice
    whose follower pod needs time to restart); tests shrink everything.
    ``barrier_budget_s = None`` lets the reformation barrier use the op
    stream's own steady budget.
    """

    max_attempts: int = 3
    backoff_base_s: float = 1.0
    backoff_cap_s: float = 30.0
    jitter: float = 0.25           # +/- fraction of the delay
    barrier_budget_s: float | None = None
    teardown_budget_s: float = 60.0
    # Crash-loop breaker: this many strikes (supervisor give-ups or
    # failed/escalated recoveries) within the recent init-events window
    # veto in-process recovery — the volume's history says this pod
    # lineage is thrashing, so escalate to the reschedule path at once.
    crash_loop_window: int = heartbeat.INIT_EVENTS_TAIL
    crash_loop_threshold: int = 3


def sweep_stranded_tmp(state_dir: str) -> list[str]:
    """Remove stranded ``*.tmp`` files from the state dir (boot time).

    Every atomic write in the state dir (prefix-cache dumps, heartbeat
    and failure records) stages through ``<name>.tmp`` + ``os.replace``;
    a SIGKILL mid-dump strands the tmp file — a multi-hundred-MB corpse
    for a prefix dump — and nothing cleaned it up. At boot no other
    writer exists yet, so every surviving tmp is garbage by definition.
    Returns the removed names (top level only; best-effort)."""
    if not state_dir or not os.path.isdir(state_dir):
        return []
    removed = []
    for name in sorted(os.listdir(state_dir)):
        if not name.endswith(".tmp"):
            continue
        path = os.path.join(state_dir, name)
        if not os.path.isfile(path):
            continue
        try:
            os.remove(path)
        except OSError:
            continue
        removed.append(name)
    return removed


class RecoverySupervisor:
    """Watches one :class:`PagedGenerationServer` and heals it in place.

    ``attach()`` chains onto the server's ``on_degraded`` observer (the
    existing failure-record writer keeps running first) and installs the
    measured retry-after hint; from then on every poisoning failure
    starts a recovery worker instead of ending the story at terminal.

    The server and its cache are driven through their public recovery
    seams — ``cache.reform()`` (slice) and ``server.revive()`` — so the
    supervisor holds no serving state of its own beyond the machine.
    """

    def __init__(self, server, *, policy: RecoveryPolicy | None = None,
                 state_dir: str = "", seed: int | None = None,
                 prefix_path: str = "", prefix_fingerprint: str = "",
                 restore_params=None):
        self.server = server
        self.policy = policy or RecoveryPolicy()
        self.state_dir = state_dir
        self.prefix_path = prefix_path
        self.prefix_fingerprint = prefix_fingerprint
        # Optional () -> params: re-restore from the latest checkpoint
        # during warm restart (workload wires StateCheckpointer via
        # _restore_latest_params; single-host only — a slice restore is
        # a collective the supervisor thread must not run alone).
        self.restore_params = restore_params
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self.state = HEALTHY
        self._attempts_total = 0
        self._recoveries = 0
        self._failures = 0
        self._last_recovery_s: float | None = None
        self._recovering_since: float | None = None
        self._worker: threading.Thread | None = None
        self._stopped = threading.Event()
        # Set whenever the machine is at rest (healthy or terminal) —
        # what tests and drain paths wait on.
        self._settled = threading.Event()
        self._settled.set()

    # ---- wiring ----------------------------------------------------------

    def attach(self) -> "RecoverySupervisor":
        """Chain onto the server's degraded observer + retry-after hint."""
        prev = self.server.on_degraded

        def observer(reason, failure):
            if prev is not None:
                try:
                    prev(reason, failure)
                except Exception as e:
                    print(f"[kvedge-recover] chained on_degraded "
                          f"observer failed: {e!r}", flush=True)
            self._on_degraded(reason, failure)

        self.server.on_degraded = observer
        self.server.retry_after_hint = self.retry_after_hint
        return self

    def stop(self) -> None:
        """Abandon recovery (server shutdown): in-flight attempts abort
        at the next stage boundary and no new ones start."""
        self._stopped.set()
        self._settled.set()

    # ---- observability ---------------------------------------------------

    def stats(self) -> dict:
        out = {
            "recovering": 1 if self.state == RECOVERING else 0,
            "recovery_state": self.state,
            "recovery_attempts_total": self._attempts_total,
            "recoveries_total": self._recoveries,
            "recovery_failures_total": self._failures,
        }
        if self._last_recovery_s is not None:
            out["last_recovery_s"] = round(self._last_recovery_s, 3)
        return out

    def health(self) -> dict:
        """The /healthz enrichment while not healthy (boot.py merges
        it): ``terminal`` only after escalation; while recovering the
        body says so and carries the measured retry-after hint."""
        doc = {"state": self.state, "terminal": self.state == TERMINAL}
        hint = self.retry_after_hint()
        if hint is not None:
            doc["retry_after_s"] = hint
        return doc

    def retry_after_hint(self) -> float | None:
        """Measured recovery time as the client's wait estimate, while
        a recovery is actually running: the last successful recovery's
        duration minus what this one has already spent (floored to 1 s).
        None otherwise — the server then falls back to its configured
        static hint (serving_retry_after_s)."""
        if self.state != RECOVERING:
            return None
        last = self._last_recovery_s
        if last is None:
            return None
        since = self._recovering_since
        elapsed = 0.0 if since is None else time.monotonic() - since
        return round(max(1.0, last - elapsed), 1)

    def wait_settled(self, timeout: float | None = None) -> str:
        """Block until the machine is at rest; returns the state."""
        self._settled.wait(timeout=timeout)
        return self.state

    # ---- crash-loop breaker ----------------------------------------------

    def _crash_loop_reason(self) -> str | None:
        """Non-None when the volume's history vetoes in-process
        recovery: count supervisor give-ups and failed/escalated
        recoveries in the recent init-events window."""
        if not self.state_dir:
            return None
        events = heartbeat.read_init_events(
            self.state_dir, tail=self.policy.crash_loop_window
        )
        strikes = sum(1 for e in events if self._is_strike(e))
        if strikes >= self.policy.crash_loop_threshold:
            boot = (heartbeat.read_heartbeat(self.state_dir)
                    or {}).get("boot_count", 0)
            return (f"{strikes} crash-loop strikes in the last "
                    f"{len(events)} init events (boot_count {boot}) — "
                    f"this lineage is thrashing")
        return None

    @staticmethod
    def _is_strike(event: dict) -> bool:
        if not isinstance(event, dict):
            return False
        name = event.get("event")
        if name in _STRIKE_EVENTS:
            return True
        return (name == "serve-recovery"
                and event.get("outcome") in ("failed", "escalated"))

    def _trace_event(self, name: str, detail: str = "") -> None:
        """Land a recovery instant in the server's flight recorder
        (runtime/tracing.py) so heal attempts and outcomes sit in the
        same timeline as the failure that started them."""
        tr = getattr(self.server, "tracer", None)
        if tr is not None:
            tr.event(name, "recovery",
                     args={"detail": detail[:160]} if detail else None)

    def _record(self, outcome: str, detail: str = "") -> None:
        """Append one recovery event to init-events.jsonl (best-effort;
        the breaker's cross-generation memory)."""
        self._trace_event(f"recovery-{outcome}", detail)
        if not self.state_dir:
            return
        doc = {"event": "serve-recovery", "outcome": outcome}
        if detail:
            doc["detail"] = detail
        try:
            heartbeat.append_init_event(self.state_dir, doc)
        except OSError as e:
            print(f"[kvedge-recover] init-event append failed: {e!r}",
                  flush=True)

    # ---- the state machine -----------------------------------------------

    def _on_degraded(self, reason, failure) -> None:
        """Runs on the dying decode thread (after _degrade), or on the
        submit thread for a submit-path poisoning — must not block:
        decide, then hand off to a worker thread."""
        with self._lock:
            if self.state in (RECOVERING, TERMINAL):
                return
            self.state = DEGRADED
            self._settled.clear()
            if self._stopped.is_set():
                self._escalate("supervisor stopped")
                return
            veto = self._crash_loop_reason()
            if veto is not None:
                print(f"[kvedge-recover] crash-loop breaker tripped: "
                      f"{veto}; escalating to terminal", flush=True)
                self._escalate(veto)
                return
            self.state = RECOVERING
            self._recovering_since = time.monotonic()
            self._trace_event("recovery-start", str(reason))
            self._worker = threading.Thread(
                target=self._recover, args=(reason,),
                name="kvedge-recover", daemon=True,
            )
            self._worker.start()

    def _escalate(self, detail: str) -> None:
        """Give up on in-process recovery: the pool stays poisoned, the
        terminal 503 path takes over (lock held or single-threaded)."""
        self.state = TERMINAL
        self._failures += 1
        self._record("escalated", detail)
        self._settled.set()

    def _backoff(self, attempt: int) -> float:
        base = min(self.policy.backoff_cap_s,
                   self.policy.backoff_base_s * (2 ** (attempt - 1)))
        return base * (1.0 + self.policy.jitter
                       * (2.0 * self._rng.random() - 1.0))

    def _recover(self, reason) -> None:
        start = time.monotonic()
        for attempt in range(1, self.policy.max_attempts + 1):
            if self._stopped.is_set():
                with self._lock:
                    self._escalate("supervisor stopped mid-recovery")
                return
            self._attempts_total += 1
            try:
                restored = self._attempt_once()
            except Exception as e:
                print(f"[kvedge-recover] attempt {attempt}/"
                      f"{self.policy.max_attempts} failed: {e!r}",
                      flush=True)
                self._record("failed",
                             f"attempt {attempt}: {type(e).__name__}")
                if attempt < self.policy.max_attempts:
                    time.sleep(self._backoff(attempt))
                continue
            took = time.monotonic() - start
            with self._lock:
                self._last_recovery_s = took
                self._recovering_since = None
                self._recoveries += 1
                self.state = HEALTHY
                self._settled.set()
            self._record("healed",
                         f"attempt {attempt} in {took:.2f}s, "
                         f"{restored} in-flight restored "
                         f"(was: {reason})")
            print(f"[kvedge-recover] pool healed in {took:.2f}s "
                  f"(attempt {attempt}; was: {reason})", flush=True)
            return
        with self._lock:
            self._escalate(
                f"{self.policy.max_attempts} recovery attempts "
                f"exhausted (was: {reason})"
            )
        print(f"[kvedge-recover] recovery exhausted after "
              f"{self.policy.max_attempts} attempts; pool is terminal "
              f"(was: {reason})", flush=True)

    def _attempt_once(self) -> int:
        """One teardown -> reform -> revive -> warm-restart pass. Any
        exception fails the attempt (the pool stays poisoned and the
        next attempt — or escalation — takes over). Returns the count
        of journaled in-flight requests revive() restored (rung 22)."""
        server = self.server
        # 1. Teardown: the decode loop exits on poison; wait for it so
        # revive() can install a fresh one. A loop still wedged past
        # the budget means the failure is NOT the deadline-bounded kind
        # this supervisor can heal (e.g. a single-host device hang
        # outside the watchdog) — fail the attempt.
        thread = server._thread
        thread.join(timeout=self.policy.teardown_budget_s)
        if thread.is_alive():
            raise RecoveryError(
                "decode thread still running after "
                f"{self.policy.teardown_budget_s:g}s — cannot revive"
            )
        # 2. Slice reformation (slice caches only): fresh DeadlineRunner
        # + barrier SYNC with a deadline. Raises SliceFollowerLost if
        # the followers are still gone — the attempt fails and backoff
        # buys the follower pod time to restart and rejoin.
        reform = getattr(server._cache, "reform", None)
        if reform is not None:
            reform(budget_s=self.policy.barrier_budget_s)
        if self._stopped.is_set():
            raise RecoveryError("supervisor stopped before revive")
        # 3. Warm restart: scrub + restart the pool in place (compiled
        # programs survive — this is the whole point vs rescheduling).
        # revive() also re-admits every journaled in-flight request
        # (rung 22 checkpoints) — the count rides into the healed
        # record so a post-mortem shows how many requests survived.
        restored = int(server.revive() or 0)
        # 4. Reload state: params from the latest checkpoint (best-
        # effort — the on-device params are intact unless the failure
        # corrupted them, and a missing checkpoint must not fail an
        # otherwise-good recovery) ...
        if self.restore_params is not None:
            try:
                params = self.restore_params()
                if params is not None:
                    server._params = params
            except Exception as e:
                print(f"[kvedge-recover] checkpoint re-restore skipped "
                      f"({e!r}); serving with in-memory params",
                      flush=True)
        # ... and the emergency prefix dump _degrade() wrote on the way
        # down (single-host only; the revive scrubbed every pin).
        if self.prefix_path:
            try:
                n = server.load_prefix_cache(
                    self.prefix_path, self.prefix_fingerprint
                )
                if n:
                    print(f"[kvedge-recover] re-pinned {n} prefix "
                          f"entries from the emergency dump", flush=True)
            except Exception as e:
                print(f"[kvedge-recover] prefix reload skipped "
                      f"({e!r})", flush=True)
        return restored
