"""Configuration surface: chart values and the opaque runtime config payload.

Two-tier config, mirroring the reference (SURVEY.md §5 "Config / flag system"):

(a) chart values — exactly six flags, the analogue of
    ``deployment/helm/values.yaml:1-17`` (:mod:`kvedge_tpu.config.values`);
(b) opaque payload config — a TOML document passed by file, base64'd into a
    Secret, surfaced in the container as a mounted file, and applied by the
    bootstrap step (:mod:`kvedge_tpu.config.runtime_config`), the analogue of
    the IoT Edge ``config.toml`` pipeline
    (``aziot-edge-runtime-config-secret.yaml:6`` -> ``_helper.tpl:61-74``).
"""

from kvedge_tpu.config.values import ChartValues, DEFAULT_VALUES
from kvedge_tpu.config.runtime_config import RuntimeConfig

__all__ = ["ChartValues", "DEFAULT_VALUES", "RuntimeConfig"]
