"""The chart's entire user-facing config surface: six mirrored values + one.

Six values mirror the reference's ``deployment/helm/values.yaml``
value-for-value (SURVEY.md §2 #2); ``tpuNumHosts`` is the one documented
addition (multi-host slices — see its field comment). The mapping, with the
reference value each one replaces:

====================================  =========================================
reference (values.yaml)               kvedge-tpu
====================================  =========================================
``aziotEdgeVmDiskSize`` (4Gi, :2)     ``tpuRuntimeDiskSize`` — state PVC size
``aziotEdgeVmEnableExternalSsh``      ``tpuRuntimeEnableExternalSsh`` — gate
  (true, :5)                            for the LoadBalancer access service
``nameOverride``                      ``nameOverride`` — resource-name prefix,
  (chart name, :8)                      defaults to chart name, trunc 40
``publicSshKey`` ("", :11)            ``publicSshKey`` — authorized key for
                                        the in-pod sshd
``azIotEdgeConfig`` ("", :14)         ``jaxRuntimeConfig`` — opaque runtime
                                        TOML passed with ``--set-file``
``macAddress``                        ``tpuAccelerator`` — stable hardware
  (fe:7e:48:a0:7d:22, :17)              identity: the GKE TPU accelerator
                                        node-selector value. (The reference
                                        pins a MAC so the VM's NIC identity
                                        survives restarts; on TPU nodes the
                                        identity that must stay stable across
                                        rescheduling is the accelerator type.)
====================================  =========================================
"""

from __future__ import annotations

import dataclasses
import re

_DISK_SIZE_RE = re.compile(r"^[1-9][0-9]*(Ei|Pi|Ti|Gi|Mi|Ki|E|P|T|G|M|K)?$")
# GKE TPU accelerator node-selector values are DNS-label-ish tokens.
_ACCELERATOR_RE = re.compile(r"^[a-z0-9]([a-z0-9-]*[a-z0-9])?$")


@dataclasses.dataclass(frozen=True)
class ChartValues:
    """The chart values: six reference mirrors + ``tpuNumHosts`` (see
    module docstring for the reference mapping)."""

    # State PVC size (reference: aziotEdgeVmDiskSize, values.yaml:2).
    tpuRuntimeDiskSize: str = "4Gi"
    # Create a LoadBalancer service for external SSH/status access
    # (reference: aziotEdgeVmEnableExternalSsh, values.yaml:5).
    tpuRuntimeEnableExternalSsh: bool = True
    # Resource-name prefix; empty ("" = unset, the reference's shipped
    # default) falls back to the chart name via the name helper's `default`
    # and is truncated to 40 chars either way (reference: nameOverride,
    # values.yaml:8). Shipping "" rather than the chart name keeps the
    # unset path — the one the reference's raw-.Values Secret ref broke
    # (aziot-edge-vm.yaml:57, live TODO) — exercised by every default
    # render; tests/test_names.py pins the fallback.
    nameOverride: str = ""
    # SSH public key authorized inside the runtime pod
    # (reference: publicSshKey, values.yaml:11).
    publicSshKey: str = ""
    # Opaque runtime config TOML, usually passed via --set-file
    # (reference: azIotEdgeConfig, values.yaml:14).
    jaxRuntimeConfig: str = ""
    # Stable hardware identity: GKE TPU accelerator type for the node selector
    # (reference: macAddress, values.yaml:17).
    tpuAccelerator: str = "tpu-v5-lite-podslice"
    # Hosts in the TPU slice. 1 (default) renders the reference-shaped
    # single-replica Deployment; N > 1 renders a StatefulSet + headless
    # service spanning an N-host slice (e.g. 4 for v5e-16). This is the one
    # deliberate addition to the reference's six-value surface: a KubeVirt
    # VM can never span hosts, but a TPU slice payload can, and the
    # resource *shape* (Deployment vs StatefulSet) must be decided at
    # render time. See kvedge_tpu/render/manifests.py:runtime_statefulset.
    tpuNumHosts: int = 1

    def validate(self) -> None:
        # Resource names must be RFC 1123 labels after the prefix is applied;
        # empty means "fall back to the chart name" (the helper's `default`).
        if self.nameOverride and not _ACCELERATOR_RE.match(self.nameOverride):
            raise ValueError(
                f"nameOverride {self.nameOverride!r} is not a valid Kubernetes "
                "resource-name prefix (lowercase RFC 1123)"
            )
        if not _DISK_SIZE_RE.match(self.tpuRuntimeDiskSize):
            raise ValueError(
                f"tpuRuntimeDiskSize {self.tpuRuntimeDiskSize!r} is not a "
                "valid Kubernetes quantity (e.g. 4Gi)"
            )
        if not isinstance(self.tpuRuntimeEnableExternalSsh, bool):
            raise ValueError("tpuRuntimeEnableExternalSsh must be a bool")
        if not _ACCELERATOR_RE.match(self.tpuAccelerator):
            raise ValueError(
                f"tpuAccelerator {self.tpuAccelerator!r} is not a valid "
                "node-selector value"
            )
        if not isinstance(self.tpuNumHosts, int) or isinstance(
            self.tpuNumHosts, bool
        ) or self.tpuNumHosts < 1:
            raise ValueError(
                f"tpuNumHosts must be a positive integer, got "
                f"{self.tpuNumHosts!r}"
            )

    def replace(self, **kwargs) -> "ChartValues":
        values = dataclasses.replace(self, **kwargs)
        values.validate()
        return values


DEFAULT_VALUES = ChartValues()

_BOOL_VALUES = {"true": True, "false": False}


def parse_set_flag(values: ChartValues, assignment: str) -> ChartValues:
    """Apply one ``--set key=value`` assignment, helm-style.

    Mirrors the install interface of ``helm install --set ...``
    (reference ``README.md:60``). Booleans accept ``true``/``false``.
    """
    key, sep, raw = assignment.partition("=")
    if not sep:
        raise ValueError(f"--set expects key=value, got {assignment!r}")
    if key not in {f.name for f in dataclasses.fields(ChartValues)}:
        raise ValueError(f"unknown value {key!r}")
    current = getattr(values, key)
    if isinstance(current, bool):
        if raw.lower() not in _BOOL_VALUES:
            raise ValueError(f"{key} expects true or false, got {raw!r}")
        parsed: object = _BOOL_VALUES[raw.lower()]
    elif isinstance(current, int):
        try:
            parsed = int(raw)
        except ValueError:
            raise ValueError(f"{key} expects an integer, got {raw!r}") from None
    else:
        parsed = raw
    return values.replace(**{key: parsed})


def parse_set_file_flag(values: ChartValues, assignment: str) -> ChartValues:
    """Apply one ``--set-file key=path`` assignment, helm-style.

    The reference passes the opaque IoT Edge config this way:
    ``--set-file azIotEdgeConfig=config.toml`` (``README.md:60``).
    """
    key, sep, path = assignment.partition("=")
    if not sep:
        raise ValueError(f"--set-file expects key=path, got {assignment!r}")
    with open(path, "r", encoding="utf-8") as fh:
        content = fh.read()
    if key not in {f.name for f in dataclasses.fields(ChartValues)}:
        raise ValueError(f"unknown value {key!r}")
    if isinstance(getattr(values, key), bool):
        raise ValueError(f"{key} cannot be set from a file")
    return values.replace(**{key: content})
