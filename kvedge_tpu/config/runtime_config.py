"""The opaque runtime-config payload: a TOML document, applied at boot.

This is the analogue of the IoT Edge ``config.toml`` the reference treats as
an opaque value: the operator passes a TOML file at install time
(``--set-file azIotEdgeConfig=config.toml``, reference ``README.md:60``), the
chart base64's it into a Secret under the key ``userdata``
(``aziot-edge-runtime-config-secret.yaml:6``), the Secret surfaces in the
guest as a serial-tagged disk, and cloud-init copies it to
``/etc/aziot/config.toml`` and runs ``iotedge config apply``
(``_helper.tpl:70-74``).

Here the payload is the JAX runtime's config: mesh shape, expected TPU
topology, state/heartbeat layout, status endpoint, and which payload to run.
``kvedge config apply`` (:func:`RuntimeConfig.apply`) validates it and
materializes it at ``/etc/kvedge/config.toml``.
"""

from __future__ import annotations

import dataclasses
import json
import os
try:
    import tomllib
except ModuleNotFoundError:  # python < 3.11: same API under the old name
    import tomli as tomllib  # type: ignore[no-redef]
from typing import Mapping


def _toml_str(value: str) -> str:
    """Quote a string as a TOML basic string (JSON escaping is TOML-valid)."""
    return json.dumps(value, ensure_ascii=True)

DEFAULT_CONFIG_PATH = "/etc/kvedge/config.toml"
DEFAULT_STATE_DIR = "/var/lib/kvedge/state"

_VALID_PAYLOADS = (
    "devicecheck", "transformer-probe", "inference-probe", "train", "eval",
    "serve", "none",
)
# "" = auto (ring iff the mesh declares a seq axis); the rest match
# TransformerConfig.attention (models/transformer.py).
_VALID_ATTENTION = ("", "naive", "flash", "ring", "ulysses")


class RuntimeConfigError(ValueError):
    """Raised when the runtime config TOML fails validation."""


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Logical device-mesh shape the runtime should assemble.

    Axis order is meaningful: it is the order handed to
    ``jax.sharding.Mesh``. A zero value means "infer from device count"
    (at most one axis may be zero).
    """

    # Default: all visible devices on the data axis (0 = inferred).
    axes: tuple[tuple[str, int], ...] = (("data", 0), ("model", 1))

    def validate(self) -> None:
        if not self.axes:
            raise RuntimeConfigError("[mesh] axes must be a non-empty table")
        for axis, size in self.axes:
            if not axis:
                raise RuntimeConfigError("mesh axis names must be non-empty")
            if not isinstance(size, int) or isinstance(size, bool) or size < 0:
                raise RuntimeConfigError(
                    f"mesh axis {axis!r} size must be a non-negative int"
                )
        names = self.axis_names()
        if len(set(names)) != len(names):
            raise RuntimeConfigError(f"duplicate mesh axis names in {names}")
        if sum(1 for _, size in self.axes if size == 0) > 1:
            raise RuntimeConfigError("at most one mesh axis may be 0 (inferred)")

    def axis_names(self) -> tuple[str, ...]:
        return tuple(name for name, _ in self.axes)

    def resolved_shape(self, n_devices: int) -> tuple[int, ...]:
        """Concrete mesh shape for ``n_devices``, inferring any zero axis."""
        sizes = [size for _, size in self.axes]
        self.validate()
        zeros = [i for i, s in enumerate(sizes) if s == 0]
        fixed = 1
        for s in sizes:
            if s:
                fixed *= s
        if zeros:
            if n_devices % fixed:
                raise RuntimeConfigError(
                    f"{n_devices} devices not divisible by fixed axes ({fixed})"
                )
            sizes[zeros[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise RuntimeConfigError(
                f"mesh {dict(self.axes)} wants {fixed} devices, have {n_devices}"
            )
        return tuple(sizes)


# Model-shape presets the [model] section may name; the shape tables
# themselves live with the model (kvedge_tpu/models/transformer.py
# PRESETS) — this module stays importable without jax.
_VALID_PRESETS = ("", "probe", "flagship")


def _parse_speculative(value):
    """``serving_speculative``: an int draft length or the string
    "auto" (resolved at serve boot by the relay-economics probe,
    models/serving.py resolve_speculation). Type errors surface in
    validate() with the full accepted-values message."""
    if isinstance(value, str):
        return value  # validate() accepts only "auto"
    return int(value)


def _parse_window(value):
    """``serving_window``: an int window cap or the string "auto"
    (the online controller, SERVING.md rung 26, picks the window per
    boundary from measured R/t). Type errors surface in validate()
    with the full accepted-values message."""
    if isinstance(value, str):
        return value  # validate() accepts only "auto"
    return int(value)


def _parse_trace(value):
    """``serving_trace``: "off"/"on" or a per-request sample rate in
    (0, 1]. Type errors surface in validate() with the full
    accepted-values message."""
    if isinstance(value, str):
        return value  # validate() accepts only "off"/"on"
    if isinstance(value, bool):
        raise RuntimeConfigError(
            "[payload] serving_trace must be 'off', 'on' or a sample "
            "rate in (0, 1] — not a boolean"
        )
    return float(value)


@dataclasses.dataclass(frozen=True)
class ModelSpec:
    """The payload model's architecture ([model] TOML section).

    The reference's most distinctive mechanism is an *opaque payload
    config* pipeline so the operator controls what the payload runs
    (reference ``_helper.tpl:61-74``, ``values.yaml:13-14``); here the
    model IS the payload, so its shape belongs in the same TOML. A
    ``preset`` names a base shape ("probe" — the tiny default — or
    "flagship", the 41.6M-param bench model); any explicitly-set field
    overrides the preset. Zero means "from the preset" (and for
    ``n_heads``/``experts``, "adapted to the mesh" — see
    runtime/workload.py derive_model_config). Explicitly-set values are
    authoritative: a mesh they cannot run on is *refused* with a clear
    error, never silently adjusted.
    """

    preset: str = ""  # "" = "probe"
    vocab: int = 0
    d_model: int = 0
    n_heads: int = 0
    # 0 here means "from the preset" (both presets are MHA); an explicit
    # value enables grouped-query attention (models/decode.py KV-cache
    # shrink by n_heads/n_kv_heads).
    n_kv_heads: int = 0
    n_layers: int = 0
    d_ff: int = 0
    # Mixture-of-experts expert count; 0 = derived from the mesh's
    # ``expert`` axis (dense when the mesh has none).
    experts: int = 0
    expert_top_k: int = 0  # 0 = 1 (Switch); 2 = GShard top-2
    # 0.0 = provably drop-free capacity (factor * top_k >= experts).
    expert_capacity_factor: float = 0.0
    # Pipeline backward schedule when the mesh has a ``stage`` axis:
    # "" / "gpipe" = GPipe + remat (general — composes with MoE and
    # sequence parallelism); "1f1b" = the fused 1F1B schedule with an
    # O(stages) activation stash (dense models, standard attention —
    # parallel/pipeline1f1b.py documents the refusals).
    pipeline_schedule: str = ""

    def validate(self) -> None:
        if self.preset not in _VALID_PRESETS:
            raise RuntimeConfigError(
                f"[model] preset must be one of {_VALID_PRESETS[1:]}, "
                f"got {self.preset!r}"
            )
        for field_name in ("vocab", "d_model", "n_heads", "n_kv_heads",
                           "n_layers", "d_ff", "experts", "expert_top_k"):
            value = getattr(self, field_name)
            if not isinstance(value, int) or isinstance(value, bool) \
                    or value < 0:
                raise RuntimeConfigError(
                    f"[model] {field_name} must be a non-negative int "
                    "(0 = from the preset)"
                )
        if self.expert_capacity_factor < 0:
            raise RuntimeConfigError(
                "[model] expert_capacity_factor must be >= 0 "
                "(0 = drop-free capacity)"
            )
        if self.expert_top_k not in (0, 1, 2):
            raise RuntimeConfigError(
                "[model] expert_top_k must be 1 or 2 (0 = default 1)"
            )
        if self.pipeline_schedule not in ("", "gpipe", "1f1b"):
            raise RuntimeConfigError(
                "[model] pipeline_schedule must be 'gpipe' or '1f1b' "
                "('' = gpipe)"
            )


@dataclasses.dataclass(frozen=True)
class DistributedSpec:
    """Multi-host topology the runtime should join at boot.

    ``num_processes == 1`` (the default) means single-host: no
    coordination service, no ``jax.distributed`` — identical to the
    pre-multi-host behavior. With N > 1 hosts, each pod resolves its own
    process id and the coordinator address at boot
    (:mod:`kvedge_tpu.parallel.distributed`); ``-1`` / ``""`` mean
    "infer from pod identity" (TPU_WORKER_ID / TPU_WORKER_HOSTNAMES env
    on GKE multi-host slices, or a ``<name>-<ordinal>`` hostname).
    """

    num_processes: int = 1
    coordinator_address: str = ""  # "" = infer; "host" or "host:port"
    coordinator_port: int = 8478
    process_id: int = -1  # -1 = infer

    def validate(self) -> None:
        if self.num_processes < 1:
            raise RuntimeConfigError(
                "[distributed] num_processes must be >= 1"
            )
        if not (0 < self.coordinator_port < 65536):
            raise RuntimeConfigError("[distributed] coordinator_port out of range")
        if self.process_id < -1 or self.process_id >= self.num_processes:
            raise RuntimeConfigError(
                f"[distributed] process_id {self.process_id} not in "
                f"[-1, {self.num_processes})"
            )


@dataclasses.dataclass(frozen=True)
class RuntimeConfig:
    """Validated runtime config (the parsed form of the opaque TOML)."""

    name: str = "kvedge-tpu"
    state_dir: str = DEFAULT_STATE_DIR
    # Where training checkpoints live. "" = <state_dir>/checkpoints on the
    # per-host PVC (single-host default). Multi-host slices must point
    # this at storage every host can reach — a shared-filesystem mount or
    # a remote URI like "gs://bucket/prefix" (resolved by orbax via
    # etils.epath). Heartbeats always stay on the per-host PVC.
    checkpoint_dir: str = ""
    heartbeat_interval_s: float = 10.0
    expected_platform: str = "tpu"
    expected_chips: int = 0  # 0 = accept whatever is visible
    mesh: MeshSpec = MeshSpec()
    model: ModelSpec = ModelSpec()
    distributed: DistributedSpec = DistributedSpec()
    status_port: int = 8476
    status_bind: str = "0.0.0.0"
    # Bearer token gating the mutating status routes (POST /profile).
    # Delivered through the runtime-config Secret like the rest of this
    # TOML, so it never appears in chart values or pod env. "" leaves the
    # POST surface open — acceptable only when the status port is not
    # exposed through the LoadBalancer (the GET surface is read-only by
    # design and stays open either way).
    status_token: str = ""
    payload: str = "devicecheck"
    # Attention mode for the transformer-probe payload. "" = auto: the
    # ring when the mesh has a seq axis, naive otherwise. Explicit values
    # select a specific sequence-parallel strategy ("ring"/"ulysses") or
    # kernel ("flash"/"naive").
    payload_attention: str = ""
    # Decode backend for the "serve" payload. "" / "contiguous" = one
    # uniform-batch cache per request (simple, request-serial); "paged" =
    # the continuous-batching server over the paged KV cache
    # (models/serving.py): concurrent requests share one page pool and
    # one batched decode step.
    payload_serving: str = ""
    # Paged DECODE attention impl ([payload] paged_attention): "" =
    # "auto" (the Pallas block-table kernel in its measured win domain —
    # TPU, long caps, big pages — gather elsewhere); "gather" forces the
    # bit-stable padded-gather path (the kernel is numerically
    # equivalent within bf16 rounding, not bit-identical); "kernel"
    # forces the kernel. The deployment-level escape hatch for the
    # trace-time auto policy (models/kvcache.py _use_paged_kernel).
    payload_paged_attention: str = ""
    # Paged-backend pool sizing ([payload] serving_*): how many requests
    # decode concurrently (slots), the KV page granule (page_size), and
    # the total page pool. pages = 0 auto-sizes the pool so every slot
    # can hold a worst-case (max_seq) request — admission then only ever
    # waits on slots. Operators trading memory for queueing can set
    # pages lower; requests that can never fit are rejected up front
    # (models/serving.py admission rules).
    serving_slots: int = 4
    serving_page_size: int = 16
    serving_pages: int = 0
    # HBM byte budget for the page pool ([payload]
    # serving_hbm_budget_mb, 0 = off): instead of counting pages, the
    # operator states how many MB of accelerator memory the KV pool may
    # hold and the pool sizes itself to ``budget // page_bytes`` pages
    # (page_bytes covers K + V at the storage dtype, plus the fp32
    # scale slabs when serving_kv_dtype="int8"). Mutually exclusive
    # with an explicit serving_pages — two sources of truth for one
    # pool would silently shadow each other.
    serving_hbm_budget_mb: int = 0
    # Free-page watermarks (fractions of the pool, 0 = off): below
    # ``low`` unreserved headroom, non-top-priority admissions shed
    # with page-capacity terms; a preempted request resumes only while
    # headroom sits at or above ``high`` (hysteresis against
    # preempt/resume thrash). low <= high when both are set.
    serving_page_low_watermark: float = 0.0
    serving_page_high_watermark: float = 0.0
    # Bucketed compile cache for the paged backend ([payload]
    # serving_min_bucket, 0 = off): the device batch dim runs at the
    # smallest power-of-two bucket (from this floor, capped at
    # serving_slots) covering the occupied rows, so hundreds of slots
    # cost compile time only when traffic actually reaches them —
    # admissions within a bucket never retrace. Single-host paged
    # backend only (the slice op stream pins shapes at slots).
    serving_min_bucket: int = 0
    # KV-cache storage dtype for the paged backend: "" = the compute
    # dtype (bf16, bit-exact vs the contiguous backend); "int8" =
    # per-token-row symmetric quantization with fp32 scales — the
    # per-token KV HBM bill roughly HALVES, doubling servable
    # context/slots on the same pool budget. Lossy (error bounded by
    # one int8 step of each row's amax; decode can diverge at
    # near-ties), so it is an explicit opt-in, never a default.
    serving_kv_dtype: str = ""
    # Prefill granule for the paged backend: prompts land in chunks of
    # this many tokens, with the admission lock released between chunks
    # (in-flight decode proceeds) and one compiled program per chunk
    # length instead of per prompt length. 0 = whole-prompt prefill.
    serving_prefill_chunk: int = 64
    # Prefix sharing for the paged backend: completed prompts register
    # page-aligned prefixes; a later prompt with the same prefix reuses
    # the pinned K/V pages read-only and prefills only its suffix
    # (exact — K/V depend only on prompt tokens/positions). Pins are
    # evicted LRU under pool pressure.
    serving_prefix_cache: bool = True
    # Host-RAM byte budget for the prefix cache's residency tier
    # ([payload] serving_prefix_host_mb, 0 = off): evicted prefix
    # entries demote their verbatim page bytes (int8 scale slabs ride
    # along) to host RAM instead of dropping, and a later prompt
    # matching a host-resident prefix swaps it back into HBM at
    # admission. LRU within the budget; requires
    # serving_prefix_cache=true to have any effect.
    serving_prefix_host_mb: int = 0
    # Prefix-cache persistence: on shutdown the registry's pinned K/V
    # pages dump to ``<state_dir>/prefix-cache.npz`` and a rescheduled
    # serve pod re-pins them at boot — warm prefixes ride the state
    # volume like checkpoints do. Guarded by a fingerprint (checkpoint
    # step + model geometry): a cache from different params is ignored,
    # never half-trusted. Single-host paged backend only.
    serving_prefix_persist: bool = True
    # Device-side decode window cap for the paged backend: up to this
    # many greedy steps run in ONE dispatched scan (one host round trip
    # per window instead of per token — the knob that decouples decode
    # throughput from the relay RTT). Compiled programs stay the powers
    # of two {2..serving_window}. Tradeoff: a new request joins at the
    # next window boundary, so admission latency grows with the window
    # (SERVING.md's performance model). 1 = per-step dispatch. "auto"
    # hands the choice to the online controller (SERVING.md rung 26):
    # every harvested window feeds EWMAs of the measured host
    # turnaround R and per-step device time t, and the next window is
    # the smallest power of two with W*t >= R — the saturation point
    # of the rung-16 law, re-picked at every boundary inside
    # [serving_window_min, serving_window_max].
    serving_window: int | str = 64
    # Controller bounds for serving_window="auto" (ignored for a
    # static window): the smallest/largest window the controller may
    # pick. Floored to powers of two. The floor guards boundary
    # staleness (cancels and newcomers wait up to a window); the cap
    # bounds the compiled-program set and admission latency.
    serving_window_min: int = 1
    serving_window_max: int = 256
    # Overlapped window dispatch for the paged backend: "auto"/"on"
    # run the double-buffered decode loop (window N+1 is enqueued on a
    # device-resident carry before window N is harvested, so host
    # processing and the dispatch RTT hide under device execution —
    # steps/s approaches 1/max(RTT, window*t_step) instead of
    # 1/(RTT + window*t_step), SERVING.md rung 16); "off" keeps the
    # serial windowed loop. Token streams are bit-identical either
    # way. Price: one extra in-flight window of admission latency.
    serving_overlap: str = "auto"
    # Server-wide speculative decoding for the paged backend: draft
    # length K (0 = off), or "auto". Greedy traffic advances by batched
    # verify passes — K prompt-lookup drafts per slot, up to K+1 tokens
    # per slot per model forward, token-for-token identical to plain
    # greedy decode (drafts accept only where they equal the model's
    # own argmax). Pays where decode is weight-bandwidth-bound: see
    # SPEC_CROSSOVER_r04.json for the model-size crossover. GREEDY
    # requests' page budgets grow by K slack positions (sampled ones
    # can never accept a draft and reserve nothing extra). "auto"
    # probes the relay at serve boot (draft length 4) and turns
    # speculation off when windowed decode dominates its best case;
    # an explicit K keeps the operator's choice but logs a loud
    # warning under the same test (single-host serve only).
    serving_speculative: int | str = 0
    # Device-resident speculative windows (SERVING.md rung 20): W > 0
    # batches W draft+verify passes into ONE dispatched device program
    # — the n-gram drafting, accept/reject, KV commits, budget
    # freezing, and the pending-token chain all run in the scan, so
    # the host round trip amortizes over up to W*(1+K) tokens instead
    # of taxing every pass (the r05 paged-spec soft spot: 69.5 tok/s
    # vs 1803 plain paged, one RTT per pass). Requires
    # serving_speculative > 0 and the overlapped loop; an all-greedy
    # batch rides windows. Token streams are bit-identical either
    # way. 0 = off (legacy per-pass speculation).
    serving_spec_window: int = 0
    # Rung 23: keep mixed greedy+sampled batches on the windowed spec
    # path (sampled rows draw their next token on device, exact key
    # schedule preserved). false = a sampled co-tenant collapses the
    # batch to the legacy per-pass program (counted in
    # spec_window_fallbacks_total{cause="sampled"}). No effect unless
    # serving_spec_window > 0.
    serving_spec_sampled_window: bool = True
    # Retry-after hint (seconds) carried by poisoned-pool refusals and
    # /healthz while degraded — what a refused client is told to wait
    # before retrying. When the recovery supervisor is active and a
    # heal is in flight, the hint is overridden by the MEASURED
    # recovery time; this static value is the fallback (no supervisor,
    # or no recovery has completed yet).
    serving_retry_after_s: float = 30.0
    # In-process recovery for the paged serving pool (SERVING.md rung
    # 15): how many heal attempts (slice reformation + warm restart,
    # exponential backoff between them) the supervisor makes before
    # escalating to the terminal 503 / reschedule path. 0 disables the
    # supervisor entirely — every poisoning failure is immediately
    # terminal, the pre-rung-15 behavior.
    serving_recovery_attempts: int = 2
    # SLO-aware admission scheduling for the paged backend (SERVING.md
    # rung 17, models/scheduler.py). Policy across priority classes
    # (requests carry [payload-level] "priority": interactive|batch):
    # "strict" admits the best class first (FIFO within a class),
    # "weighted" shares by serving_sched_weights, "fifo" ignores
    # classes — the baseline the bench overload leg compares against.
    serving_sched_policy: str = "strict"
    # Weighted-policy shares, "class=weight,..." (ignored unless
    # serving_sched_policy = "weighted"). Higher weight = more
    # admissions per round; every class with weight > 0 keeps making
    # progress, so batch traffic is never starved outright.
    serving_sched_weights: str = "interactive=4,batch=1"
    # Overload shedding watermarks: reject a submit IMMEDIATELY (with
    # the measured per-class queue wait as the retry_after hint)
    # instead of letting it burn its timeout — when more than this many
    # requests are already parked (0 = no depth watermark) ...
    serving_sched_max_queue_depth: int = 0
    # ... or when the measured queue wait for the request's class
    # exceeds this many seconds (0 = no wait watermark).
    serving_sched_max_queue_wait_s: float = 0.0
    # Host-RAM budget (MB) for preemptive KV swap: when a higher-class
    # request cannot admit, the scheduler may swap a lower-class
    # victim's live pages to host RAM at a window boundary and resume
    # it later, bit-identically. 0 disables preemption (priority
    # ordering still applies at admission).
    serving_sched_swap_budget_mb: int = 0
    # Request-scoped tracing for the paged backend (SERVING.md rung 18,
    # runtime/tracing.py): "off" (default — zero recorder in the
    # process), "on" (every request traced), or a sample rate in
    # (0, 1] — the per-request decision is a deterministic hash of the
    # request ID, so all spans of one request share fate. Tracing on is
    # token-bit-identical to off; the flight recorder's tail ships in
    # the last-failure.json post-mortem and GET /trace exports
    # Chrome/Perfetto trace-event JSON.
    serving_trace: str | float = "off"
    # Lock-discipline assertions (SERVING.md rung 19): swap the
    # serving stack's work lock for an ownership-asserting DebugLock
    # and wrap every *_locked method to verify the calling thread
    # holds it — the runtime twin of `tools/locklint.py`. Debug/test
    # only: correct code behaves identically, violations raise
    # LockDisciplineError instead of racing.
    serving_debug_locks: bool = False
    # Boundary checkpointing for in-flight durability (SERVING.md rung
    # 22, runtime/journal.py): every N quiescent pipeline boundaries
    # the decode loop journals each live request's resumable state (KV
    # pages as verbatim swapout bytes, token log, sampler position,
    # original ticket) so poison/revive and slice reformation RESUME
    # in-flight requests bit-identically instead of failing them, and
    # clients reconnect exactly-once via X-Request-Id +
    # emitted_offset. 0 (default) = off: today's fail-and-retry poison
    # semantics, zero overhead. Cost per checkpoint is roughly
    # pages_live x swap bandwidth; 16 is a reasonable cadence when on.
    serving_checkpoint_every: int = 0
    # Page-conservation audit (rung 22's invariant 1): assert
    # free + live == pages_total at every quiescent boundary, raising
    # a typed PageAccountingError — loud, attributable leak detection
    # for debug/test runs (the chaos soak runs with it on).
    serving_debug_pages: bool = False
    # SLO engine (SERVING.md rung 25, runtime/slo.py): rolling
    # multi-window SLIs (TTFT/inter-token/queue-wait p99, goodput,
    # shed rate) computed from boundary-snapshot deltas of the
    # cumulative serving histograms, with fast/slow-window error-
    # budget burn-rate alerts. Off (default) = no engine in the
    # process; on exposes GET /slo and the serve_slo_* gauges. Tokens
    # are bit-identical either way (pinned by tests/test_slo.py).
    serving_slo: bool = False
    # Compliance target: the error budget is 1 - target; burn rate
    # over a window is bad_fraction / (1 - target).
    serving_slo_target: float = 0.99
    # Latency objectives (ms): the per-window over-objective fraction
    # of each is a bad-event fraction competing for the error budget.
    serving_slo_ttft_ms: float = 1000.0
    serving_slo_itl_ms: float = 250.0
    serving_slo_queue_ms: float = 1000.0
    # The multi-window burn-rate pair (seconds): the slow window
    # proves an incident is real, the fast window proves it is still
    # happening. Alert thresholds are the SRE-workbook constants
    # (14x fast / 6x slow), not knobs.
    serving_slo_fast_s: float = 60.0
    serving_slo_slow_s: float = 600.0
    # Burn-gated shedding: while the multi-window alert fires, the
    # scheduler sheds non-top classes at the door. Off (default)
    # keeps the rung-17 shed paths byte-for-byte; requires
    # serving_slo.
    serving_slo_shed: bool = False
    # Flight-recorder bundle (rung 25): on poison the workload layer
    # writes flight-bundle.json (one versioned document: metrics
    # snapshot, SLO/burn state, occupancy tail, journal summary, page
    # books, config fingerprint, trace tail) next to
    # last-failure.json, and GET /debug/bundle serves the same
    # document live. Off (default) = neither.
    serving_bundle: bool = False
    # Occupancy timeline ring depth (samples; 0 = off): HBM/page/
    # bucket/prefix-residency gauges sampled at quiescent boundaries,
    # exported as serve_occupancy_* gauges, Chrome counter tracks in
    # GET /trace, and the bundle's occupancy tail. 256 is a
    # reasonable depth when on.
    serving_occupancy_ring: int = 0
    # The "train" payload: resumable training over a token corpus on the
    # state volume. ``train_corpus`` is the corpus path (required for the
    # payload; rebased like every other in-pod path); steps count from 0
    # across ALL pod generations — a rescheduled pod resumes from the
    # latest checkpoint and the feeder continues at the exact batch.
    train_corpus: str = ""
    # Held-out corpus for the "eval" payload ([payload] eval_corpus).
    # "" falls back to the TRAINING corpus — eval then reports training
    # loss, not held-out loss, and says so loudly. Produce a split with
    # `kvedge-tpu corpus --holdout 0.1` (writes <out> and <out>.eval).
    eval_corpus: str = ""
    train_steps: int = 100
    train_batch: int = 8
    train_seq: int = 128
    train_checkpoint_every: int = 10

    @classmethod
    def parse(cls, text: str) -> "RuntimeConfig":
        """Parse and validate the TOML document."""
        try:
            doc = tomllib.loads(text)
        except tomllib.TOMLDecodeError as e:
            raise RuntimeConfigError(f"invalid TOML: {e}") from e
        return cls.from_mapping(doc)

    @classmethod
    def from_mapping(cls, doc: Mapping) -> "RuntimeConfig":
        runtime = dict(doc.get("runtime", {}))
        tpu = dict(doc.get("tpu", {}))
        mesh_doc = dict(doc.get("mesh", {}))
        model_doc = dict(doc.get("model", {}))
        dist_doc = dict(doc.get("distributed", {}))
        status = dict(doc.get("status", {}))
        payload_doc = dict(doc.get("payload", {}))

        axes_doc = mesh_doc.get("axes", dict(MeshSpec.axes))
        if not isinstance(axes_doc, Mapping):
            raise RuntimeConfigError("[mesh] axes must be a table")
        axes = [(str(axis), size) for axis, size in axes_doc.items()]

        try:
            cfg = cls(
                name=str(runtime.get("name", cls.name)),
                state_dir=str(runtime.get("state_dir", cls.state_dir)),
                checkpoint_dir=str(
                    runtime.get("checkpoint_dir", cls.checkpoint_dir)
                ),
                heartbeat_interval_s=float(
                    runtime.get("heartbeat_interval_s", cls.heartbeat_interval_s)
                ),
                expected_platform=str(tpu.get("platform", cls.expected_platform)),
                expected_chips=int(tpu.get("expected_chips", cls.expected_chips)),
                mesh=MeshSpec(axes=tuple(axes)),
                model=ModelSpec(
                    preset=str(model_doc.get("preset", ModelSpec.preset)),
                    vocab=int(model_doc.get("vocab", ModelSpec.vocab)),
                    d_model=int(model_doc.get("d_model", ModelSpec.d_model)),
                    n_heads=int(model_doc.get("n_heads", ModelSpec.n_heads)),
                    n_kv_heads=int(
                        model_doc.get("n_kv_heads", ModelSpec.n_kv_heads)
                    ),
                    n_layers=int(
                        model_doc.get("n_layers", ModelSpec.n_layers)
                    ),
                    d_ff=int(model_doc.get("d_ff", ModelSpec.d_ff)),
                    experts=int(model_doc.get("experts", ModelSpec.experts)),
                    expert_top_k=int(
                        model_doc.get("expert_top_k", ModelSpec.expert_top_k)
                    ),
                    expert_capacity_factor=float(
                        model_doc.get("expert_capacity_factor",
                                      ModelSpec.expert_capacity_factor)
                    ),
                    pipeline_schedule=str(
                        model_doc.get("pipeline_schedule",
                                      ModelSpec.pipeline_schedule)
                    ),
                ),
                distributed=DistributedSpec(
                    num_processes=int(
                        dist_doc.get("num_processes",
                                     DistributedSpec.num_processes)
                    ),
                    coordinator_address=str(
                        dist_doc.get("coordinator_address",
                                     DistributedSpec.coordinator_address)
                    ),
                    coordinator_port=int(
                        dist_doc.get("coordinator_port",
                                     DistributedSpec.coordinator_port)
                    ),
                    process_id=int(
                        dist_doc.get("process_id", DistributedSpec.process_id)
                    ),
                ),
                status_port=int(status.get("port", cls.status_port)),
                status_bind=str(status.get("bind", cls.status_bind)),
                status_token=str(status.get("token", cls.status_token)),
                payload=str(payload_doc.get("kind", cls.payload)),
                payload_attention=str(
                    payload_doc.get("attention", cls.payload_attention)
                ),
                payload_serving=str(
                    payload_doc.get("serving", cls.payload_serving)
                ),
                payload_paged_attention=str(
                    payload_doc.get("paged_attention",
                                    cls.payload_paged_attention)
                ),
                serving_slots=int(
                    payload_doc.get("serving_slots", cls.serving_slots)
                ),
                serving_page_size=int(
                    payload_doc.get("serving_page_size",
                                    cls.serving_page_size)
                ),
                serving_pages=int(
                    payload_doc.get("serving_pages", cls.serving_pages)
                ),
                serving_hbm_budget_mb=int(
                    payload_doc.get("serving_hbm_budget_mb",
                                    cls.serving_hbm_budget_mb)
                ),
                serving_page_low_watermark=float(
                    payload_doc.get("serving_page_low_watermark",
                                    cls.serving_page_low_watermark)
                ),
                serving_page_high_watermark=float(
                    payload_doc.get("serving_page_high_watermark",
                                    cls.serving_page_high_watermark)
                ),
                serving_min_bucket=int(
                    payload_doc.get("serving_min_bucket",
                                    cls.serving_min_bucket)
                ),
                serving_kv_dtype=str(
                    payload_doc.get("serving_kv_dtype",
                                    cls.serving_kv_dtype)
                ),
                serving_prefill_chunk=int(
                    payload_doc.get("serving_prefill_chunk",
                                    cls.serving_prefill_chunk)
                ),
                serving_prefix_cache=payload_doc.get(
                    "serving_prefix_cache", cls.serving_prefix_cache
                ),
                serving_prefix_host_mb=int(
                    payload_doc.get("serving_prefix_host_mb",
                                    cls.serving_prefix_host_mb)
                ),
                serving_prefix_persist=payload_doc.get(
                    "serving_prefix_persist", cls.serving_prefix_persist
                ),
                serving_window=_parse_window(
                    payload_doc.get("serving_window", cls.serving_window)
                ),
                serving_window_min=int(
                    payload_doc.get("serving_window_min",
                                    cls.serving_window_min)
                ),
                serving_window_max=int(
                    payload_doc.get("serving_window_max",
                                    cls.serving_window_max)
                ),
                serving_overlap=str(
                    payload_doc.get("serving_overlap",
                                    cls.serving_overlap)
                ),
                serving_spec_window=int(
                    payload_doc.get("serving_spec_window",
                                    cls.serving_spec_window)
                ),
                serving_spec_sampled_window=payload_doc.get(
                    "serving_spec_sampled_window",
                    cls.serving_spec_sampled_window
                ),
                serving_speculative=_parse_speculative(
                    payload_doc.get("serving_speculative",
                                    cls.serving_speculative)
                ),
                serving_retry_after_s=float(
                    payload_doc.get("serving_retry_after_s",
                                    cls.serving_retry_after_s)
                ),
                serving_recovery_attempts=int(
                    payload_doc.get("serving_recovery_attempts",
                                    cls.serving_recovery_attempts)
                ),
                serving_sched_policy=str(
                    payload_doc.get("serving_sched_policy",
                                    cls.serving_sched_policy)
                ),
                serving_sched_weights=str(
                    payload_doc.get("serving_sched_weights",
                                    cls.serving_sched_weights)
                ),
                serving_sched_max_queue_depth=int(
                    payload_doc.get("serving_sched_max_queue_depth",
                                    cls.serving_sched_max_queue_depth)
                ),
                serving_sched_max_queue_wait_s=float(
                    payload_doc.get("serving_sched_max_queue_wait_s",
                                    cls.serving_sched_max_queue_wait_s)
                ),
                serving_sched_swap_budget_mb=int(
                    payload_doc.get("serving_sched_swap_budget_mb",
                                    cls.serving_sched_swap_budget_mb)
                ),
                serving_trace=_parse_trace(
                    payload_doc.get("serving_trace", cls.serving_trace)
                ),
                serving_debug_locks=payload_doc.get(
                    "serving_debug_locks", cls.serving_debug_locks
                ),
                serving_checkpoint_every=int(
                    payload_doc.get("serving_checkpoint_every",
                                    cls.serving_checkpoint_every)
                ),
                serving_debug_pages=payload_doc.get(
                    "serving_debug_pages", cls.serving_debug_pages
                ),
                serving_slo=payload_doc.get(
                    "serving_slo", cls.serving_slo
                ),
                serving_slo_target=float(
                    payload_doc.get("serving_slo_target",
                                    cls.serving_slo_target)
                ),
                serving_slo_ttft_ms=float(
                    payload_doc.get("serving_slo_ttft_ms",
                                    cls.serving_slo_ttft_ms)
                ),
                serving_slo_itl_ms=float(
                    payload_doc.get("serving_slo_itl_ms",
                                    cls.serving_slo_itl_ms)
                ),
                serving_slo_queue_ms=float(
                    payload_doc.get("serving_slo_queue_ms",
                                    cls.serving_slo_queue_ms)
                ),
                serving_slo_fast_s=float(
                    payload_doc.get("serving_slo_fast_s",
                                    cls.serving_slo_fast_s)
                ),
                serving_slo_slow_s=float(
                    payload_doc.get("serving_slo_slow_s",
                                    cls.serving_slo_slow_s)
                ),
                serving_slo_shed=payload_doc.get(
                    "serving_slo_shed", cls.serving_slo_shed
                ),
                serving_bundle=payload_doc.get(
                    "serving_bundle", cls.serving_bundle
                ),
                serving_occupancy_ring=int(
                    payload_doc.get("serving_occupancy_ring",
                                    cls.serving_occupancy_ring)
                ),
                train_corpus=str(
                    payload_doc.get("corpus", cls.train_corpus)
                ),
                eval_corpus=str(
                    payload_doc.get("eval_corpus", cls.eval_corpus)
                ),
                train_steps=int(payload_doc.get("steps", cls.train_steps)),
                train_batch=int(payload_doc.get("batch", cls.train_batch)),
                train_seq=int(payload_doc.get("seq", cls.train_seq)),
                train_checkpoint_every=int(
                    payload_doc.get("checkpoint_every",
                                    cls.train_checkpoint_every)
                ),
            )
        except (TypeError, ValueError) as e:
            if isinstance(e, RuntimeConfigError):
                raise
            raise RuntimeConfigError(f"wrongly-typed config value: {e}") from e
        cfg.validate()
        return cfg

    def sched_weights_dict(self) -> dict[str, float]:
        """Parse ``serving_sched_weights`` ("class=weight,...") to a dict.

        Raises ``ValueError`` on malformed entries or non-positive
        weights; validate() surfaces that as a RuntimeConfigError and
        workload.py reuses the parsed dict when building the server.
        """
        out: dict[str, float] = {}
        for part in self.serving_sched_weights.split(","):
            part = part.strip()
            if not part:
                continue
            name, sep, val = part.partition("=")
            name = name.strip()
            if not sep or not name:
                raise ValueError(
                    f"expected 'class=weight', got {part!r}"
                )
            weight = float(val.strip())
            if weight <= 0:
                raise ValueError(
                    f"weight for {name!r} must be > 0, got {weight}"
                )
            out[name] = weight
        return out

    def validate(self) -> None:
        if not self.name:
            raise RuntimeConfigError("[runtime] name must be non-empty")
        if self.heartbeat_interval_s <= 0:
            raise RuntimeConfigError("[runtime] heartbeat_interval_s must be > 0")
        if self.expected_chips < 0:
            raise RuntimeConfigError("[tpu] expected_chips must be >= 0")
        # Port 0 = bind an ephemeral port (tests / local verification).
        if not (0 <= self.status_port < 65536):
            raise RuntimeConfigError("[status] port out of range")
        if self.payload not in _VALID_PAYLOADS:
            raise RuntimeConfigError(
                f"[payload] kind must be one of {_VALID_PAYLOADS}, "
                f"got {self.payload!r}"
            )
        if self.payload_attention not in _VALID_ATTENTION:
            raise RuntimeConfigError(
                f"[payload] attention must be one of {_VALID_ATTENTION}, "
                f"got {self.payload_attention!r}"
            )
        if self.payload_serving not in ("", "contiguous", "paged"):
            raise RuntimeConfigError(
                "[payload] serving must be '', 'contiguous', or 'paged', "
                f"got {self.payload_serving!r}"
            )
        if self.payload_paged_attention not in ("", "auto", "kernel",
                                                "gather"):
            raise RuntimeConfigError(
                "[payload] paged_attention must be '', 'auto', "
                f"'kernel', or 'gather', got "
                f"{self.payload_paged_attention!r}"
            )
        if self.serving_slots < 1:
            raise RuntimeConfigError("[payload] serving_slots must be >= 1")
        if self.serving_page_size < 1:
            raise RuntimeConfigError(
                "[payload] serving_page_size must be >= 1"
            )
        if self.serving_pages < 0:
            raise RuntimeConfigError(
                "[payload] serving_pages must be >= 0 (0 = auto-size so "
                "every slot fits a worst-case request)"
            )
        if self.serving_hbm_budget_mb < 0:
            raise RuntimeConfigError(
                "[payload] serving_hbm_budget_mb must be >= 0 "
                "(0 = off; size the pool by serving_pages instead)"
            )
        if self.serving_hbm_budget_mb > 0 and self.serving_pages > 0:
            raise RuntimeConfigError(
                "[payload] serving_hbm_budget_mb and serving_pages are "
                "mutually exclusive — two sources of truth for one "
                "page pool; set one and leave the other 0"
            )
        for name in ("serving_page_low_watermark",
                     "serving_page_high_watermark"):
            v = getattr(self, name)
            if not isinstance(v, (int, float)) or not 0.0 <= v < 1.0:
                raise RuntimeConfigError(
                    f"[payload] {name} must be a fraction in [0, 1) "
                    "(0 = off)"
                )
        if (self.serving_page_low_watermark
                and self.serving_page_high_watermark
                and self.serving_page_low_watermark
                > self.serving_page_high_watermark):
            raise RuntimeConfigError(
                "[payload] serving_page_low_watermark must be <= "
                "serving_page_high_watermark"
            )
        if self.serving_min_bucket < 0:
            raise RuntimeConfigError(
                "[payload] serving_min_bucket must be >= 0 (0 = off: "
                "the device batch dim is pinned to serving_slots)"
            )
        if self.serving_kv_dtype not in ("", "int8"):
            raise RuntimeConfigError(
                "[payload] serving_kv_dtype must be '' (compute dtype) "
                f"or 'int8', got {self.serving_kv_dtype!r}"
            )
        if self.serving_prefill_chunk < 0:
            raise RuntimeConfigError(
                "[payload] serving_prefill_chunk must be >= 0 "
                "(0 = whole-prompt prefill)"
            )
        if not isinstance(self.serving_prefix_cache, bool):
            raise RuntimeConfigError(
                "[payload] serving_prefix_cache must be a boolean"
            )
        if not isinstance(self.serving_prefix_persist, bool):
            raise RuntimeConfigError(
                "[payload] serving_prefix_persist must be a boolean"
            )
        if self.serving_prefix_host_mb < 0:
            raise RuntimeConfigError(
                "[payload] serving_prefix_host_mb must be >= 0 "
                "(0 disables the host residency tier)"
            )
        if self.serving_window != "auto" and not (
            isinstance(self.serving_window, int)
            and 1 <= self.serving_window <= 1024
        ):
            raise RuntimeConfigError(
                "[payload] serving_window must be in [1, 1024] "
                "(1 = per-step dispatch) or 'auto' (online "
                "controller, SERVING.md rung 26)"
            )
        if not 1 <= self.serving_window_min <= 1024:
            raise RuntimeConfigError(
                "[payload] serving_window_min must be in [1, 1024]"
            )
        if not 1 <= self.serving_window_max <= 1024:
            raise RuntimeConfigError(
                "[payload] serving_window_max must be in [1, 1024]"
            )
        if self.serving_window_min > self.serving_window_max:
            raise RuntimeConfigError(
                "[payload] serving_window_min must be <= "
                "serving_window_max (controller bounds)"
            )
        if self.serving_overlap not in ("auto", "on", "off"):
            raise RuntimeConfigError(
                "[payload] serving_overlap must be 'auto', 'on' or "
                "'off'"
            )
        if self.serving_speculative != "auto" and not (
            isinstance(self.serving_speculative, int)
            and 0 <= self.serving_speculative <= 16
        ):
            raise RuntimeConfigError(
                "[payload] serving_speculative (draft length) must be "
                "in [0, 16] (0 = off) or 'auto'"
            )
        if not 0 <= self.serving_spec_window <= 64:
            raise RuntimeConfigError(
                "[payload] serving_spec_window must be in [0, 64] "
                "(0 = one spec pass per dispatch)"
            )
        if self.serving_spec_window > 0 and self.serving_speculative == 0:
            raise RuntimeConfigError(
                "[payload] serving_spec_window > 0 needs speculative "
                "decoding (serving_speculative 'auto' or > 0)"
            )
        if not isinstance(self.serving_spec_sampled_window, bool):
            raise RuntimeConfigError(
                "[payload] serving_spec_sampled_window must be a boolean"
            )
        if self.serving_retry_after_s <= 0:
            raise RuntimeConfigError(
                "[payload] serving_retry_after_s must be > 0 "
                "(seconds a refused client should wait before retrying)"
            )
        if self.serving_recovery_attempts < 0:
            raise RuntimeConfigError(
                "[payload] serving_recovery_attempts must be >= 0 "
                "(0 = no in-process recovery; degrade is terminal)"
            )
        if self.serving_sched_policy not in ("fifo", "strict",
                                             "weighted"):
            raise RuntimeConfigError(
                "[payload] serving_sched_policy must be 'fifo', "
                "'strict' or 'weighted'"
            )
        try:
            self.sched_weights_dict()
        except ValueError as e:
            raise RuntimeConfigError(
                f"[payload] serving_sched_weights: {e}"
            ) from None
        if self.serving_sched_max_queue_depth < 0:
            raise RuntimeConfigError(
                "[payload] serving_sched_max_queue_depth must be >= 0 "
                "(0 = no depth watermark)"
            )
        if self.serving_sched_max_queue_wait_s < 0:
            raise RuntimeConfigError(
                "[payload] serving_sched_max_queue_wait_s must be >= 0 "
                "(0 = no wait watermark)"
            )
        if self.serving_sched_swap_budget_mb < 0:
            raise RuntimeConfigError(
                "[payload] serving_sched_swap_budget_mb must be >= 0 "
                "(0 = preemptive swap off)"
            )
        if isinstance(self.serving_trace, str):
            if self.serving_trace not in ("off", "on"):
                raise RuntimeConfigError(
                    "[payload] serving_trace must be 'off', 'on' or a "
                    f"sample rate in (0, 1], got {self.serving_trace!r}"
                )
        elif not 0.0 < self.serving_trace <= 1.0:
            raise RuntimeConfigError(
                "[payload] serving_trace sample rate must be in "
                f"(0, 1], got {self.serving_trace!r}"
            )
        if not isinstance(self.serving_debug_locks, bool):
            raise RuntimeConfigError(
                "[payload] serving_debug_locks must be a boolean"
            )
        if self.serving_checkpoint_every < 0:
            raise RuntimeConfigError(
                "[payload] serving_checkpoint_every must be >= 0 "
                "(0 = off: no in-flight checkpointing)"
            )
        if not isinstance(self.serving_debug_pages, bool):
            raise RuntimeConfigError(
                "[payload] serving_debug_pages must be a boolean"
            )
        for knob in ("serving_slo", "serving_slo_shed",
                     "serving_bundle"):
            if not isinstance(getattr(self, knob), bool):
                raise RuntimeConfigError(
                    f"[payload] {knob} must be a boolean"
                )
        if not 0.0 < self.serving_slo_target < 1.0:
            raise RuntimeConfigError(
                "[payload] serving_slo_target must be in (0, 1) "
                f"(got {self.serving_slo_target!r}; the error budget "
                "is 1 - target)"
            )
        for knob in ("serving_slo_ttft_ms", "serving_slo_itl_ms",
                     "serving_slo_queue_ms"):
            if getattr(self, knob) <= 0.0:
                raise RuntimeConfigError(
                    f"[payload] {knob} must be > 0 (an objective in "
                    "milliseconds)"
                )
        if not (0.0 < self.serving_slo_fast_s
                <= self.serving_slo_slow_s):
            raise RuntimeConfigError(
                "[payload] serving_slo windows must satisfy "
                "0 < serving_slo_fast_s <= serving_slo_slow_s "
                f"(got fast={self.serving_slo_fast_s!r}, "
                f"slow={self.serving_slo_slow_s!r})"
            )
        if self.serving_slo_shed and not self.serving_slo:
            raise RuntimeConfigError(
                "[payload] serving_slo_shed requires serving_slo = "
                "true (the burn-rate input comes from the SLO engine)"
            )
        if self.serving_occupancy_ring < 0:
            raise RuntimeConfigError(
                "[payload] serving_occupancy_ring must be >= 0 "
                "(0 = off; otherwise the ring depth in samples)"
            )
        if self.payload == "train" and not self.train_corpus:
            raise RuntimeConfigError(
                "[payload] kind = 'train' requires corpus = '<path>' "
                "(a KVFEED01 token file, typically on the state volume)"
            )
        if self.payload == "eval" and not (self.train_corpus
                                           or self.eval_corpus):
            raise RuntimeConfigError(
                "[payload] kind = 'eval' requires corpus = '<path>' or "
                "eval_corpus = '<path>' (a KVFEED01 token file; "
                "eval_corpus is the held-out split)"
            )
        for field_name in ("train_steps", "train_batch", "train_seq",
                           "train_checkpoint_every"):
            if getattr(self, field_name) <= 0:
                toml_key = field_name.removeprefix("train_")
                raise RuntimeConfigError(
                    f"[payload] {toml_key} must be positive"
                )
        self.mesh.validate()
        self.model.validate()
        self.distributed.validate()

    def to_toml(self) -> str:
        """Serialize back to TOML (the form written by ``config apply``).

        String values are emitted as TOML basic strings via JSON escaping
        (valid TOML: ``\"``, ``\\``, ``\\uXXXX``), so quotes/backslashes in
        names or paths survive the apply -> re-parse round trip.
        """
        s = _toml_str
        axes = ", ".join(f"{s(name)} = {size}" for name, size in self.mesh.axes)
        return (
            "[runtime]\n"
            f"name = {s(self.name)}\n"
            f"state_dir = {s(self.state_dir)}\n"
            f"checkpoint_dir = {s(self.checkpoint_dir)}\n"
            f"heartbeat_interval_s = {self.heartbeat_interval_s}\n"
            "\n[tpu]\n"
            f"platform = {s(self.expected_platform)}\n"
            f"expected_chips = {self.expected_chips}\n"
            "\n[mesh]\n"
            f"axes = {{ {axes} }}\n"
            "\n[model]\n"
            f"preset = {s(self.model.preset)}\n"
            f"vocab = {self.model.vocab}\n"
            f"d_model = {self.model.d_model}\n"
            f"n_heads = {self.model.n_heads}\n"
            f"n_kv_heads = {self.model.n_kv_heads}\n"
            f"n_layers = {self.model.n_layers}\n"
            f"d_ff = {self.model.d_ff}\n"
            f"experts = {self.model.experts}\n"
            f"expert_top_k = {self.model.expert_top_k}\n"
            f"expert_capacity_factor = {self.model.expert_capacity_factor}\n"
            f"pipeline_schedule = {s(self.model.pipeline_schedule)}\n"
            "\n[distributed]\n"
            f"num_processes = {self.distributed.num_processes}\n"
            f"coordinator_address = {s(self.distributed.coordinator_address)}\n"
            f"coordinator_port = {self.distributed.coordinator_port}\n"
            f"process_id = {self.distributed.process_id}\n"
            "\n[status]\n"
            f"port = {self.status_port}\n"
            f"bind = {s(self.status_bind)}\n"
            f"token = {s(self.status_token)}\n"
            "\n[payload]\n"
            f"kind = {s(self.payload)}\n"
            f"attention = {s(self.payload_attention)}\n"
            f"serving = {s(self.payload_serving)}\n"
            f"paged_attention = {s(self.payload_paged_attention)}\n"
            f"serving_slots = {self.serving_slots}\n"
            f"serving_page_size = {self.serving_page_size}\n"
            f"serving_pages = {self.serving_pages}\n"
            f"serving_hbm_budget_mb = {self.serving_hbm_budget_mb}\n"
            "serving_page_low_watermark = "
            f"{self.serving_page_low_watermark}\n"
            "serving_page_high_watermark = "
            f"{self.serving_page_high_watermark}\n"
            f"serving_min_bucket = {self.serving_min_bucket}\n"
            f"serving_kv_dtype = {s(self.serving_kv_dtype)}\n"
            f"serving_prefill_chunk = {self.serving_prefill_chunk}\n"
            "serving_prefix_cache = "
            f"{'true' if self.serving_prefix_cache else 'false'}\n"
            f"serving_prefix_host_mb = {self.serving_prefix_host_mb}\n"
            "serving_prefix_persist = "
            f"{'true' if self.serving_prefix_persist else 'false'}\n"
            "serving_window = "
            f"{s(self.serving_window) if isinstance(self.serving_window, str) else self.serving_window}\n"
            f"serving_window_min = {self.serving_window_min}\n"
            f"serving_window_max = {self.serving_window_max}\n"
            f"serving_overlap = {s(self.serving_overlap)}\n"
            "serving_speculative = "
            f"{s(self.serving_speculative) if isinstance(self.serving_speculative, str) else self.serving_speculative}\n"
            f"serving_spec_window = {self.serving_spec_window}\n"
            "serving_spec_sampled_window = "
            f"{'true' if self.serving_spec_sampled_window else 'false'}\n"
            f"serving_retry_after_s = {self.serving_retry_after_s}\n"
            f"serving_recovery_attempts = {self.serving_recovery_attempts}\n"
            f"serving_sched_policy = {s(self.serving_sched_policy)}\n"
            f"serving_sched_weights = {s(self.serving_sched_weights)}\n"
            "serving_sched_max_queue_depth = "
            f"{self.serving_sched_max_queue_depth}\n"
            "serving_sched_max_queue_wait_s = "
            f"{self.serving_sched_max_queue_wait_s}\n"
            "serving_sched_swap_budget_mb = "
            f"{self.serving_sched_swap_budget_mb}\n"
            "serving_trace = "
            f"{s(self.serving_trace) if isinstance(self.serving_trace, str) else self.serving_trace}\n"
            "serving_debug_locks = "
            f"{'true' if self.serving_debug_locks else 'false'}\n"
            "serving_checkpoint_every = "
            f"{self.serving_checkpoint_every}\n"
            "serving_debug_pages = "
            f"{'true' if self.serving_debug_pages else 'false'}\n"
            f"serving_slo = {'true' if self.serving_slo else 'false'}\n"
            f"serving_slo_target = {self.serving_slo_target}\n"
            f"serving_slo_ttft_ms = {self.serving_slo_ttft_ms}\n"
            f"serving_slo_itl_ms = {self.serving_slo_itl_ms}\n"
            f"serving_slo_queue_ms = {self.serving_slo_queue_ms}\n"
            f"serving_slo_fast_s = {self.serving_slo_fast_s}\n"
            f"serving_slo_slow_s = {self.serving_slo_slow_s}\n"
            "serving_slo_shed = "
            f"{'true' if self.serving_slo_shed else 'false'}\n"
            "serving_bundle = "
            f"{'true' if self.serving_bundle else 'false'}\n"
            "serving_occupancy_ring = "
            f"{self.serving_occupancy_ring}\n"
            f"corpus = {s(self.train_corpus)}\n"
            f"eval_corpus = {s(self.eval_corpus)}\n"
            f"steps = {self.train_steps}\n"
            f"batch = {self.train_batch}\n"
            f"seq = {self.train_seq}\n"
            f"checkpoint_every = {self.train_checkpoint_every}\n"
        )

    def apply(self, config_path: str = DEFAULT_CONFIG_PATH) -> str:
        """Materialize the validated config — ``iotedge config apply`` analog.

        Writes the canonical TOML to ``config_path`` and creates the state
        directory, so a subsequent runtime boot finds both in place
        (reference: ``_helper.tpl:73-74``).
        """
        self.validate()
        os.makedirs(os.path.dirname(config_path), exist_ok=True)
        os.makedirs(self.state_dir, exist_ok=True)
        tmp = config_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            fh.write(self.to_toml())
        os.replace(tmp, config_path)
        return config_path
