"""Parse the ``#kvedge-boot-config`` document (cloud-init user-data analogue).

The document is rendered by :mod:`kvedge_tpu.render.bootconfig`, shipped as a
Secret, and mounted into the runtime container; this module is the consumer
side. Mirrors the cloud-init contract the reference relies on
(``_helper.tpl:31-75``): ``hostname``, ``ssh_authorized_keys``, ``bootcmd``
(runs first, pre-runtime), ``runcmd`` (runs after, in order).
"""

from __future__ import annotations

import dataclasses
import shlex

import yaml

from kvedge_tpu.render.bootconfig import HEADER


class BootDocError(ValueError):
    """Raised when the boot-config document is malformed."""


@dataclasses.dataclass(frozen=True)
class BootDocument:
    hostname: str
    ssh_authorized_keys: tuple[str, ...]
    bootcmd: tuple[tuple[str, ...], ...]
    runcmd: tuple[tuple[str, ...], ...]


def _parse_commands(doc: dict, key: str) -> tuple[tuple[str, ...], ...]:
    raw = doc.get(key, [])
    if not isinstance(raw, list):
        raise BootDocError(f"{key} must be a list of commands")
    commands = []
    for item in raw:
        if isinstance(item, str):
            argv = tuple(shlex.split(item))
        elif isinstance(item, list) and all(isinstance(a, str) for a in item):
            argv = tuple(item)
        else:
            raise BootDocError(f"{key} entries must be strings or string lists")
        if not argv:
            raise BootDocError(f"{key} contains an empty command")
        commands.append(argv)
    return tuple(commands)


def parse_boot_document(text: str) -> BootDocument:
    """Parse and validate a boot-config document.

    The header line is required — like cloud-init's ``#cloud-config``
    sentinel, it guards against mounting the wrong Secret into the
    boot-config slot.
    """
    first_line = text.split("\n", 1)[0].strip()
    if first_line != HEADER:
        raise BootDocError(
            f"not a boot-config document (first line {first_line!r}, "
            f"expected {HEADER!r})"
        )
    try:
        doc = yaml.safe_load(text)
    except yaml.YAMLError as e:
        raise BootDocError(f"invalid YAML: {e}") from e
    if not isinstance(doc, dict):
        raise BootDocError("boot-config document must be a mapping")

    keys = doc.get("ssh_authorized_keys", [])
    if not isinstance(keys, list) or not all(isinstance(k, str) for k in keys):
        raise BootDocError("ssh_authorized_keys must be a list of strings")

    return BootDocument(
        hostname=str(doc.get("hostname", "")),
        # Empty entries (no key injected) are dropped, never authorized.
        ssh_authorized_keys=tuple(k for k in keys if k.strip()),
        bootcmd=_parse_commands(doc, "bootcmd"),
        runcmd=_parse_commands(doc, "runcmd"),
    )
