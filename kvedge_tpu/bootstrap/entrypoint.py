"""Container entrypoint: execute the boot-config document — PID 1's cloud-init.

Boot sequence (mirroring cloud-init's phase ordering, which the reference
depends on — ``_helper.tpl:67`` notes ``packages:`` was avoided precisely
because only ``bootcmd``/``runcmd`` guarantee order):

1. read + validate the boot-config document (header sentinel);
2. authorize SSH keys and start sshd if the image carries one;
3. run every ``bootcmd`` in order (config-volume discovery);
4. run every ``runcmd`` in order (config apply, then runtime boot —
   the final command typically never returns in a real pod).

Any step failing exits non-zero so Kubernetes restarts the pod — the
analogue of the VM-level restart the reference gets from
``running: true`` (``aziot-edge-vm.yaml:9``).
"""

from __future__ import annotations

import argparse
import os
import shutil
import subprocess
import sys

from kvedge_tpu.bootstrap.bootdoc import BootDocError, parse_boot_document
from kvedge_tpu.bootstrap.commands import CommandError, rebase, run_command

SSH_DIR = "/home/kvedge/.ssh"


def _log(msg: str) -> None:
    print(f"[kvedge-bootstrap] {msg}", flush=True)


def authorize_ssh_keys(keys: tuple[str, ...], root: str) -> str | None:
    """Write authorized_keys (cloud-init ``ssh_authorized_keys`` analogue)."""
    if not keys:
        return None
    ssh_dir = rebase(SSH_DIR, root)
    os.makedirs(ssh_dir, mode=0o700, exist_ok=True)
    path = os.path.join(ssh_dir, "authorized_keys")
    with open(path, "w", encoding="utf-8") as fh:
        fh.write("".join(f"{key}\n" for key in keys))
    os.chmod(path, 0o600)
    return path


def start_sshd_if_present(root: str, have_keys: bool) -> bool:
    """Start sshd when the image ships one AND a public key was injected.

    External SSH is an optional capability gated by a chart value (the
    Service may not even exist, ``aziot-edge-vm-service.yaml:1``), so a
    missing sshd must not fail the boot — and without an authorized key
    there is nothing to serve, so no daemon is started at all.

    The runtime image ships without SSH host keys (shared baked-in host
    keys would let anyone who pulls the public image impersonate any
    deployment), so they are generated here on first start.
    """
    if root not in ("", "/"):
        return False  # never start a real daemon from a test root
    if not have_keys:
        return False
    sshd = shutil.which("sshd") or (
        "/usr/sbin/sshd" if os.path.exists("/usr/sbin/sshd") else None
    )
    if not sshd:
        _log("no sshd in image; skipping SSH access setup")
        return False
    os.makedirs("/run/sshd", exist_ok=True)  # privsep dir, absent in containers
    if not any(
        name.startswith("ssh_host_") for name in os.listdir("/etc/ssh")
    ):
        subprocess.run(["ssh-keygen", "-A"], check=False)
        _log("generated per-container SSH host keys")
    subprocess.Popen([sshd, "-D", "-e"])
    _log(f"started {sshd}")
    return True


def run_boot_sequence(boot_config_path: str, root: str = "/") -> None:
    with open(boot_config_path, "r", encoding="utf-8") as fh:
        document = parse_boot_document(fh.read())
    _log(f"boot document ok (hostname {document.hostname!r})")

    key_path = authorize_ssh_keys(document.ssh_authorized_keys, root)
    if key_path:
        _log(f"authorized {len(document.ssh_authorized_keys)} ssh key(s)")
    start_sshd_if_present(root, have_keys=bool(document.ssh_authorized_keys))

    for phase, commands in (("bootcmd", document.bootcmd),
                            ("runcmd", document.runcmd)):
        for argv in commands:
            _log(f"{phase}: {' '.join(argv)}")
            run_command(argv, root=root)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="kvedge-entrypoint",
        description="Execute a #kvedge-boot-config document.",
    )
    parser.add_argument("--boot-config", required=True,
                        help="path to the mounted boot-config document")
    parser.add_argument("--root", default="/",
                        help="filesystem root to resolve in-pod paths against "
                             "(tests/local verification)")
    args = parser.parse_args(argv)
    forced = os.environ.get("KVEDGE_FORCE_VIRTUAL_DEVICES", "")
    if forced:
        # Test/local-verification knob: run the whole boot against an
        # n-device virtual CPU mesh. Must happen here — before any boot
        # command can touch a JAX backend — because environments that
        # preload jax pointed at real hardware ignore inherited env vars
        # alone (see kvedge_tpu/testing/jaxenv.py).
        from kvedge_tpu.testing.jaxenv import force_virtual_cpu_devices

        force_virtual_cpu_devices(int(forced))
    try:
        run_boot_sequence(args.boot_config, root=args.root)
    except (BootDocError, CommandError, OSError) as e:
        _log(f"boot failed: {e}")
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
