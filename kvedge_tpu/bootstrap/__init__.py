"""Boot-time bootstrap: the cloud-init analogue.

The reference's guest bootstrap (SURVEY.md §1 L4) is cloud-init executing the
rendered user-data: mount the serial-tagged config disk (``_helper.tpl:61-64``),
install the runtime, copy the injected config into place, and apply it
(``_helper.tpl:68-74``). kvedge-tpu's bootstrap is the container entrypoint
executing the rendered ``#kvedge-boot-config`` document the same way:

* :mod:`kvedge_tpu.bootstrap.bootdoc` — parse the boot-config document;
* :mod:`kvedge_tpu.bootstrap.mount` — locate the config volume by serial
  (the ``lsblk | grep <serial>`` analogue);
* :mod:`kvedge_tpu.bootstrap.commands` — the in-process ``kvedge-bootstrap``
  / ``kvedge-runtime`` command handlers bootcmd/runcmd dispatch to;
* :mod:`kvedge_tpu.bootstrap.entrypoint` — PID-1 sequencing: parse document,
  authorize SSH keys, run ``bootcmd`` then ``runcmd`` in order (the ordering
  guarantee the reference calls out at ``_helper.tpl:67``).
"""
