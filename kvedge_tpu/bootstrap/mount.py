"""Identity-addressed config-volume discovery — the serial-disk analogue.

Reference mechanism (``_helper.tpl:61-64``): the config Secret surfaces in
the VM as a disk tagged with serial ``D23YZ9W6WA5DJ487``; cloud-init's
``bootcmd`` greps ``lsblk`` for that serial and mounts the match at
``/mnt/app-secret``, so the guest never hardcodes a device path.

Pod analogue: the chart mounts the config Secret under
``<search_root>/<serial>`` (see ``render/manifests.py``); :func:`locate`
scans the search root for the serial-named volume, verifies it actually
carries config payload (a ``userdata`` file, the Secret's single key), and
publishes it at a stable path (``/mnt/app-secret``) via symlink.
"""

from __future__ import annotations

import os


class MountError(RuntimeError):
    """Raised when the serial-tagged config volume cannot be located."""


def locate(serial: str, search_root: str, link: str) -> str:
    """Find the serial-tagged volume and link it at a stable path.

    Returns the resolved volume directory. Idempotent: re-running replaces
    the link (cloud-init's bootcmd similarly re-runs on every boot).
    """
    if not serial:
        raise MountError("empty serial")
    candidate = os.path.join(search_root, serial)
    if not os.path.isdir(candidate):
        try:
            visible = sorted(os.listdir(search_root))
        except OSError:
            visible = []
        raise MountError(
            f"no volume with serial {serial!r} under {search_root} "
            f"(visible: {visible})"
        )
    userdata = os.path.join(candidate, "userdata")
    if not os.path.isfile(userdata):
        raise MountError(
            f"volume {candidate} has no 'userdata' payload — wrong Secret "
            "mounted into the config slot?"
        )
    os.makedirs(os.path.dirname(link) or "/", exist_ok=True)
    tmp = f"{link}.tmp"
    if os.path.islink(tmp) or os.path.exists(tmp):
        os.remove(tmp)
    # The target must be absolute: a relative symlink target resolves
    # against the LINK's directory, not the invoker's cwd, so a relative
    # search root (e.g. `entrypoint --root .`) would produce a dangling
    # link like mnt/app-secret -> mnt/disks/<serial>.
    os.symlink(os.path.abspath(candidate), tmp)
    os.replace(tmp, link)
    return os.path.abspath(candidate)
