"""In-process handlers for the boot-document commands.

``bootcmd``/``runcmd`` entries name two virtual binaries:

* ``kvedge-bootstrap locate|apply`` — volume discovery and config apply
  (the ``mount`` + ``cp`` + ``iotedge config apply`` steps of
  ``_helper.tpl:61-74``);
* ``kvedge-runtime boot`` — hand off to the JAX runtime
  (:mod:`kvedge_tpu.runtime.boot`).

Both are dispatched in-process (testable, no shell); any other argv is
executed as a subprocess so operators can extend the boot sequence from the
Secret without changing the image — the property that makes the reference's
cloud-init-in-a-Secret design useful.

All absolute paths are resolved against a ``root`` prefix (``/`` in a real
pod), so the whole boot sequence can run against a scratch directory in
tests and local verification.
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import subprocess

from kvedge_tpu.bootstrap import mount
from kvedge_tpu.config.runtime_config import RuntimeConfig, RuntimeConfigError


def rebase(path: str, root: str) -> str:
    """Resolve an absolute in-pod path against a test/verification root."""
    if root in ("", "/"):
        return path
    return os.path.join(root, path.lstrip("/"))


class CommandError(RuntimeError):
    """Raised when a boot command fails."""


def cmd_locate(argv: list[str], root: str) -> None:
    parser = argparse.ArgumentParser(prog="kvedge-bootstrap locate")
    parser.add_argument("--serial", required=True)
    parser.add_argument("--search-root", required=True)
    parser.add_argument("--link", required=True)
    args = parser.parse_args(argv)
    try:
        mount.locate(
            serial=args.serial,
            search_root=rebase(args.search_root, root),
            link=rebase(args.link, root),
        )
    except mount.MountError as e:
        raise CommandError(str(e)) from e


def cmd_apply(argv: list[str], root: str) -> None:
    parser = argparse.ArgumentParser(prog="kvedge-bootstrap apply")
    parser.add_argument("--source", required=True)
    parser.add_argument("--target", required=True)
    args = parser.parse_args(argv)
    source = rebase(args.source, root)
    try:
        with open(source, "r", encoding="utf-8") as fh:
            text = fh.read()
    except OSError as e:
        raise CommandError(f"cannot read injected config {source}: {e}") from e
    try:
        cfg = RuntimeConfig.parse(text)
    except RuntimeConfigError as e:
        raise CommandError(f"injected config is invalid: {e}") from e
    # Rebase the in-pod paths too so `apply` stays inside the test root.
    cfg = dataclasses.replace(
        cfg,
        state_dir=rebase(cfg.state_dir, root),
        train_corpus=(
            rebase(cfg.train_corpus, root) if cfg.train_corpus else ""
        ),
        eval_corpus=(
            rebase(cfg.eval_corpus, root) if cfg.eval_corpus else ""
        ),
    )
    cfg.apply(config_path=rebase(args.target, root))


def cmd_runtime_boot(argv: list[str], root: str) -> None:
    from kvedge_tpu.runtime import boot  # deferred: pulls in jax

    parser = argparse.ArgumentParser(prog="kvedge-runtime boot")
    parser.add_argument("--config", required=True)
    parser.add_argument("--once", action="store_true")
    args = parser.parse_args(argv)
    boot.boot(config_path=rebase(args.config, root), once=args.once, root=root)


_BOOTSTRAP_COMMANDS = {"locate": cmd_locate, "apply": cmd_apply}
_RUNTIME_COMMANDS = {"boot": cmd_runtime_boot}


def run_command(argv: tuple[str, ...], root: str = "/") -> None:
    """Dispatch one boot-document command."""
    head, rest = argv[0], list(argv[1:])
    if head == "kvedge-bootstrap":
        table = _BOOTSTRAP_COMMANDS
    elif head == "kvedge-runtime":
        table = _RUNTIME_COMMANDS
    else:
        # Operator-extended command: execute as a subprocess.
        result = subprocess.run(argv)
        if result.returncode != 0:
            raise CommandError(
                f"command {argv!r} exited with {result.returncode}"
            )
        return
    if not rest or rest[0] not in table:
        raise CommandError(
            f"{head} expects a subcommand in {sorted(table)}, got {rest[:1]}"
        )
    table[rest[0]](rest[1:], root=root)
