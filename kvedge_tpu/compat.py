"""Version shims for jax APIs with moved/renamed surfaces.

The repo targets the current jax API; these wrappers keep it importable
and correct on older releases baked into some containers, where the same
operation exists under a different name. Centralized so every call site
states the MODERN spelling and the translation lives in exactly one
place.
"""

from __future__ import annotations

import jax

try:
    _shard_map = jax.shard_map  # public since jax 0.6
    _MODERN = True
except AttributeError:  # older jax keeps it in experimental
    from jax.experimental.shard_map import shard_map as _shard_map

    _MODERN = False


def shard_map(f, *, mesh, in_specs, out_specs, axis_names=None,
              check_vma=None):
    """``jax.shard_map`` with the modern keyword surface on any jax.

    Translations for the experimental-era API:

    * ``axis_names`` (the axes that go MANUAL) becomes ``auto`` (its
      complement — the axes that stay automatic);
    * ``check_vma`` becomes ``check_rep`` (same meaning, old name).
    """
    kw = {}
    if _MODERN:
        if axis_names is not None:
            kw["axis_names"] = axis_names
        if check_vma is not None:
            kw["check_vma"] = check_vma
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, **kw)
    if axis_names is not None:
        kw["auto"] = frozenset(mesh.axis_names) - frozenset(axis_names)
    # The legacy replication checker has false positives the modern
    # check_vma pass fixed (e.g. "branches of cond produced mismatched
    # replication types" on ring attention's rotation cond), so it stays
    # off on legacy jax.
    kw["check_rep"] = False
    return _shard_map(f, mesh, in_specs, out_specs, **kw)
