"""Shared utilities."""

from kvedge_tpu.utils.gojson import go_json

__all__ = ["go_json"]
