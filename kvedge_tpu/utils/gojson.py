"""JSON encoding matching Go's ``encoding/json`` (what Helm's ``toJson`` uses).

Go's ``json.Marshal`` HTML-escapes ``&``, ``<`` and ``>`` to ``\\u0026``,
``\\u003c``, ``\\u003e``. Anything we render through a template construct
that real Helm would render with ``toJson`` (the boot-config SSH key) must
use *this* encoder, or the shipped chart's output would silently differ from
the Python renderer's for keys containing those characters.
"""

from __future__ import annotations

import json

_GO_ESCAPES = {"&": "\\u0026", "<": "\\u003c", ">": "\\u003e"}


def go_json(value) -> str:
    text = json.dumps(value, ensure_ascii=True)
    for char, escape in _GO_ESCAPES.items():
        text = text.replace(char, escape)
    return text
