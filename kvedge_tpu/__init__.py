"""kvedge-tpu: a TPU-native deployment accelerator for JAX runtimes on Kubernetes.

This package is the idiomatic JAX/TPU re-design of the capabilities of the
reference accelerator ``levi106/kvedge`` (a Helm chart that boots the Azure
IoT Edge runtime inside a KubeVirt VM on Kubernetes; see SURVEY.md for the
full structural analysis).  The reference's five capabilities map here as:

1. Declarative isolated-runtime provisioning
   (reference: KubeVirt ``VirtualMachine``,
   ``deployment/helm/templates/aziot-edge-vm.yaml``)
   -> a single-replica Recreate Deployment pinned to TPU-bearing nodes
   (:mod:`kvedge_tpu.render`).
2. Boot-time config injection
   (reference: Secret -> serial-tagged disk -> cloud-init copy ->
   ``iotedge config apply``, ``_helper.tpl:61-74``)
   -> Secret volume -> marker-file mount discovery -> ``kvedge config apply``
   (:mod:`kvedge_tpu.bootstrap`).
3. Persistent state across rescheduling
   (reference: CDI DataVolume / PVC, ``README.md:88``)
   -> PVC-backed state directory written through by the runtime
   (:mod:`kvedge_tpu.runtime`).
4. External access
   (reference: conditional LoadBalancer SSH service,
   ``aziot-edge-vm-service.yaml``)
   -> conditional LoadBalancer exposing SSH and a status endpoint.
5. Prebuilt boot image
   (reference: ``deployment/Dockerfile`` containerDisk)
   -> a runtime OCI image with ``jax[tpu]`` preinstalled
   (``deployment/Dockerfile``).

On top of the provisioning layer this package carries the minimum end-to-end
TPU payload (SURVEY.md §7 step 4): a device-visibility check, a sharded
matmul probe, and a compact flagship transformer whose training step shards
over a ``jax.sharding.Mesh``.
"""

from kvedge_tpu.version import __version__

__all__ = ["__version__"]
