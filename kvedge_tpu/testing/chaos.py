"""Seeded multi-fault chaos campaigns for the durable serving stack.

``testing/servingfaults.py`` proves ONE fault terminates cleanly; this
module is the soak that rung 22 (SERVING.md — boundary checkpoints +
resume-after-revive) is accepted against: a campaign drives several
ROUNDS of seeded traffic into one long-lived server wearing a
:class:`~kvedge_tpu.testing.servingfaults.FaultyCache`, arms a fresh
seeded :class:`~kvedge_tpu.testing.servingfaults.FaultPlan` each round
(so faults land mid-window, mid-spec-harvest, mid-swap, mid-prefill —
wherever the seam counter happens to fall), heals every poison with
``revive()``, and checks the GLOBAL invariants after every round:

1. **Page conservation** — the pool's books balance
   (``kvcache.page_accounting``: ``free + live == pages_total``, no
   negative refcount, no page both free and live) and every page is
   free once the round's requests settle. The server's own
   ``debug_pages`` audit runs at every quiescent boundary during the
   round, so a transient leak poisons loudly instead of hiding.
   With the shared-prefix mix (``prefix_mix=True``) the check is
   REFCOUNT-AWARE: registry-pinned pages are legitimately live after
   settle, so conservation becomes ``free + |distinct pinned pages|
   == pages_total`` (a page shared by several entries counts ONCE),
   every pinned page's refcount must equal exactly the number of
   entries holding it (no leaked retains after poison/revive or a
   journal-refcount restore), the journal's shadow store must be
   empty, and force-evicting the whole registry must return the pool
   to every-page-free.
2. **No stuck tickets** — every submission terminates (tokens or a
   typed error) within the round's deadline; the journal and the
   active set are empty once the round settles.
3. **Monotone emitted offsets** — a streamed consumer's token log only
   grows, and never beyond its ``n_new`` budget (no duplicate delivery
   after a resume, no over-emission).
4. **Bit-identity vs the fault-free oracle** — every request that
   completes matches the tokens an uninterrupted greedy run produces;
   with boundary checkpoints on, requests that were in flight when the
   pool poisoned complete (restored from the journal) rather than
   failing. Failures that do occur (e.g. a fault raising into the
   submit path before admission) must be typed.

Seed-derived, same replay contract as the fault harnesses: the
campaign's whole DECISION stream — server shape, prompts, consumer
mix, per-round fault plans — derives from ``random.Random(seed)`` and
is appended to ``trace``. The seam a plan ends up firing on still
depends on thread interleaving (submission arrival order is real
concurrency), which is exactly why the trace records it: a failing
campaign ships both the decisions and what they landed on.
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time

from kvedge_tpu.runtime.failures import (
    PageAccountingError,
    ServingFailure,
)
from kvedge_tpu.testing.faults import InvariantViolation
from kvedge_tpu.testing.servingfaults import (
    FaultPlan,
    FaultyCache,
    InjectedFault,
)

__all__ = ["ChaosResult", "run_chaos_campaign"]


@dataclasses.dataclass
class ChaosResult:
    """One campaign's outcome (all invariants already enforced)."""

    seed: int
    config: dict
    rounds: int
    fired: list  # seam label (or None) per round
    completed: int
    failed: int
    revives: int
    restored_total: int
    trace: list


@dataclasses.dataclass
class _Sub:
    prompt: list
    n_new: int
    streaming: bool
    want: list
    tokens: list | None = None
    got: list = dataclasses.field(default_factory=list)
    over_emitted: bool = False
    error: Exception | None = None
    finished: threading.Event = dataclasses.field(
        default_factory=threading.Event
    )


def _draw_config(rng: random.Random) -> dict:
    """The campaign's server shape: checkpoints always ON (this is the
    durability soak), the rest drawn so the seeded fleet covers the
    serial loop, the overlapped pipeline, and windowed speculation."""
    spec = rng.choice([0, 0, 2])
    return {
        "checkpoint_every": rng.choice([1, 2]),
        "overlap": rng.choice(["off", "on"]),
        "window": rng.choice([1, 2, 4]),
        "speculative": spec,
        "spec_window": rng.choice([0, 2]) if spec else 0,
    }


def run_chaos_campaign(params, tcfg, seed: int, *, rounds: int = 2,
                       requests_per_round: int = 3, n_new: int = 8,
                       slots: int = 3, pages: int = 24,
                       page_size: int = 4, vocab: int | None = None,
                       prompt_len: tuple = (3, 7),
                       config: dict | None = None, oracle=None,
                       wound=None, prefix_mix: bool = False,
                       join_timeout_s: float = 180.0) -> ChaosResult:
    """Run one seeded campaign against a fresh server; raise
    :class:`~kvedge_tpu.testing.faults.InvariantViolation` (carrying
    the full decision trace) on any breach, else return the result.

    ``config`` pins the server shape instead of drawing it (the
    deterministic tier-1 subset pins a cheap shape; the soak draws).
    ``oracle(prompt, n_new) -> tokens`` supplies the fault-free
    reference (tests memoize it across campaigns); None builds one
    from ``models.generate`` per prompt. ``wound(round_i, server,
    cache, plan)`` runs after each round's plan is armed — the hook
    slice/capacity tests use to compose extra damage (follower loss,
    bucket pressure) on top of the seam fault. ``prefix_mix`` turns
    the prefix cache ON and draws prompts from a small set of shared
    page-sized stems, so faults land on COW admissions, leased pages,
    and journal-refcount checkpoints — the settle check then runs the
    refcount-aware conservation invariant (docstring point 1).
    """
    from kvedge_tpu.models.serving import (
        PagedGenerationServer,
        RequestCancelled,
        ServerBusy,
        ServerClosed,
    )

    rng = random.Random(seed)
    cfg_draw = dict(_draw_config(rng))
    if config:
        cfg_draw.update(config)
    trace = [f"[campaign] seed={seed} config={cfg_draw}"]
    allowed = (ServingFailure, ServerBusy, ServerClosed,
               RequestCancelled, InjectedFault)

    if oracle is None:
        import jax.numpy as jnp
        import numpy as np

        from kvedge_tpu.models import generate

        def oracle(prompt, n):
            out = generate(params, jnp.asarray([prompt], jnp.int32),
                           tcfg, n_new=n)
            return [int(t) for t in np.asarray(out)[0]]

    vocab = vocab or tcfg.vocab
    cache = FaultyCache(tcfg, slots=slots, pages=pages,
                        page_size=page_size)
    # Default mix runs prefix_cache off: pinned prefix pages are
    # LEGITIMATELY live across requests, which would poison the plain
    # every-page-free check — and prefix reuse is orthogonal to the
    # basic durability story. ``prefix_mix`` flips it on and switches
    # the settle check to the refcount-aware invariant.
    #
    # The observability stack (rung 25) runs ON in every campaign: the
    # SLO engine snapshots and occupancy ring sample at the same
    # boundaries faults land on, and the flight-recorder completeness
    # invariant below asserts the bundle survives every poison/revive.
    from kvedge_tpu.runtime.slo import SloObjectives

    server = PagedGenerationServer(
        params, tcfg, cache=cache, prefix_cache=prefix_mix,
        debug_pages=True, slo=SloObjectives(), occupancy_ring=64,
        **cfg_draw,
    )
    stems = []
    if prefix_mix:
        # Two fixed page-multiple stems (so full-block trie hits) the
        # seeded prompts below share; suffixes diverge mid-page too,
        # exercising the COW path.
        stems = [
            [rng.randrange(1, vocab) for _ in range(page_size)],
            [rng.randrange(1, vocab) for _ in range(2 * page_size)],
        ]

    def fail(msg):
        raise InvariantViolation(f"[chaos seed={seed}] {msg}", trace)

    fired, completed, failed = [], 0, 0
    revives = restored_total = 0
    try:
        for round_i in range(rounds):
            plan = FaultPlan(
                seed=rng.randrange(1 << 30),
                # No "hang": the single-host pool has no deadline
                # watchdog, so a parked seam would stall the round,
                # not poison it — raise/delay cover the poison and
                # slow-path stories the soak is after.
                kinds=("raise", "delay"),
                # Coalesced boundary checkpoints (one swapout per
                # boundary, unchanged requests skipped) mean a round
                # crosses far fewer device seams than the per-request
                # swapout era — indices past ~10 are reached only on
                # lucky interleavings. Keep the drawn fire index low
                # so every plan lands mid-flight deterministically.
                fire_window=(1, rng.randrange(3, 10)),
                delay_s=0.05,
            )
            cache.plan = plan
            trace.extend(plan.trace[:1])
            if wound is not None:
                wound(round_i, server, cache, plan)
            subs = []
            for _ in range(requests_per_round):
                prompt = [rng.randrange(1, vocab)
                          for _ in range(rng.randrange(*prompt_len))]
                if prefix_mix and rng.random() < 0.75:
                    prompt = rng.choice(stems) + prompt
                subs.append(_Sub(
                    prompt=prompt, n_new=n_new,
                    streaming=rng.random() < 0.5,
                    want=oracle(prompt, n_new),
                ))
            threads = [
                threading.Thread(target=_drive, args=(server, sub),
                                 name=f"chaos-{round_i}-{i}",
                                 daemon=True)
                for i, sub in enumerate(subs)
            ]
            for i, sub in enumerate(subs):
                trace.append(
                    f"[round {round_i} submit {i}] "
                    f"len={len(sub.prompt)} "
                    f"{'stream' if sub.streaming else 'block'}"
                )
                threads[i].start()

            def heal(round_i=round_i):
                """Revive a poisoned pool; returns True if it healed
                one. Page-audit poisons are invariant breaches, never
                healed — they mean the books are already broken."""
                nonlocal revives, restored_total
                if server.degraded is None:
                    return False
                poison = server._poison
                if isinstance(poison, PageAccountingError):
                    fail(f"round {round_i}: page books broken — "
                         f"{poison}")
                server._thread.join(timeout=60)
                if server._thread.is_alive():
                    fail(f"round {round_i}: decode thread still "
                         "alive after poison")
                restored = server.revive()
                revives += 1
                restored_total += restored
                trace.append(f"[round {round_i}] revived, "
                             f"restored={restored}")
                return True

            # Pump the round: heal every poison until all settle.
            deadline = time.monotonic() + join_timeout_s
            while not all(s.finished.is_set() for s in subs):
                if time.monotonic() > deadline:
                    plan.close()
                    fail(f"round {round_i}: stuck ticket — a request "
                         f"never terminated within {join_timeout_s:g}s")
                if not heal():
                    time.sleep(0.01)
            for t in threads:
                t.join(timeout=10)
            # A poison that failed every request before the pump saw it
            # (e.g. the very first checkpoint's swapout raising, with
            # nothing journaled yet) still needs healing — the settle
            # checks below run against a live pool, and the next round
            # submits into it.
            heal()
            fired.append(plan.fired_on)
            trace.append(f"[round {round_i}] fired_on={plan.fired_on}")

            # Invariant 3/4 per request; 1/2 for the settled pool.
            for i, sub in enumerate(subs):
                if sub.over_emitted:
                    fail(f"round {round_i} request {i}: stream emitted "
                         f"beyond its n_new={n_new} budget")
                if sub.error is not None:
                    if not isinstance(sub.error, allowed):
                        fail(f"round {round_i} request {i} died "
                             f"UNTYPED: {type(sub.error).__name__}: "
                             f"{sub.error}")
                    failed += 1
                    trace.append(f"[round {round_i} outcome {i}] "
                                 f"{type(sub.error).__name__}")
                    continue
                if sub.tokens != sub.want:
                    fail(f"round {round_i} request {i}: tokens diverge "
                         f"from the fault-free oracle\n got "
                         f"{sub.tokens}\nwant {sub.want}")
                completed += 1
                trace.append(f"[round {round_i} outcome {i}] ok")
            _check_settled(server, cache, fail,
                           context=f"round {round_i}")
            _check_bundle(server, cache, fail,
                          context=f"round {round_i}")
            plan.close()
        return ChaosResult(
            seed=seed, config=cfg_draw, rounds=rounds, fired=fired,
            completed=completed, failed=failed, revives=revives,
            restored_total=restored_total, trace=trace,
        )
    finally:
        cache.plan = None
        server.close()


def _drive(server, sub: _Sub) -> None:
    """One consumer. Streaming consumers keep the per-token log the
    monotone-offset invariant checks; both park across revive (no
    timeout — the campaign's pump owns the deadline)."""
    try:
        if sub.streaming:
            handle = server.submit_stream(sub.prompt, sub.n_new)
            for tok in handle:
                sub.got.append(tok)
                if len(sub.got) > sub.n_new:
                    sub.over_emitted = True
                    break
            sub.tokens = sub.prompt + sub.got
        else:
            sub.tokens = server.submit(sub.prompt, sub.n_new)
    except Exception as e:
        sub.error = e
    finally:
        sub.finished.set()


def _check_settled(server, cache, fail, *, context: str) -> None:
    """Invariants 1 + 2 once a round's requests have all terminated:
    balanced books, no journal residue, nothing still admitted. With
    the prefix cache off, every page must be free; with it on, the
    REFCOUNT-AWARE form applies — registry pins are the only
    legitimate holds, each counted once however many entries share
    it, each page's refcount exactly the holding-entry count, the
    journal's shadow store empty, and a full force-evict returns the
    pool to every-page-free (no leaked retains or leases)."""
    acct = cache.page_accounting()
    ok = (acct["free"] + acct["live"] == acct["pages_total"]
          and not acct["free_dup"] and not acct["neg_refs"]
          and not acct["free_live"])
    if not ok:
        fail(f"{context}: page books broken after settle: {acct}")
    with server._lock:
        holds: dict = {}
        for entry in server._prefix_entry_nodes.values():
            for p in entry["pages"]:
                holds[p] = holds.get(p, 0) + 1
        leases = dict(server._lease)
        shadow_nodes = len(server._prefix_shadow)
    if leases:
        fail(f"{context}: leases leaked after settle: {leases}")
    if acct["free"] + len(holds) != acct["pages_total"]:
        fail(f"{context}: pages leaked after settle "
             f"(free={acct['free']} pinned={len(holds)} "
             f"total={acct['pages_total']})")
    for p in range(acct["pages_total"]):
        want = holds.get(p, 0)
        got = cache.page_refcount(p)
        if got != want:
            fail(f"{context}: page {p} refcount {got} != "
                 f"{want} holding entries — leaked retain")
    stats = server.stats()
    if stats.get("journal_entries"):
        fail(f"{context}: journal residue after settle: "
             f"{stats['journal_entries']} entries")
    if shadow_nodes or stats.get("journal_shadow_bytes"):
        fail(f"{context}: shadow residue after settle: "
             f"{shadow_nodes} nodes, "
             f"{stats.get('journal_shadow_bytes')} bytes")
    if stats.get("in_flight"):
        fail(f"{context}: {stats['in_flight']} requests still "
             "admitted after settle")
    # The pins themselves must release cleanly: force-evict the whole
    # registry (and the host tier) and require every page free.
    if holds:
        with server._lock:
            for node in list(server._prefix_entry_nodes):
                server._evict_prefix_node(node, "pressure")
            for node in list(server._prefix_host_nodes):
                server._drop_host_record_locked(node)
        if cache.free_pages() != acct["pages_total"]:
            fail(f"{context}: {acct['pages_total'] - cache.free_pages()}"
                 f" pages still held after force-evicting the registry")


# Every key a version-1 flight-recorder bundle must carry
# (models/serving.py flight_bundle). Completeness is the invariant:
# a post-mortem missing its books or its SLO state is worse than no
# post-mortem, because it looks authoritative.
_BUNDLE_V1_KEYS = frozenset((
    "bundle_version", "reason", "degraded", "metrics", "slo",
    "occupancy_tail", "journal", "config", "config_fingerprint",
    "trace_tail", "page_accounting",
))


def _check_bundle(server, cache, fail, *, context: str) -> None:
    """Rung-25 flight-recorder completeness after every round: the
    bundle must be schema-complete, JSON-serialisable, and its
    SLO/burn state and page books must agree with a fresh stats()
    snapshot — the bundle claims to BE the server's final state, so
    any drift between the two means the single-lock assembly broke."""
    import json as _json

    bundle = server.flight_bundle()
    missing = _BUNDLE_V1_KEYS - set(bundle)
    if missing:
        fail(f"{context}: bundle incomplete — missing "
             f"{sorted(missing)}")
    if bundle["bundle_version"] != 1:
        fail(f"{context}: unknown bundle_version "
             f"{bundle['bundle_version']!r}")
    try:
        _json.dumps(bundle)
    except (TypeError, ValueError) as e:
        fail(f"{context}: bundle is not JSON-serialisable: {e}")
    if not bundle["config_fingerprint"]:
        fail(f"{context}: bundle config_fingerprint is empty")
    if bundle["slo"] is None:
        fail(f"{context}: bundle has no SLO state with the engine on")
    # The campaign's server runs with an occupancy ring, and settle
    # happens after at least one quiescent boundary — the timeline
    # tail must not be empty.
    if not bundle["occupancy_tail"]:
        fail(f"{context}: bundle occupancy_tail is empty")
    books = bundle["page_accounting"]
    if books is None:
        fail(f"{context}: bundle page books absent (cache exposes "
             "page_accounting)")
    if books != cache.page_accounting():
        fail(f"{context}: bundle page books diverge from the live "
             f"pool: {books} vs {cache.page_accounting()}")
    # SLO/burn agreement with the server's own metrics snapshot: the
    # pool is quiescent after settle, so the flat slo_* gauges stats()
    # exports must be exactly what the bundle froze.
    stats = server.stats()
    for key in stats:
        if key.startswith("slo_") and bundle["metrics"].get(key) != stats[key]:
            fail(f"{context}: bundle {key}={bundle['metrics'].get(key)!r}"
                 f" != live stats {stats[key]!r}")
