"""A deterministic fake Kubernetes for testing the chart's control flow.

Simulates exactly the controller behavior the rendered manifests rely on
(SURVEY.md §3.1 steps 3-5, translated from KubeVirt/CDI to pods/PVCs):

* **PVC binder** — WaitForFirstConsumer-style: a PVC binds to the node of
  the first pod that mounts it. By default the volume is then *node-bound*
  (the reference's documented failure mode: rescheduling can fail to
  re-attach, ``README.md:89``); ``resilient_storage=True`` models a
  detachable storage class (the ``README.md:88`` StorageOS mitigation).
* **Deployment controller** — keeps one pod existing per single-replica
  Recreate Deployment; never runs two pods concurrently.
* **StatefulSet controller** — the multi-host chart variant: ``replicas``
  pods with STABLE ordinal names (``<name>-<ordinal>``, the identity
  ``parallel/distributed.py`` infers the process id from), each owning a
  per-ordinal PVC stamped from ``volumeClaimTemplates``
  (``<template>-<pod>``, the K8s naming rule). A killed pod is recreated
  under the same name and re-attaches the SAME per-ordinal claim —
  per-host state identity across generations, which is the property the
  StatefulSet exists for.
* **Scheduler** — places pending pods on nodes matching ``nodeSelector``
  with the mounted PVC attachable there; otherwise the pod stays Pending
  with a reason.
* **Service endpoints** — label-selector resolution.
* **Failure injection** — ``kill_node`` terminates a node and its pods.

``boot_pod`` optionally *executes the real container entrypoint* against a
scratch pod filesystem whose state mount is the PVC's persistent backing
directory — so resilience tests observe genuine state survival (heartbeat
``boot_count`` increments across rescheduling) rather than a mock of it.
"""

from __future__ import annotations

import base64
import dataclasses
import itertools
import json
import os
import time


@dataclasses.dataclass
class FakeNode:
    name: str
    labels: dict[str, str] = dataclasses.field(default_factory=dict)
    alive: bool = True


@dataclasses.dataclass
class FakePod:
    name: str
    spec: dict
    owner: str  # deployment / statefulset name
    node: str | None = None
    phase: str = "Pending"  # Pending | Running | Terminated
    reason: str = ""
    # Incremented each time the controller recreates this (stable-named)
    # pod — StatefulSet generations share a name, unlike Deployment pods.
    generation: int = 1


@dataclasses.dataclass
class FakePVC:
    name: str
    spec: dict
    bound_node: str | None = None


class FakeClusterError(RuntimeError):
    pass


class FakeCluster:
    def __init__(self, nodes: list[FakeNode], *, resilient_storage: bool = False,
                 state_root: str | None = None):
        self.nodes = {n.name: n for n in nodes}
        self.resilient_storage = resilient_storage
        self.state_root = state_root
        self.secrets: dict[str, dict] = {}
        self.pvcs: dict[str, FakePVC] = {}
        self.deployments: dict[str, dict] = {}
        self.statefulsets: dict[str, dict] = {}
        self.services: dict[str, dict] = {}
        self.pods: dict[str, FakePod] = {}
        # helm-hook manifests (the chart's `helm test` healthz Pod): real
        # helm holds these back from install and runs them on demand; the
        # fake cluster records them without scheduling anything.
        self.hooks: dict[str, dict] = {}
        self._pod_seq = itertools.count(1)

    # ---- admission -------------------------------------------------------

    def apply(self, manifests: dict[str, dict] | list[dict]) -> None:
        docs = list(
            manifests.values() if isinstance(manifests, dict) else manifests
        )
        # Duplicate detection is per apply batch (two docs colliding on one
        # name, the .helmignore hazard) — re-applying an existing resource
        # is a normal upgrade and overwrites, like `kubectl apply`.
        seen: set[tuple[str, str]] = set()
        for doc in docs:
            key = (doc["kind"], doc["metadata"]["name"])
            if key in seen:
                raise FakeClusterError(
                    f"{key[0]} {key[1]!r} already exists in this batch "
                    "(duplicate resource name)"
                )
            seen.add(key)
        for doc in docs:
            kind = doc["kind"]
            name = doc["metadata"]["name"]
            if "helm.sh/hook" in doc["metadata"].get("annotations", {}):
                # Checked before kind dispatch: helm holds back ANY
                # hook-annotated resource from install, whatever its kind.
                self.hooks[name] = doc
            elif kind == "Secret":
                self.secrets[name] = doc
            elif kind == "PersistentVolumeClaim":
                if name not in self.pvcs:  # keep binding across upgrades
                    self.pvcs[name] = FakePVC(name=name, spec=doc["spec"])
            elif kind == "Deployment":
                self.deployments[name] = doc
            elif kind == "StatefulSet":
                self.statefulsets[name] = doc
            elif kind == "Service":
                self.services[name] = doc
            else:
                raise FakeClusterError(f"unsupported kind {kind!r}")

    # ---- controllers -----------------------------------------------------

    def step(self) -> None:
        """One reconcile pass of every controller. Deterministic."""
        self._reconcile_deployments()
        self._reconcile_statefulsets()
        self._schedule_pods()

    def converge(self, max_steps: int = 10) -> None:
        for _ in range(max_steps):
            before = self._state_fingerprint()
            self.step()
            if self._state_fingerprint() == before:
                return
        raise FakeClusterError("cluster did not converge")

    def _state_fingerprint(self):
        return tuple(
            (p.name, p.node, p.phase) for p in sorted(
                self.pods.values(), key=lambda p: p.name
            )
        ) + tuple(
            (c.name, c.bound_node) for c in sorted(
                self.pvcs.values(), key=lambda c: c.name
            )
        )

    def _reconcile_deployments(self) -> None:
        for name, dep in self.deployments.items():
            live = [
                p for p in self.pods.values()
                if p.owner == name and p.phase != "Terminated"
            ]
            replicas = dep["spec"].get("replicas", 1)
            strategy = dep["spec"].get("strategy", {}).get("type")
            if len(live) < replicas:
                # Recreate: never start a replacement while an old pod is
                # still non-terminated (there is none here by construction).
                if strategy == "Recreate" and any(
                    p.phase == "Running" for p in live
                ):
                    continue
                pod_spec = dep["spec"]["template"]["spec"]
                self._validate_pod_refs(pod_spec)
                pod = FakePod(
                    name=f"{name}-{next(self._pod_seq)}",
                    spec=dep["spec"]["template"],
                    owner=name,
                )
                self.pods[pod.name] = pod

    def _reconcile_statefulsets(self) -> None:
        for name, sts in self.statefulsets.items():
            spec = sts["spec"]
            replicas = spec.get("replicas", 1)
            templates = spec.get("volumeClaimTemplates", [])
            for ordinal in range(replicas):
                pod_name = f"{name}-{ordinal}"
                existing = self.pods.get(pod_name)
                if existing is not None and existing.phase != "Terminated":
                    continue
                # Stamp the per-ordinal claims (K8s names them
                # <template>-<pod>); they persist across pod generations —
                # that persistence IS the StatefulSet contract under test.
                pod_template = json.loads(json.dumps(spec["template"]))
                pod_spec = pod_template["spec"]
                for tpl in templates:
                    claim = f"{tpl['metadata']['name']}-{pod_name}"
                    if claim not in self.pvcs:
                        self.pvcs[claim] = FakePVC(
                            name=claim, spec=tpl["spec"]
                        )
                    pod_spec.setdefault("volumes", []).append({
                        "name": tpl["metadata"]["name"],
                        "persistentVolumeClaim": {"claimName": claim},
                    })
                self._validate_pod_refs(pod_spec)
                self.pods[pod_name] = FakePod(
                    name=pod_name,
                    spec=pod_template,
                    owner=name,
                    generation=(existing.generation + 1) if existing else 1,
                )

    def _validate_pod_refs(self, pod_spec: dict) -> None:
        for vol in pod_spec.get("volumes", []):
            if "secret" in vol:
                ref = vol["secret"]["secretName"]
                if ref not in self.secrets:
                    raise FakeClusterError(
                        f"pod references missing Secret {ref!r} — the "
                        "name-mismatch class of bug the reference carried "
                        "(aziot-edge-vm.yaml:57)"
                    )
            if "persistentVolumeClaim" in vol:
                ref = vol["persistentVolumeClaim"]["claimName"]
                if ref not in self.pvcs:
                    raise FakeClusterError(
                        f"pod references missing PVC {ref!r}"
                    )

    def _pod_pvcs(self, pod: FakePod) -> list[FakePVC]:
        return [
            self.pvcs[v["persistentVolumeClaim"]["claimName"]]
            for v in pod.spec["spec"].get("volumes", [])
            if "persistentVolumeClaim" in v
        ]

    def pod_state_path(self, pod: FakePod, relpath: str) -> str:
        """Path of a file on the pod's PVC backing dir (mount-path free).

        The persistent backing directory under ``state_root`` is keyed by
        PVC name, so this resolves the same file across pod generations —
        the public way to inspect persisted state (heartbeats etc.)
        without hardcoding the chart's mountPath.
        """
        if self.state_root is None:
            raise FakeClusterError("state_root required for pod_state_path")
        (pvc,) = self._pod_pvcs(pod)
        return os.path.join(self.state_root, pvc.name, relpath)

    def _schedulable_node(self, pod: FakePod) -> tuple[str | None, str]:
        selector = pod.spec["spec"].get("nodeSelector", {})
        candidates = [
            n for n in self.nodes.values()
            if n.alive and all(n.labels.get(k) == v for k, v in selector.items())
        ]
        if not candidates:
            return None, f"no alive node matches nodeSelector {selector}"
        for pvc in self._pod_pvcs(pod):
            if pvc.bound_node is not None and not self.resilient_storage:
                # Node-bound volume: only its node is eligible
                # (the README.md:89 failure mode).
                candidates = [n for n in candidates if n.name == pvc.bound_node]
                if not candidates:
                    return None, (
                        f"PVC {pvc.name} is bound to node {pvc.bound_node} "
                        "which is not schedulable (node-bound volume; see "
                        "reference README.md:89)"
                    )
        return candidates[0].name, ""

    def _schedule_pods(self) -> None:
        for pod in self.pods.values():
            if pod.phase != "Pending":
                continue
            node, reason = self._schedulable_node(pod)
            if node is None:
                pod.reason = reason
                continue
            pod.node = node
            pod.phase = "Running"
            pod.reason = ""
            for pvc in self._pod_pvcs(pod):
                if pvc.bound_node is None or self.resilient_storage:
                    pvc.bound_node = node

    # ---- failure injection ----------------------------------------------

    def kill_node(self, name: str) -> None:
        self.nodes[name].alive = False
        for pod in self.pods.values():
            if pod.node == name and pod.phase == "Running":
                pod.phase = "Terminated"
                pod.reason = f"node {name} failed"

    def revive_node(self, name: str) -> None:
        self.nodes[name].alive = True

    # ---- observation -----------------------------------------------------

    def running_pod(self, deployment: str) -> FakePod | None:
        for pod in self.pods.values():
            if pod.owner == deployment and pod.phase == "Running":
                return pod
        return None

    def pending_pods(self, deployment: str) -> list[FakePod]:
        return [
            p for p in self.pods.values()
            if p.owner == deployment and p.phase == "Pending"
        ]

    def sts_pods(self, statefulset: str) -> list[FakePod]:
        """The StatefulSet's pods, by ordinal."""
        replicas = self.statefulsets[statefulset]["spec"].get("replicas", 1)
        return [
            self.pods[f"{statefulset}-{i}"]
            for i in range(replicas)
            if f"{statefulset}-{i}" in self.pods
        ]

    def service_endpoints(self, service: str) -> list[str]:
        svc = self.services[service]
        selector = svc["spec"]["selector"]
        return sorted(
            p.name for p in self.pods.values()
            if p.phase == "Running" and all(
                p.spec["metadata"]["labels"].get(k) == v
                for k, v in selector.items()
            )
        )

    # ---- real-entrypoint execution ---------------------------------------

    def boot_pod(self, pod: FakePod, scratch_dir: str) -> int:
        """Run the pod's real container entrypoint against a scratch root.

        Projects the referenced Secrets to their mount paths (what kubelet
        does) and maps each PVC mount onto a persistent per-PVC directory
        under ``state_root`` — the same directory across pod generations,
        which is what makes the PVC a PVC.
        """
        from kvedge_tpu.bootstrap.commands import rebase
        from kvedge_tpu.bootstrap.entrypoint import main as entrypoint_main

        if self.state_root is None:
            raise FakeClusterError("state_root required for boot_pod")
        if pod.phase != "Running":
            raise FakeClusterError(f"pod {pod.name} is {pod.phase}, not Running")
        spec = pod.spec["spec"]
        container = spec["containers"][0]
        secret_by_vol = {
            v["name"]: v["secret"]["secretName"]
            for v in spec.get("volumes", []) if "secret" in v
        }
        pvc_by_vol = {
            v["name"]: v["persistentVolumeClaim"]["claimName"]
            for v in spec.get("volumes", []) if "persistentVolumeClaim" in v
        }
        for vm in container.get("volumeMounts", []):
            target = rebase(vm["mountPath"], scratch_dir)
            if vm["name"] in secret_by_vol:
                os.makedirs(target, exist_ok=True)
                secret = self.secrets[secret_by_vol[vm["name"]]]
                for key, b64 in secret.get("data", {}).items():
                    with open(os.path.join(target, key), "wb") as fh:
                        fh.write(base64.b64decode(b64))
            elif vm["name"] in pvc_by_vol:
                backing = os.path.join(
                    self.state_root, pvc_by_vol[vm["name"]]
                )
                os.makedirs(backing, exist_ok=True)
                os.makedirs(os.path.dirname(target), exist_ok=True)
                if not os.path.islink(target):
                    os.symlink(backing, target)
        command = container["command"]
        if command and command[0].endswith("/kvedge-init"):
            # The pod command wraps the entrypoint with the native PID-1
            # supervisor (native/kvedge-init.cc). The fake cluster boots
            # pods in-process, so it unwraps to the supervised child — but
            # first records a supervisor-start event to the rebased events
            # path, preserving the observable contract that a booted pod's
            # /status carries init_events from its state volume.
            if "--" not in command:
                raise FakeClusterError(
                    f"kvedge-init command without '--': {command}"
                )
            sep = command.index("--")
            wrapper, command = command[1:sep], command[sep + 1:]
            if "--events" in wrapper:
                events_path = rebase(
                    wrapper[wrapper.index("--events") + 1], scratch_dir
                )
                os.makedirs(os.path.dirname(events_path), exist_ok=True)
                with open(events_path, "a", encoding="utf-8") as fh:
                    fh.write(
                        json.dumps({
                            "ts": time.time(),
                            "event": "supervisor-start",
                            "fake": True,
                            "pod": pod.name,
                        }) + "\n"
                    )
        if command[:3] != ["python", "-m", "kvedge_tpu.bootstrap.entrypoint"]:
            raise FakeClusterError(f"unexpected container command {command}")
        boot_config = command[command.index("--boot-config") + 1]
        boot_path = rebase(boot_config, scratch_dir)
        # Tests must not block in the heartbeat loop: run the boot sequence
        # with --once appended to the final runcmd.
        with open(boot_path, "r", encoding="utf-8") as fh:
            doc = fh.read()
        patched = doc.replace("kvedge-runtime boot ", "kvedge-runtime boot --once ")
        if patched == doc:
            raise FakeClusterError(
                "rendered runcmd wording changed; --once patch did not apply"
            )
        with open(boot_path, "w", encoding="utf-8") as fh:
            fh.write(patched)
        return entrypoint_main(
            ["--boot-config", boot_path, "--root", scratch_dir]
        )
