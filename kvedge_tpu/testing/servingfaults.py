"""Seeded fault injection for the SERVING path.

``testing/faults.py`` walks node kills against the fake cluster; this
module is its serving-layer sibling, exercising the failure taxonomy of
``runtime/failures.py`` end to end: faults fire at the *device seams* —
the exact boundaries where a real follower dies, a broadcast stalls, or
a device op raises — while concurrent requests are in flight, and the
harness then asserts the recovery contract the taxonomy promises:

* **Every request terminates** — tokens or a typed error, never a hang.
* **No token is emitted twice** and no stream over-emits its budget.
* **The server lock is never orphaned** (a wedged op must not exit
  holding it).
* **close() stays bounded** and the decode thread is actually gone.
* **Prefix-cache files are never torn** — absent or fully loadable,
  even when a dump is killed mid-write.

Deterministic per seed, same contract as the cluster harness: the plan
draws its fault kind and firing seam from ``random.Random(seed)`` and
records every seam it crosses in ``trace``, so a failing schedule
replays exactly from its seed + trace.
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import threading
import time

import numpy as np

from kvedge_tpu.models.kvcache import PagedKVCache
from kvedge_tpu.runtime.failures import ServingFailure
from kvedge_tpu.testing.faults import InvariantViolation

__all__ = [
    "FaultPlan",
    "FaultyCache",
    "FaultySliceTransport",
    "InjectedFault",
    "ServingFaultResult",
    "ServingFaultSchedule",
    "prefix_file_intact",
]


class InjectedFault(RuntimeError):
    """The raw (untyped) error a fault injector raises at a seam —
    deliberately NOT a ServingFailure, so runs also prove the
    classification path (classify_failure wraps it as PoolPoisoned)."""


class FaultPlan:
    """A seeded decision of WHAT fails and WHEN.

    The plan counts every seam crossing (device op on a
    :class:`FaultyCache`, broadcast on a
    :class:`FaultySliceTransport`) and fires once, at the drawn index:

    * ``"raise"`` — the seam raises :class:`InjectedFault` (a device op
      failing loudly);
    * ``"hang"`` — the seam parks until :meth:`close` (a dead follower:
      the op never returns, only the deadline watchdog can detect it);
    * ``"delay"`` — the seam sleeps ``delay_s`` then proceeds (a stalled
      broadcast: past-deadline completion must still surface typed).

    A parked seam raises after release rather than completing, so an
    orphaned op thread can never mutate cache state behind a pool that
    already poisoned.

    By default the plan fires ONCE — the original contract, under which
    every seam after ``fire_at`` succeeds. ``heal_at`` switches to
    **outage-window** semantics for recovery schedules: every seam in
    ``[fire_at, heal_at)`` fires (the follower is *gone*, not
    glitching), and seams from ``heal_at`` on succeed (the follower
    rejoined). ``heal_at`` far beyond any reachable seam count models a
    follower that never comes back — the escalation path.
    """

    def __init__(self, seed: int, *, kinds=("raise", "hang", "delay"),
                 fire_window: tuple[int, int] = (1, 12),
                 delay_s: float = 0.0, heal_at: int | None = None):
        rng = random.Random(seed)
        self.kind = rng.choice(list(kinds))
        self.fire_at = rng.randrange(*fire_window)
        self.heal_at = heal_at
        self.delay_s = delay_s
        self.count = 0
        self.fired_on: str | None = None
        self.trace: list[str] = [
            f"[plan] seed={seed} kind={self.kind} fire_at={self.fire_at}"
            + (f" heal_at={heal_at}" if heal_at is not None else "")
        ]
        self._release = threading.Event()
        self._lock = threading.Lock()

    def at_seam(self, label: str) -> None:
        """Called by the injectors at every seam crossing."""
        with self._lock:
            i = self.count
            self.count += 1
            if self.heal_at is None:
                fire = i == self.fire_at and self.fired_on is None
            else:
                fire = self.fire_at <= i < self.heal_at
            if fire and self.fired_on is None:
                self.fired_on = label
            self.trace.append(
                f"[{i}] {label}" + (f" <- {self.kind}" if fire else "")
            )
        if not fire:
            return
        if self.kind == "raise":
            raise InjectedFault(f"injected raise at seam {i} ({label})")
        if self.kind == "hang":
            # Park like a dead follower's collective. The watchdog
            # orphans this thread; the bounded wait below is the
            # harness's own leak guard, not part of the simulation.
            self._release.wait(timeout=120.0)
            raise InjectedFault(
                f"injected hang at seam {i} ({label}) released"
            )
        time.sleep(self.delay_s)

    def close(self) -> None:
        """Release any parked seam (end-of-run cleanup)."""
        self._release.set()


class FaultyCache(PagedKVCache):
    """A paged cache whose device seams consult a :class:`FaultPlan`
    before executing — fault injection at exactly the boundary where a
    real device/transport failure would surface, with the genuine
    kernels running everywhere the plan stays quiet."""

    def __init__(self, *args, plan: FaultPlan | None = None, **kwargs):
        self.plan = plan
        super().__init__(*args, **kwargs)

    def _seam(self, label: str) -> None:
        if self.plan is not None:
            self.plan.at_seam(label)

    def _device_prefill(self, params, tokens, slot: int, offset: int):
        self._seam(f"prefill[{np.asarray(tokens).shape[0]}]")
        return super()._device_prefill(params, tokens, slot, offset)

    def _device_step(self, params, tokens, active):
        self._seam("step")
        return super()._device_step(params, tokens, active)

    def _device_window(self, params, tokens, n_steps: int, active):
        self._seam(f"window[{n_steps}]")
        return super()._device_window(params, tokens, n_steps, active)

    def _device_window_sampled(self, params, tokens, n_steps: int,
                               active, key_data, base_steps, temps,
                               top_ps, sampled_mask):
        self._seam(f"wsample[{n_steps}]")
        return super()._device_window_sampled(
            params, tokens, n_steps, active, key_data, base_steps,
            temps, top_ps, sampled_mask,
        )

    def _device_spec(self, params, tokens, active, spec_mask):
        self._seam("spec")
        return super()._device_spec(params, tokens, active, spec_mask)

    # Preemptive-swap seams (models/scheduler.py, SERVING.md rung 17):
    # a swap-out dies with the victim's pages still intact on device
    # (the poison path must not leak its snapshot-to-be); a swap-in
    # dies after the resume reservation was re-booked (revive must
    # return the pool to the idle fixpoint regardless).
    def _device_swapout(self, ids):
        self._seam("swapout")
        return super()._device_swapout(ids)

    def _device_swapin(self, ids, arrays):
        self._seam("swapin")
        return super()._device_swapin(ids, arrays)

    # Overlapped-pipeline seams (models/serving.py _loop_once_overlap):
    # dispatch and harvest are SEPARATE failure boundaries now — a
    # dispatch can die while an earlier window is still in flight, and
    # a harvest can die on a window that was dispatched healthy. Both
    # must drain cleanly into the poison path.
    def _device_window_dispatch(self, params, tokens, n_steps: int,
                                active, steps_left, stop_tokens):
        self._seam(f"windowp[{n_steps}]")
        return super()._device_window_dispatch(
            params, tokens, n_steps, active, steps_left, stop_tokens
        )

    def _device_window_sampled_dispatch(self, params, tokens,
                                        n_steps: int, active, key_data,
                                        base_steps, temps, top_ps,
                                        sampled_mask, steps_left,
                                        stop_tokens):
        self._seam(f"wsamplep[{n_steps}]")
        return super()._device_window_sampled_dispatch(
            params, tokens, n_steps, active, key_data, base_steps,
            temps, top_ps, sampled_mask, steps_left, stop_tokens,
        )

    def harvest_window(self, handle):
        self._seam("wharvest")
        return super().harvest_window(handle)

    # Windowed-spec seams (SERVING.md rung 20): like the overlapped
    # pair, dispatch and harvest are separate failure boundaries — a
    # spec-window dispatch can die with an earlier spec window still in
    # flight, and a harvest can die on a healthy dispatch. The drained
    # poison path must settle (or cleanly abandon) the worst-case
    # _spec_unharvested reservation either way.
    def _device_spec_window(self, params, tokens, n_passes: int,
                            k_len: int, active, budgets, ctx, ctx_len,
                            sampling=None):
        self._seam(f"specw[{n_passes}]")
        return super()._device_spec_window(
            params, tokens, n_passes, k_len, active, budgets, ctx,
            ctx_len, sampling,
        )

    def _force_spec_window(self, handle):
        self._seam("specwharvest")
        return super()._force_spec_window(handle)


class FaultySliceTransport:
    """Route a ``SlicePagedKVCache``'s broadcasts through a plan.

    Instance-level patch of ``cache._bcast``: the seam fires on the
    DeadlineRunner's op thread (where the real collective would block),
    so a ``"hang"`` plan reproduces the dead-follower wedge exactly —
    the watchdog orphans the op and raises ``SliceFollowerLost``.
    """

    def __init__(self, cache, plan: FaultPlan):
        self._cache = cache
        self._orig = cache._bcast
        self.plan = plan
        cache._bcast = self._bcast

    def _bcast(self, tree):
        self.plan.at_seam("bcast")
        return self._orig(tree)

    def heal(self) -> None:
        """Unhook: restore the cache's real transport. Use with a
        fire-once plan to model 'the follower is back'; plans with
        ``heal_at`` model the rejoin inside the plan itself and don't
        need this."""
        self._cache._bcast = self._orig


def prefix_file_intact(path: str) -> bool:
    """True iff ``path`` is absent or a complete, parseable prefix-cache
    dump — the never-torn invariant (dump writes tmp + os.replace, so a
    kill mid-write may strand a ``.tmp`` but never a torn ``path``)."""
    if not os.path.exists(path):
        return True
    try:
        with np.load(path) as data:
            json.loads(bytes(data["doc"]).decode())
            _ = data["pool_k"].shape, data["pool_v"].shape
    except Exception:
        return False
    return True


@dataclasses.dataclass
class _Submission:
    prompt: list[int]
    n_new: int
    streaming: bool
    tokens: list[int] | None = None
    error: Exception | None = None
    finished: threading.Event = dataclasses.field(
        default_factory=threading.Event
    )


@dataclasses.dataclass
class ServingFaultResult:
    requests: int
    completed: int
    failed: int
    kind: str
    fired_on: str | None
    degraded: str | None
    close_s: float
    trace: list[str]


class ServingFaultSchedule:
    """Drive seeded concurrent traffic into a server wearing a
    :class:`FaultPlan`, then enforce the recovery invariants.

    ``run()`` submits ``n_requests`` (prompts drawn from the seed, a
    seeded mix of blocking and streaming consumers), joins every
    waiter with a hard bound, closes the server, and checks:
    termination, typed errors only, no over-emission, lock health,
    bounded close, decode thread gone. Raises
    :class:`~kvedge_tpu.testing.faults.InvariantViolation` carrying the
    full seam trace on any breach.
    """

    # Errors a request is ALLOWED to terminate with. InjectedFault is
    # legal only on the submit path (a prefill seam raises into the
    # submitting thread before classification); the decode loop always
    # classifies, so waiters see ServingFailure subtypes.
    _TYPED = (ServingFailure,)

    def __init__(self, server, plan: FaultPlan, *, seed: int,
                 join_timeout_s: float = 60.0):
        from kvedge_tpu.models.serving import (
            RequestCancelled,
            ServerBusy,
            ServerClosed,
        )

        self.server = server
        self.plan = plan
        self.rng = random.Random(seed)
        self.join_timeout_s = join_timeout_s
        self.trace = plan.trace
        self._allowed = self._TYPED + (
            ServerBusy, ServerClosed, RequestCancelled, InjectedFault,
        )

    # ---- schedule -------------------------------------------------------

    def run(self, n_requests: int = 3, n_new: int = 6, *,
            vocab: int = 64,
            prompt_len: tuple[int, int] = (2, 8)) -> ServingFaultResult:
        subs = [
            _Submission(
                prompt=[self.rng.randrange(1, vocab)
                        for _ in range(self.rng.randrange(*prompt_len))],
                n_new=n_new,
                streaming=self.rng.random() < 0.5,
            )
            for _ in range(n_requests)
        ]
        threads = []
        for i, sub in enumerate(subs):
            t = threading.Thread(
                target=self._drive, args=(sub,),
                name=f"fault-submit-{i}", daemon=True,
            )
            threads.append(t)
            self.trace.append(
                f"[submit {i}] len={len(sub.prompt)} n_new={sub.n_new} "
                f"{'stream' if sub.streaming else 'block'}"
            )
            t.start()

        for i, sub in enumerate(subs):
            if not sub.finished.wait(timeout=self.join_timeout_s):
                self.plan.close()  # free any parked seam before raising
                self._fail(
                    f"request {i} never terminated within "
                    f"{self.join_timeout_s:g}s — wedged waiter"
                )
        self._check_outcomes(subs)
        self._check_lock("after join")

        start = time.monotonic()
        self.server.close()
        close_s = time.monotonic() - start
        self.plan.close()
        if close_s > self.join_timeout_s:
            self._fail(f"close() took {close_s:.1f}s — unbounded teardown")
        if self.server._thread.is_alive():
            self.server._thread.join(timeout=10)
            if self.server._thread.is_alive():
                self._fail("decode thread still alive after close()")
        self._check_lock("after close")
        for t in threads:
            t.join(timeout=5)

        completed = sum(1 for s in subs if s.error is None)
        self.trace.append(
            f"[done] completed={completed} "
            f"failed={n_requests - completed} close={close_s:.2f}s"
        )
        return ServingFaultResult(
            requests=n_requests, completed=completed,
            failed=n_requests - completed, kind=self.plan.kind,
            fired_on=self.plan.fired_on, degraded=self.server.degraded,
            close_s=close_s, trace=self.trace,
        )

    def _drive(self, sub: _Submission) -> None:
        try:
            if sub.streaming:
                handle = self.server.submit_stream(
                    sub.prompt, sub.n_new, timeout=self.join_timeout_s
                )
                got = [tok for tok in handle]
                sub.tokens = sub.prompt + got
            else:
                sub.tokens = self.server.submit(
                    sub.prompt, sub.n_new, timeout=self.join_timeout_s
                )
        except Exception as e:
            sub.error = e
        finally:
            sub.finished.set()

    # ---- invariants -----------------------------------------------------

    def _fail(self, message: str) -> None:
        raise InvariantViolation(message, self.trace)

    def _check_outcomes(self, subs: list[_Submission]) -> None:
        for i, sub in enumerate(subs):
            if sub.error is not None:
                if not isinstance(sub.error, self._allowed):
                    self._fail(
                        f"request {i} died UNTYPED: "
                        f"{type(sub.error).__name__}: {sub.error}"
                    )
                self.trace.append(
                    f"[outcome {i}] {type(sub.error).__name__}"
                )
                continue
            want = len(sub.prompt) + sub.n_new
            if sub.tokens is None or len(sub.tokens) != want:
                got = None if sub.tokens is None else len(sub.tokens)
                self._fail(
                    f"request {i} over/under-emitted: {got} tokens, "
                    f"budget {want} — double emission or truncation"
                )
            self.trace.append(f"[outcome {i}] ok ({want} tokens)")

    def _check_lock(self, context: str) -> None:
        if not self.server._lock.acquire(timeout=10):
            self._fail(f"server lock orphaned ({context})")
        self.server._lock.release()
