"""Force JAX onto a virtual n-device CPU mesh (test/demo environments).

Multi-chip TPU hardware is not available in CI; sharding behavior is
exercised on virtual CPU devices instead. The ordering here is
load-bearing: some environments preload jax via a sitecustomize hook with
JAX_PLATFORMS pointed at real hardware, so setting env vars alone is too
late — the override must also go through ``jax.config`` before any backend
is initialized. Used by ``tests/conftest.py`` and ``tools/demo_cluster.py``.
"""

from __future__ import annotations

import os


def force_virtual_cpu_devices(n: int = 8) -> None:
    """Point JAX at ``n`` virtual CPU devices; call before any computation."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()

    import jax

    jax.config.update("jax_platforms", "cpu")
