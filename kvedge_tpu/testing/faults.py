"""Randomized fault injection for the fake cluster.

The reference has **no fault-injection tooling** (SURVEY.md §5); its
resilience story is verified by one manual end-to-end run. This harness is
the missing piece the build adds: a seeded random walk of node
kills/revivals driven against the rendered manifests, with the cluster's
resilience invariants checked after every event. Deterministic per seed —
a failing schedule replays exactly from its seed + trace.

Invariants enforced after every converge (derived from the reference's own
documented guarantees and failure modes):

* **Single-writer**: a single-replica Recreate deployment never has two
  Running pods (the property ``strategy: Recreate`` exists to provide —
  two concurrent writers would corrupt the state volume).
* **Node-bound storage honesty** (reference ``README.md:89``): once a PVC
  binds, a pod only ever runs on the bound node; when that node is dead the
  replacement stays Pending *with a stated reason* — degraded must be
  explained, not silent.
* **Resilient storage liveness** (reference ``README.md:88``): with
  detachable storage, whenever any schedulable node is alive the runtime
  converges back to Running.
* **State monotonicity**: each real boot of a pod generation increments the
  persisted heartbeat ``boot_count`` by exactly one and never loses
  heartbeat sequence — state survival is observed, not assumed.
"""

from __future__ import annotations

import dataclasses
import json
import os
import random

from kvedge_tpu.testing.fakecluster import FakeCluster


class InvariantViolation(AssertionError):
    """An invariant failed; ``trace`` replays the schedule that broke it."""

    def __init__(self, message: str, trace: list[str]):
        super().__init__(
            message + "\nschedule trace:\n  " + "\n  ".join(trace)
        )
        self.trace = trace


@dataclasses.dataclass
class FaultScheduleResult:
    events: int
    kills: int
    revivals: int
    boots: int
    reschedules: int
    trace: list[str]


class FaultSchedule:
    """A seeded random walk of node failures against one deployment.

    ``boot_root`` enables real-entrypoint boots: whenever a converge leaves
    a *new* pod generation Running, the pod is actually booted against a
    fresh scratch filesystem (PVC backing persists inside the cluster's
    ``state_root``) and the persisted heartbeat is checked.
    """

    def __init__(self, cluster: FakeCluster, deployment: str, *,
                 seed: int, boot_root: str | None = None):
        self.cluster = cluster
        self.deployment = deployment
        self.rng = random.Random(seed)
        self.boot_root = boot_root
        self.trace: list[str] = []
        self.kills = 0
        self.revivals = 0
        self.boots = 0
        self.reschedules = 0
        self._booted_pods: set[str] = set()
        self._expected_boot_count = 0
        self._last_seq = 0
        self._last_running: str | None = None

    # ---- schedule -------------------------------------------------------

    def run(self, n_events: int) -> FaultScheduleResult:
        self.cluster.converge()
        self._check_invariants("initial converge")
        self._maybe_boot()
        for i in range(n_events):
            self._one_event(i)
        # End on a healed cluster so terminal liveness is always exercised.
        for node in list(self.cluster.nodes):
            if not self.cluster.nodes[node].alive:
                self._revive(node)
        self.cluster.converge()
        self._check_invariants("final heal")
        self._maybe_boot()
        return FaultScheduleResult(
            events=n_events, kills=self.kills, revivals=self.revivals,
            boots=self.boots, reschedules=self.reschedules, trace=self.trace,
        )

    def _one_event(self, i: int) -> None:
        alive = [n for n, node in self.cluster.nodes.items() if node.alive]
        dead = [n for n, node in self.cluster.nodes.items() if not node.alive]
        # Kill with p=0.5 when possible, else revive; always converge+check.
        if alive and (not dead or self.rng.random() < 0.5):
            victim = self.rng.choice(alive)
            self.cluster.kill_node(victim)
            self.kills += 1
            self.trace.append(f"[{i}] kill {victim}")
        elif dead:
            self._revive(self.rng.choice(dead), index=i)
        self.cluster.converge()
        self._check_invariants(self.trace[-1])
        self._maybe_boot()

    def _revive(self, node: str, index: int | None = None) -> None:
        self.cluster.revive_node(node)
        self.revivals += 1
        prefix = f"[{index}] " if index is not None else "[heal] "
        self.trace.append(f"{prefix}revive {node}")

    # ---- invariants -----------------------------------------------------

    def _fail(self, message: str, context: str) -> None:
        raise InvariantViolation(f"{message} (after {context})", self.trace)

    def _check_invariants(self, context: str) -> None:
        cluster, dep = self.cluster, self.deployment
        running = [
            p for p in cluster.pods.values()
            if p.owner == dep and p.phase == "Running"
        ]
        if len(running) > 1:
            self._fail(
                f"single-writer violated: {len(running)} Running pods "
                f"({[p.name for p in running]})", context,
            )

        for pod in running:
            if not cluster.nodes[pod.node].alive:
                self._fail(
                    f"pod {pod.name} Running on dead node {pod.node}", context
                )
            for pvc in cluster._pod_pvcs(pod):
                if (pvc.bound_node != pod.node
                        and not cluster.resilient_storage):
                    self._fail(
                        f"pod {pod.name} on {pod.node} but node-bound PVC "
                        f"{pvc.name} is bound to {pvc.bound_node}", context,
                    )

        for pod in cluster.pending_pods(dep):
            if not pod.reason:
                self._fail(
                    f"pod {pod.name} Pending without a stated reason", context
                )

        # Liveness: under resilient storage, any alive selector-matching
        # node must be enough to get back to Running.
        if cluster.resilient_storage and not running:
            alive = [n for n in cluster.nodes.values() if n.alive]
            schedulable = [
                n for n in alive
                if any(
                    self.cluster._schedulable_node(p)[0] == n.name
                    for p in cluster.pending_pods(dep)
                )
            ]
            if schedulable:
                self._fail(
                    "liveness violated: schedulable node(s) "
                    f"{[n.name for n in schedulable]} alive but no Running "
                    "pod after converge", context,
                )

        if running:
            pod = running[0]
            if pod.name != self._last_running:
                if self._last_running is not None:
                    self.reschedules += 1
                self._last_running = pod.name

    # ---- real boots -----------------------------------------------------

    def _maybe_boot(self) -> None:
        if self.boot_root is None:
            return
        pod = self.cluster.running_pod(self.deployment)
        if pod is None or pod.name in self._booted_pods:
            return
        scratch = os.path.join(self.boot_root, f"podfs-{pod.name}")
        rc = self.cluster.boot_pod(pod, scratch)
        if rc != 0:
            self._fail(f"entrypoint boot of {pod.name} exited {rc}",
                       f"boot {pod.name}")
        self._booted_pods.add(pod.name)
        self.boots += 1
        self._expected_boot_count += 1
        self.trace.append(f"[boot] {pod.name}")
        beat = self._read_heartbeat()
        if beat.get("boot_count") != self._expected_boot_count:
            self._fail(
                f"boot_count {beat.get('boot_count')} != expected "
                f"{self._expected_boot_count} — state loss or double-count",
                f"boot {pod.name}",
            )
        seq = beat.get("seq", 0)
        if seq <= self._last_seq:
            self._fail(
                f"heartbeat seq went backwards ({self._last_seq} -> {seq})",
                f"boot {pod.name}",
            )
        self._last_seq = seq

    def _read_heartbeat(self) -> dict:
        pod = self.cluster.running_pod(self.deployment)
        path = self.cluster.pod_state_path(pod, "heartbeat.json")
        try:
            with open(path, "r", encoding="utf-8") as fh:
                return json.load(fh)
        except FileNotFoundError:
            self._fail(f"no persisted heartbeat at {path}", f"boot {pod.name}")
