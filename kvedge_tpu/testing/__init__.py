"""Test harnesses: the fake-cluster layer.

The reference has no tests at all (SURVEY.md §4) — its resilience story
(node dies -> KubeVirt reschedules the VM -> PVC re-attaches, preserving
state, ``README.md:88-89``) was only ever demonstrated manually. kvedge-tpu
adds the missing verification layer: a deterministic in-process simulation
of the Kubernetes controllers the chart depends on, able to run the *real*
container entrypoint against per-PVC backing directories so rescheduling
tests observe genuine state survival, not a mock of it.
"""

from kvedge_tpu.testing.fakecluster import FakeCluster, FakeNode
from kvedge_tpu.testing.faults import (
    FaultSchedule,
    FaultScheduleResult,
    InvariantViolation,
)
from kvedge_tpu.testing.servingfaults import (
    FaultPlan,
    FaultyCache,
    FaultySliceTransport,
    InjectedFault,
    ServingFaultResult,
    ServingFaultSchedule,
    prefix_file_intact,
)

__all__ = [
    "FakeCluster",
    "FakeNode",
    "FaultPlan",
    "FaultSchedule",
    "FaultScheduleResult",
    "FaultyCache",
    "FaultySliceTransport",
    "InjectedFault",
    "InvariantViolation",
    "ServingFaultResult",
    "ServingFaultSchedule",
    "prefix_file_intact",
]
