"""Chart and application version, mirroring the reference's identity surface.

Reference: ``deployment/helm/Chart.yaml:19,23`` pins ``version: 0.1.0`` and
``appVersion: 0.1.0``; both are surfaced here for the renderer and the chart.
"""

__version__ = "0.1.0"

CHART_NAME = "kvedge-tpu"
CHART_VERSION = __version__
APP_VERSION = __version__
CHART_DESCRIPTION = (
    "A Helm chart for deploying a resilient JAX TPU runtime on K8s as a "
    "PVC-backed single-replica Deployment."
)
CHART_KEYWORDS = ("jax", "tpu", "kvedge")
