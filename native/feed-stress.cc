// feed-stress — sanitizer harness for the native feeder.
//
// SURVEY.md §5 records the reference as having no race detection or
// sanitizers ("no compiled code exists to sanitize"). kvedge-tpu *does*
// ship compiled code — the feeder's prefetch thread and ring buffer are
// exactly the kind of concurrency TSAN exists for — so this driver
// exercises the library's full lifecycle under stress and is built with
// -fsanitize=thread / address by the Makefile's `tsan` / `asan` targets
// (run from tests/test_native_sanitizers.py):
//
//   * open -> many kvf_next iterations (consumer races the prefetch
//     thread on the ring buffer) -> close (teardown races shutdown);
//   * a mid-stream close while the producer is blocked on a full ring
//     (the can_produce wakeup path);
//   * error-path opens (no such file, bad magic) for leak coverage.
//
// Usage: feed-stress <corpus-path> [iterations]
// Exits non-zero on any contract violation; the sanitizer runtime exits
// non-zero on any detected race/leak, which the pytest wrapper asserts.

#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#include "kvedge-feed.h"

int main(int argc, char **argv) {
  if (argc < 2) {
    fprintf(stderr, "usage: feed-stress <corpus> [iterations]\n");
    return 64;
  }
  const char *corpus = argv[1];
  int iterations = argc > 2 ? atoi(argv[2]) : 200;

  // Error paths first (leak coverage).
  if (kvf_open("/no/such/corpus.kvfeed", 2, 8, 2, 0) != nullptr) {
    fprintf(stderr, "open of missing file unexpectedly succeeded\n");
    return 1;
  }
  if (kvf_open(corpus, 0, 8, 2, 0) != nullptr) {
    fprintf(stderr, "open with batch=0 unexpectedly succeeded\n");
    return 1;
  }
  // Bad magic: the early exit where an fd AND a live mmap exist at the
  // failure return — the most leak-prone path.
  {
    std::string bad_path = std::string(corpus) + ".badmagic";
    FILE *bad = fopen(bad_path.c_str(), "wb");
    if (!bad) {
      fprintf(stderr, "cannot create bad-magic fixture\n");
      return 1;
    }
    const char payload[32] = "NOTAFEEDxxxxxxxxxxxxxxxxxxx";
    fwrite(payload, 1, sizeof payload, bad);
    fclose(bad);
    if (kvf_open(bad_path.c_str(), 2, 8, 2, 0) != nullptr) {
      fprintf(stderr, "open with bad magic unexpectedly succeeded\n");
      return 1;
    }
    remove(bad_path.c_str());
  }

  // Sustained consumption: consumer races the prefetch thread.
  const int batch = 4, seq = 16;
  void *h = kvf_open(corpus, batch, seq, 3, 0);
  if (!h) {
    fprintf(stderr, "open failed: %s\n", kvf_last_error());
    return 1;
  }
  std::vector<int32_t> out(batch * (seq + 1));
  long long checksum = 0;
  for (int i = 0; i < iterations; ++i) {
    if (kvf_next(h, out.data()) != 0) {
      fprintf(stderr, "kvf_next failed at iteration %d\n", i);
      kvf_close(h);
      return 1;
    }
    checksum += out[0] + out[out.size() - 1];
  }
  kvf_close(h);

  // Sharded parity under the sanitizers: two half-batch shards raced
  // against their own prefetch threads must reproduce the global batch
  // row-for-row.
  {
    void *g = kvf_open(corpus, batch, seq, 2, 0);
    void *lo = kvf_open_sharded(corpus, batch / 2, seq, 2, 0, batch, 0);
    void *hi = kvf_open_sharded(corpus, batch / 2, seq, 2, 0, batch,
                                batch / 2);
    if (!g || !lo || !hi) {
      fprintf(stderr, "sharded open failed: %s\n", kvf_last_error());
      return 1;
    }
    std::vector<int32_t> whole(batch * (seq + 1));
    std::vector<int32_t> half(batch / 2 * (seq + 1));
    for (int i = 0; i < 8; ++i) {
      if (kvf_next(g, whole.data()) != 0 || kvf_next(lo, half.data()) != 0) {
        fprintf(stderr, "sharded next failed\n");
        return 1;
      }
      if (memcmp(whole.data(), half.data(),
                 half.size() * sizeof(int32_t)) != 0) {
        fprintf(stderr, "low shard diverged from global batch at %d\n", i);
        return 1;
      }
      if (kvf_next(hi, half.data()) != 0 ||
          memcmp(whole.data() + half.size(), half.data(),
                 half.size() * sizeof(int32_t)) != 0) {
        fprintf(stderr, "high shard diverged from global batch at %d\n", i);
        return 1;
      }
    }
    // Shard bounds are validated at open.
    if (kvf_open_sharded(corpus, batch, seq, 2, 0, batch, 1) != nullptr) {
      fprintf(stderr, "out-of-range shard unexpectedly opened\n");
      return 1;
    }
    kvf_close(g);
    kvf_close(lo);
    kvf_close(hi);
  }

  // Close while the producer is blocked on a full ring (depth 1): one
  // consumed batch proves the thread is producing; it then refills the
  // single slot and *blocks* in can_produce.wait — the sleep gives it
  // time to get there deterministically — and close must wake it via
  // the stop flag, not deadlock.
  h = kvf_open(corpus, batch, seq, 1, 0);
  if (!h) {
    fprintf(stderr, "reopen failed: %s\n", kvf_last_error());
    return 1;
  }
  if (kvf_next(h, out.data()) != 0) {
    fprintf(stderr, "kvf_next after reopen failed\n");
    kvf_close(h);
    return 1;
  }
  std::this_thread::sleep_for(std::chrono::milliseconds(100));
  kvf_close(h);

  printf("feed-stress ok (checksum %lld)\n", checksum);
  return 0;
}
