// C ABI of libkvedge-feed — shared by the library (kvedge-feed.cc), the
// sanitizer stress harness (feed-stress.cc), and documented for the
// ctypes consumer (kvedge_tpu/data/feeder.py). One declaration site so a
// signature change is a compile error in every native TU, not silent UB
// through an unmangled extern "C" symbol.

#ifndef KVEDGE_FEED_H_
#define KVEDGE_FEED_H_

#include <cstdint>

extern "C" {

// Opens a KVFEED01 corpus and starts the prefetch thread. Returns an
// opaque handle, or NULL with kvf_last_error() set.
void *kvf_open(const char *path, int batch, int seq, int depth,
               unsigned long long start_batch);

// Multi-host form: each logical batch has `global_batch` rows, of which
// this feeder produces the `batch` rows starting at row `shard_offset`
// (host p of P passes batch = global/P, shard_offset = p * global/P).
// `start_batch` stays a GLOBAL batch index, so checkpoint/resume math is
// identical on every host. kvf_open == kvf_open_sharded with
// global_batch = batch, shard_offset = 0.
void *kvf_open_sharded(const char *path, int batch, int seq, int depth,
                       unsigned long long start_batch, int global_batch,
                       int shard_offset);

// Blocking copy of the next [batch, seq+1] int32 batch. 0 = ok.
int kvf_next(void *h, int32_t *out);

// Corpus token count.
unsigned long long kvf_tokens(void *h);

// Stops the prefetch thread and releases the mapping.
void kvf_close(void *h);

// Thread-local description of the most recent kvf_open failure.
const char *kvf_last_error();

}  // extern "C"

#endif  // KVEDGE_FEED_H_
