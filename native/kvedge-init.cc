// kvedge-init — native PID-1 supervisor for the runtime container.
//
// The reference runs its payload inside a full VM where *native* system
// software owns process lifecycle: systemd supervises the IoT Edge daemon
// (installed by cloud-init, reference _helper.tpl:68-74) and restarts it on
// failure, while KubeVirt's `running: true` (aziot-edge-vm.yaml:9) restarts
// the whole VM. The pod-world analogue keeps both levels: kvedge-init is
// the in-container systemd analogue (supervise + restart-on-failure with
// backoff, reap orphans, forward termination), and the Deployment's pod
// restart is the KubeVirt analogue (kvedge-init exits non-zero when it
// gives up, so Kubernetes recreates the pod).
//
// Why native and not Python: PID 1 in a container inherits kernel-level
// duties — reaping re-parented orphans (the entrypoint starts sshd, whose
// session children orphan grandchildren) and receiving SIGTERM with no
// default handler installed. A supervisor must also stay alive and
// responsive while the Python runtime is wedged in a C extension or being
// OOM-killed, which is exactly when an in-process Python supervisor dies
// with its payload.
//
// Usage:
//   kvedge-init [--max-restarts N] [--backoff-ms MS] [--backoff-max-ms MS]
//               [--grace-ms MS] [--events FILE] -- prog [args...]
//
// Behavior contract (tests/test_kvedge_init.py):
//   * child runs in its own process group; SIGTERM/SIGINT to kvedge-init
//     are forwarded to the group, then escalated to SIGKILL after
//     --grace-ms (the terminationGracePeriod handshake);
//   * exit 0 from the child ends supervision with exit 0 (run-to-
//     completion payloads); non-zero restarts it up to --max-restarts
//     times with exponential backoff, then exits with the child's code
//     (128+signal for signal deaths) so the pod restart takes over;
//   * any process re-parented to kvedge-init is reaped promptly
//     (PR_SET_CHILD_SUBREAPER makes this testable without being PID 1);
//   * every lifecycle event is appended to --events as one JSON line —
//     the status server surfaces this file, the pod-level analogue of
//     `systemctl status` inside the reference VM.

#include <cerrno>
#include <cinttypes>
#include <cstdarg>
#include <csignal>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <string>
#include <vector>

#include <fcntl.h>
#include <sys/prctl.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>

namespace {

struct Options {
  long max_restarts = 5;
  long backoff_ms = 500;
  long backoff_max_ms = 30000;
  long grace_ms = 10000;
  std::string events_path;
  std::vector<char *> child_argv;  // null-terminated for execvp
};

double now_unix() {
  struct timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  return static_cast<double>(ts.tv_sec) + static_cast<double>(ts.tv_nsec) / 1e9;
}

double now_mono_ms() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<double>(ts.tv_sec) * 1e3 +
         static_cast<double>(ts.tv_nsec) / 1e6;
}

// Append one JSON event line; best-effort (supervision must not fail
// because the events file is unwritable).
void emit_event(const Options &opts, const char *event, const char *fmt = "",
                ...) {
  char extra[256] = "";
  if (fmt[0] != '\0') {
    va_list ap;
    va_start(ap, fmt);
    vsnprintf(extra, sizeof extra, fmt, ap);
    va_end(ap);
  }
  char line[512];
  snprintf(line, sizeof line, "{\"ts\": %.3f, \"event\": \"%s\"%s%s}\n",
           now_unix(), event, extra[0] ? ", " : "", extra);
  fprintf(stderr, "[kvedge-init] %s", line);
  if (opts.events_path.empty()) return;
  int fd = open(opts.events_path.c_str(), O_WRONLY | O_CREAT | O_APPEND, 0644);
  if (fd < 0) return;
  ssize_t unused = write(fd, line, strlen(line));
  (void)unused;
  close(fd);
}

[[noreturn]] void usage_error(const char *msg) {
  fprintf(stderr,
          "kvedge-init: %s\n"
          "usage: kvedge-init [--max-restarts N] [--backoff-ms MS] "
          "[--backoff-max-ms MS] [--grace-ms MS] [--events FILE] -- prog "
          "[args...]\n",
          msg);
  exit(64);  // EX_USAGE
}

long parse_long(const char *flag, const char *value) {
  char *end = nullptr;
  errno = 0;
  long parsed = strtol(value, &end, 10);
  if (errno != 0 || end == value || *end != '\0' || parsed < 0)
    usage_error((std::string("bad value for ") + flag).c_str());
  return parsed;
}

Options parse_args(int argc, char **argv) {
  Options opts;
  int i = 1;
  for (; i < argc; ++i) {
    std::string arg = argv[i];
    if (arg == "--") {
      ++i;
      break;
    }
    if (i + 1 >= argc) usage_error(("missing value for " + arg).c_str());
    if (arg == "--max-restarts")
      opts.max_restarts = parse_long("--max-restarts", argv[++i]);
    else if (arg == "--backoff-ms")
      opts.backoff_ms = parse_long("--backoff-ms", argv[++i]);
    else if (arg == "--backoff-max-ms")
      opts.backoff_max_ms = parse_long("--backoff-max-ms", argv[++i]);
    else if (arg == "--grace-ms")
      opts.grace_ms = parse_long("--grace-ms", argv[++i]);
    else if (arg == "--events")
      opts.events_path = argv[++i];
    else
      usage_error(("unknown flag " + arg).c_str());
  }
  for (; i < argc; ++i) opts.child_argv.push_back(argv[i]);
  if (opts.child_argv.empty()) usage_error("no child command after --");
  opts.child_argv.push_back(nullptr);
  return opts;
}

pid_t spawn_child(const Options &opts, const sigset_t &orig_mask) {
  pid_t pid = fork();
  if (pid < 0) {
    emit_event(opts, "fork-failed", "\"errno\": %d", errno);
    return -1;
  }
  if (pid == 0) {
    // Child: own process group (so the supervisor can signal the whole
    // payload tree), original signal mask restored before exec.
    setpgid(0, 0);
    sigprocmask(SIG_SETMASK, &orig_mask, nullptr);
    execvp(opts.child_argv[0], opts.child_argv.data());
    fprintf(stderr, "[kvedge-init] exec %s failed: %s\n", opts.child_argv[0],
            strerror(errno));
    _exit(127);
  }
  // Also set the pgid from the parent side: whichever of the two races
  // ahead, the group exists before we ever kill(-pid).
  setpgid(pid, pid);
  return pid;
}

int exit_code_of(int wstatus) {
  if (WIFEXITED(wstatus)) return WEXITSTATUS(wstatus);
  if (WIFSIGNALED(wstatus)) return 128 + WTERMSIG(wstatus);
  return 1;
}

}  // namespace

int main(int argc, char **argv) {
  Options opts = parse_args(argc, argv);

  // Orphans re-parent to us even when we are not PID 1 (tests, or a
  // container runtime that injects its own init above us).
  prctl(PR_SET_CHILD_SUBREAPER, 1);

  // Signal handling via sigtimedwait: block everything we manage and
  // consume signals synchronously in the supervision loop — no handlers,
  // no self-pipe, no async-signal-safety concerns.
  sigset_t managed, orig_mask;
  sigemptyset(&managed);
  sigaddset(&managed, SIGTERM);
  sigaddset(&managed, SIGINT);
  sigaddset(&managed, SIGCHLD);
  sigprocmask(SIG_BLOCK, &managed, &orig_mask);

  long restarts_used = 0;
  bool terminating = false;
  double kill_deadline_ms = 0;     // escalation deadline while terminating
  double restart_at_ms = 0;        // backoff deadline while child is down
  long backoff_ms = opts.backoff_ms;
  int last_status = 0;
  pid_t dead_child_pgid = -1;      // failed attempt's group, killed pre-respawn

  emit_event(opts, "supervisor-start", "\"pid\": %d, \"child\": \"%s\"",
             getpid(), opts.child_argv[0]);
  pid_t child = spawn_child(opts, orig_mask);
  if (child < 0) return 1;
  emit_event(opts, "child-start", "\"pid\": %d, \"attempt\": %ld", child,
             restarts_used);

  while (true) {
    // Pick the nearest deadline (kill escalation or restart backoff).
    struct timespec timeout;
    struct timespec *timeout_ptr = nullptr;
    double now = now_mono_ms();
    double deadline = 0;
    if (terminating && child > 0 && kill_deadline_ms > 0)
      deadline = kill_deadline_ms;
    else if (child < 0 && restart_at_ms > 0)
      deadline = restart_at_ms;
    if (deadline > 0) {
      double wait_ms = deadline - now;
      if (wait_ms < 0) wait_ms = 0;
      timeout.tv_sec = static_cast<time_t>(wait_ms / 1000);
      timeout.tv_nsec =
          static_cast<long>((wait_ms - timeout.tv_sec * 1000) * 1e6);
      timeout_ptr = &timeout;
    }

    siginfo_t info;
    int sig = sigtimedwait(&managed, &info, timeout_ptr);
    if (sig < 0 && errno == EINTR) continue;

    if (sig == SIGTERM || sig == SIGINT) {
      terminating = true;
      if (child > 0) {
        emit_event(opts, "forward-signal", "\"signal\": %d, \"pid\": %d", sig,
                   child);
        kill(-child, sig);
        kill_deadline_ms = now_mono_ms() + static_cast<double>(opts.grace_ms);
      } else {
        // No child to wind down (we were in backoff): exit as if the
        // child had been killed by this signal.
        emit_event(opts, "terminated-in-backoff", "\"signal\": %d", sig);
        return 128 + sig;
      }
    } else if (sig == SIGCHLD || sig < 0 /* timeout */) {
      // Reap everything that is ready: our child and any re-parented
      // orphans (subreaper duty).
      while (true) {
        int wstatus = 0;
        pid_t reaped = waitpid(-1, &wstatus, WNOHANG);
        if (reaped <= 0) break;
        if (reaped != child) continue;  // orphan: reaped, nothing else to do
        child = -1;
        dead_child_pgid = reaped;
        last_status = wstatus;
        emit_event(opts, "child-exit", "\"code\": %d", exit_code_of(wstatus));
        if (terminating) return exit_code_of(wstatus);
        if (WIFEXITED(wstatus) && WEXITSTATUS(wstatus) == 0) {
          emit_event(opts, "supervisor-exit", "\"code\": 0");
          return 0;  // run-to-completion payload finished
        }
        if (restarts_used >= opts.max_restarts) {
          emit_event(opts, "give-up", "\"restarts\": %ld, \"code\": %d",
                     restarts_used, exit_code_of(wstatus));
          return exit_code_of(wstatus);
        }
        restart_at_ms = now_mono_ms() + static_cast<double>(backoff_ms);
        emit_event(opts, "restart-scheduled",
                   "\"backoff_ms\": %ld, \"attempt\": %ld", backoff_ms,
                   restarts_used + 1);
        backoff_ms = backoff_ms * 2;
        if (backoff_ms > opts.backoff_max_ms) backoff_ms = opts.backoff_max_ms;
      }

      // Deadlines that may have fired with the timeout.
      now = now_mono_ms();
      if (terminating && child > 0 && kill_deadline_ms > 0 &&
          now >= kill_deadline_ms) {
        emit_event(opts, "escalate-sigkill", "\"pid\": %d", child);
        kill(-child, SIGKILL);
        kill_deadline_ms = 0;  // waitpid via SIGCHLD will finish up
      }
      if (!terminating && child < 0 && restart_at_ms > 0 &&
          now >= restart_at_ms) {
        restart_at_ms = 0;
        ++restarts_used;
        // Sweep the failed attempt's process group before respawning:
        // survivors (a wedged runtime still holding the TPU device, a
        // Popen'd sshd on port 22) would otherwise make every restart
        // fail on a conflict the supervisor itself preserved. This is
        // the cgroup-kill systemd performs before a service restart.
        if (dead_child_pgid > 0) {
          if (kill(-dead_child_pgid, SIGKILL) == 0)
            emit_event(opts, "sweep-stale-group", "\"pgid\": %d",
                       dead_child_pgid);
          dead_child_pgid = -1;
        }
        child = spawn_child(opts, orig_mask);
        if (child < 0) return 1;
        emit_event(opts, "child-start", "\"pid\": %d, \"attempt\": %ld", child,
                   restarts_used);
      }
      if (terminating && child < 0) return exit_code_of(last_status);
    }
  }
}
