// libkvedge-feed — native training-input feeder for the runtime.
//
// The training payload consumes fixed-shape [batch, seq+1] int32 token
// batches (models/training.py). This library streams them from a binary
// corpus file on the state volume with a *prefetch thread* and a bounded
// ring buffer, so host-side IO and slicing overlap the device's step time
// instead of serializing with it — the input-pipeline half of keeping the
// MXU busy. Native C++ because the feeder must keep producing while the
// Python thread is blocked inside a jit'd step (the GIL is released there,
// but a Python feeder thread would contend for it on every batch; this
// thread never touches Python at all).
//
// Corpus format (written by kvedge_tpu.data.write_corpus):
//   8 bytes  magic   "KVFEED01"
//   8 bytes  uint64  n_tokens (little-endian)
//   N * 4    int32   tokens
//
// Batch layout: deterministic sequential order. Batch b row r covers
// tokens [(b*batch + r) * seq, ... + seq + 1) — overlapping by one token
// so targets = inputs shifted by one — wrapping around the corpus at the
// end (an "epoch" is implicit). Deterministic order makes resume exact:
// a consumer that restarts at step k sees the same batches (the
// checkpoint/resume contract of models/training.py).
//
// C ABI (consumed via ctypes from kvedge_tpu/data/feeder.py):
//   void* kvf_open(const char* path, int batch, int seq, int depth,
//                  unsigned long long start_batch);
//   int   kvf_next(void* h, int* out);        // blocking; 0 = ok
//   const char* kvf_last_error();             // after a NULL open
//   unsigned long long kvf_tokens(void* h);   // corpus token count
//   void  kvf_close(void* h);

#include "kvedge-feed.h"

#include <atomic>
#include <memory>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr char kMagic[8] = {'K', 'V', 'F', 'E', 'E', 'D', '0', '1'};
constexpr size_t kHeaderBytes = 16;

thread_local std::string g_last_error;

struct Feeder {
  int fd = -1;
  const int32_t *tokens = nullptr;  // mmap'd, past the header
  uint64_t n_tokens = 0;
  size_t map_bytes = 0;
  void *map_base = nullptr;

  int batch = 0;       // rows THIS feeder produces per batch
  int seq = 0;
  int global_batch = 0;  // rows per logical batch across all hosts
  int shard_offset = 0;  // first global row this feeder covers
  size_t batch_elems = 0;  // batch * (seq + 1)

  // Bounded ring buffer of prefetched batches.
  std::vector<std::vector<int32_t>> ring;
  size_t head = 0, tail = 0, filled = 0;
  std::mutex mu;
  std::condition_variable can_produce, can_consume;
  std::atomic<bool> stop{false};
  uint64_t next_batch_index = 0;
  std::thread worker;

  ~Feeder() {
    {
      std::lock_guard<std::mutex> lock(mu);
      stop = true;
    }
    can_produce.notify_all();
    can_consume.notify_all();
    if (worker.joinable()) worker.join();
    if (map_base) munmap(map_base, map_bytes);
    if (fd >= 0) close(fd);
  }

  void fill_batch(uint64_t index, int32_t *out) const {
    // Local row r is global row (shard_offset + r) of global batch
    // `index`; that row starts at token
    // (index*global_batch + shard_offset + r) * seq, wrapping modulo the
    // corpus. Single-host (global_batch == batch, shard_offset == 0)
    // reduces to the original (index*batch + r) * seq.
    for (int r = 0; r < batch; ++r) {
      uint64_t start = (static_cast<uint64_t>(index) * global_batch +
                        shard_offset + r) *
                       seq % n_tokens;
      size_t row_len = static_cast<size_t>(seq) + 1;
      uint64_t contiguous = n_tokens - start;
      if (contiguous >= row_len) {
        memcpy(out, tokens + start, row_len * sizeof(int32_t));
      } else {
        memcpy(out, tokens + start, contiguous * sizeof(int32_t));
        memcpy(out + contiguous, tokens,
               (row_len - contiguous) * sizeof(int32_t));
      }
      out += row_len;
    }
  }

  void run() {
    std::vector<int32_t> scratch(batch_elems);
    while (true) {
      fill_batch(next_batch_index, scratch.data());
      std::unique_lock<std::mutex> lock(mu);
      can_produce.wait(lock,
                       [&] { return stop || filled < ring.size(); });
      if (stop) return;
      ring[tail].swap(scratch);
      tail = (tail + 1) % ring.size();
      ++filled;
      ++next_batch_index;
      lock.unlock();
      can_consume.notify_one();
    }
  }
};

}  // namespace

extern "C" {

const char *kvf_last_error() { return g_last_error.c_str(); }

void *kvf_open(const char *path, int batch, int seq, int depth,
               unsigned long long start_batch) {
  return kvf_open_sharded(path, batch, seq, depth, start_batch, batch, 0);
}

void *kvf_open_sharded(const char *path, int batch, int seq, int depth,
                       unsigned long long start_batch, int global_batch,
                       int shard_offset) try {
  if (batch <= 0 || seq <= 0 || depth <= 0) {
    g_last_error = "batch, seq, and depth must be positive";
    return nullptr;
  }
  if (global_batch < batch || shard_offset < 0 ||
      shard_offset + batch > global_batch) {
    g_last_error =
        "shard must satisfy 0 <= shard_offset and "
        "shard_offset + batch <= global_batch";
    return nullptr;
  }
  auto owned = std::make_unique<Feeder>();
  Feeder *feeder = owned.get();
  feeder->fd = open(path, O_RDONLY);
  if (feeder->fd < 0) {
    g_last_error = std::string("cannot open ") + path;
    return nullptr;
  }
  struct stat st;
  if (fstat(feeder->fd, &st) != 0 ||
      static_cast<size_t>(st.st_size) < kHeaderBytes) {
    g_last_error = "corpus file too small for header";
    return nullptr;
  }
  feeder->map_bytes = st.st_size;
  feeder->map_base =
      mmap(nullptr, feeder->map_bytes, PROT_READ, MAP_PRIVATE, feeder->fd, 0);
  if (feeder->map_base == MAP_FAILED) {
    feeder->map_base = nullptr;
    g_last_error = "mmap failed";
    return nullptr;
  }
  const char *base = static_cast<const char *>(feeder->map_base);
  if (memcmp(base, kMagic, sizeof kMagic) != 0) {
    g_last_error = "bad corpus magic (expected KVFEED01)";
    return nullptr;
  }
  uint64_t n_tokens;
  memcpy(&n_tokens, base + 8, sizeof n_tokens);
  // Divide instead of multiply: n_tokens * 4 could wrap uint64 for a
  // corrupt header and bypass the bound check entirely.
  uint64_t max_tokens =
      (static_cast<uint64_t>(st.st_size) - kHeaderBytes) / sizeof(int32_t);
  if (n_tokens > max_tokens) {
    g_last_error = "corpus header claims more tokens than the file holds";
    return nullptr;
  }
  if (n_tokens < static_cast<uint64_t>(seq) + 1) {
    g_last_error = "corpus smaller than one sequence";
    return nullptr;
  }
  feeder->tokens = reinterpret_cast<const int32_t *>(base + kHeaderBytes);
  feeder->n_tokens = n_tokens;
  feeder->batch = batch;
  feeder->seq = seq;
  feeder->global_batch = global_batch;
  feeder->shard_offset = shard_offset;
  feeder->batch_elems = static_cast<size_t>(batch) * (seq + 1);
  feeder->ring.resize(depth);
  for (auto &slot : feeder->ring) slot.resize(feeder->batch_elems);
  feeder->next_batch_index = start_batch;
  feeder->worker = std::thread(&Feeder::run, feeder);
  return owned.release();
} catch (const std::exception &e) {
  // C++ exceptions must not cross the C ABI into ctypes (std::terminate
  // would abort the whole runtime process). The realistic throwers are
  // the ring/thread allocations — e.g. an absurd batch*seq from a bad
  // config ends here as std::bad_alloc, surfaced as a clean error.
  g_last_error = std::string("kvf_open failed: ") + e.what();
  return nullptr;
} catch (...) {
  g_last_error = "kvf_open failed: unknown C++ exception";
  return nullptr;
}

int kvf_next(void *h, int32_t *out) {
  auto feeder = static_cast<Feeder *>(h);
  std::unique_lock<std::mutex> lock(feeder->mu);
  feeder->can_consume.wait(
      lock, [&] { return feeder->stop.load() || feeder->filled > 0; });
  if (feeder->stop) return 1;
  memcpy(out, feeder->ring[feeder->head].data(),
         feeder->batch_elems * sizeof(int32_t));
  feeder->head = (feeder->head + 1) % feeder->ring.size();
  --feeder->filled;
  lock.unlock();
  feeder->can_produce.notify_one();
  return 0;
}

unsigned long long kvf_tokens(void *h) {
  return static_cast<Feeder *>(h)->n_tokens;
}

void kvf_close(void *h) { delete static_cast<Feeder *>(h); }

}  // extern "C"
